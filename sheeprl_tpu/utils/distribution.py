"""Probability distributions in pure jax.

Re-implements the distribution zoo of reference sheeprl/utils/distribution.py
(TruncatedNormal:116, SymlogDistribution:152, MSEDistribution:196,
TwoHotEncodingDistribution:224, OneHotCategoricalValidateArgs:281,
OneHotCategoricalStraightThrough:387, BernoulliSafeMode:409) plus the
torch.distributions primitives the algorithms rely on (Normal, Independent,
Categorical, TanhNormal for SAC).

Distributions are plain python containers over jnp arrays; they are created
inside jit-traced functions, so every method must be traceable (no python
branching on array values). Sampling takes an explicit PRNG key.
Straight-through gradients use the ``sg(x) + p - sg(p)`` identity instead of
torch's ``.rsample`` machinery.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.utils import symexp, symlog

sg = jax.lax.stop_gradient
_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


class Distribution:
    """Minimal distribution interface: log_prob / sample / rsample / mode /
    mean / entropy. ``sample`` is stop-gradient of ``rsample`` where a
    reparameterized path exists."""

    def log_prob(self, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def rsample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        raise NotImplementedError

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return sg(self.rsample(key, sample_shape))

    @property
    def mode(self) -> jax.Array:
        raise NotImplementedError

    @property
    def mean(self) -> jax.Array:
        raise NotImplementedError

    def entropy(self) -> jax.Array:
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc: jax.Array, scale: jax.Array):
        self.loc = loc
        self.scale = scale

    def log_prob(self, x: jax.Array) -> jax.Array:
        var = self.scale**2
        return -((x - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - _HALF_LOG_2PI

    def rsample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = sample_shape + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(key, shape, dtype=jnp.result_type(self.loc))
        return self.loc + eps * self.scale

    @property
    def mode(self) -> jax.Array:
        return self.loc

    @property
    def mean(self) -> jax.Array:
        return self.loc

    @property
    def stddev(self) -> jax.Array:
        return self.scale

    def entropy(self) -> jax.Array:
        return 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)


class Independent(Distribution):
    """Sums log_prob/entropy over the last ``reinterpreted_batch_ndims`` dims."""

    def __init__(self, base: Distribution, reinterpreted_batch_ndims: int = 1):
        self.base = base
        self.ndims = reinterpreted_batch_ndims

    def _reduce(self, x: jax.Array) -> jax.Array:
        if self.ndims == 0:
            return x
        return x.sum(axis=tuple(range(-self.ndims, 0)))

    def log_prob(self, x: jax.Array) -> jax.Array:
        return self._reduce(self.base.log_prob(x))

    def rsample(self, key, sample_shape=()):
        return self.base.rsample(key, sample_shape)

    def sample(self, key, sample_shape=()):
        return self.base.sample(key, sample_shape)

    @property
    def mode(self):
        return self.base.mode

    @property
    def mean(self):
        return self.base.mean

    def entropy(self) -> jax.Array:
        return self._reduce(self.base.entropy())


class TanhNormal(Distribution):
    """tanh-squashed diagonal Normal (SAC actor — reference
    sheeprl/algos/sac/agent.py:57-143 uses TanhTransform on Normal)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, eps: float = 1e-6):
        self.base = Normal(loc, scale)
        self.eps = eps

    def rsample_and_log_prob(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = self.base.rsample(key)
        y = jnp.tanh(x)
        # log|d tanh / dx| = 2*(log2 - x - softplus(-2x)) — numerically stable
        log_det = 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))
        logp = self.base.log_prob(x) - log_det
        return y, logp

    def log_prob(self, y: jax.Array) -> jax.Array:
        x = jnp.arctanh(jnp.clip(y, -1.0 + self.eps, 1.0 - self.eps))
        log_det = 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))
        return self.base.log_prob(x) - log_det

    def rsample(self, key, sample_shape=()):
        return jnp.tanh(self.base.rsample(key, sample_shape))

    @property
    def mode(self):
        return jnp.tanh(self.base.loc)

    @property
    def mean(self):
        return jnp.tanh(self.base.loc)


class TruncatedNormal(Distribution):
    """Normal truncated to [low, high] (reference utils/distribution.py:25-150,
    used by DreamerV1's action head)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, low: float = -1.0, high: float = 1.0):
        self.loc, self.scale, self.low, self.high = loc, scale, low, high
        self._a = (low - loc) / scale
        self._b = (high - loc) / scale
        sqrt2 = math.sqrt(2.0)
        self._phi_a = 0.5 * (1 + jax.scipy.special.erf(self._a / sqrt2))
        self._phi_b = 0.5 * (1 + jax.scipy.special.erf(self._b / sqrt2))
        self._z = jnp.clip(self._phi_b - self._phi_a, 1e-8, None)

    def log_prob(self, x: jax.Array) -> jax.Array:
        xi = (x - self.loc) / self.scale
        return -(xi**2) / 2 - _HALF_LOG_2PI - jnp.log(self.scale) - jnp.log(self._z)

    def rsample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = sample_shape + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        u = jax.random.uniform(key, shape, dtype=jnp.result_type(self.loc), minval=1e-6, maxval=1.0 - 1e-6)
        p = self._phi_a + u * (self._phi_b - self._phi_a)
        x = self.loc + self.scale * jnp.sqrt(2.0) * jax.scipy.special.erfinv(2 * p - 1)
        return jnp.clip(x, self.low + 1e-6, self.high - 1e-6)

    @property
    def mode(self):
        return jnp.clip(self.loc, self.low, self.high)

    @property
    def mean(self):
        # E[X] = loc + scale * (pdf(a) - pdf(b)) / Z
        pdf = lambda t: jnp.exp(-(t**2) / 2) / math.sqrt(2 * math.pi)  # noqa: E731
        return self.loc + self.scale * (pdf(self._a) - pdf(self._b)) / self._z

    def entropy(self) -> jax.Array:
        # H = log(sqrt(2*pi*e) * scale * Z) + (a*pdf(a) - b*pdf(b)) / (2Z)
        pdf = lambda t: jnp.exp(-(t**2) / 2) / math.sqrt(2 * math.pi)  # noqa: E731
        return (
            0.5 * math.log(2 * math.pi * math.e)
            + jnp.log(self.scale)
            + jnp.log(self._z)
            + (self._a * pdf(self._a) - self._b * pdf(self._b)) / (2 * self._z)
        )


class Categorical(Distribution):
    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        if logits is None:
            logits = jnp.log(jnp.clip(probs, 1e-10, None))
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self.logits, axis=-1)

    def log_prob(self, x: jax.Array) -> jax.Array:
        x = x.astype(jnp.int32)
        return jnp.take_along_axis(self.logits, x[..., None], axis=-1).squeeze(-1)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return jax.random.categorical(key, self.logits, shape=sample_shape + self.logits.shape[:-1])

    @property
    def mode(self):
        return jnp.argmax(self.logits, axis=-1)

    def entropy(self) -> jax.Array:
        p = self.probs
        return -(p * self.logits).sum(-1)


class OneHotCategorical(Distribution):
    """One-hot samples; log_prob of one-hot inputs (reference
    OneHotCategoricalValidateArgs, utils/distribution.py:281)."""

    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        self._cat = Categorical(logits=logits, probs=probs)

    @property
    def logits(self):
        return self._cat.logits

    @property
    def probs(self):
        return self._cat.probs

    def log_prob(self, x: jax.Array) -> jax.Array:
        return (self._cat.logits * x).sum(-1)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        idx = self._cat.sample(key, sample_shape)
        return jax.nn.one_hot(idx, self.logits.shape[-1], dtype=self.logits.dtype)

    @property
    def mode(self):
        return jax.nn.one_hot(self._cat.mode, self.logits.shape[-1], dtype=self.logits.dtype)

    @property
    def mean(self):
        return self.probs

    def entropy(self) -> jax.Array:
        return self._cat.entropy()


class OneHotCategoricalStraightThrough(OneHotCategorical):
    """One-hot samples with straight-through gradients to ``probs``
    (reference utils/distribution.py:387-404; Dreamer V2/V3 latents)."""

    def rsample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        hard = self.sample(key, sample_shape)
        p = self.probs
        return sg(hard) + p - sg(p)


class Bernoulli(Distribution):
    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        if logits is None:
            logits = jnp.log(jnp.clip(probs, 1e-10, None)) - jnp.log(jnp.clip(1 - probs, 1e-10, None))
        self.logits = logits

    @property
    def probs(self) -> jax.Array:
        return jax.nn.sigmoid(self.logits)

    def log_prob(self, x: jax.Array) -> jax.Array:
        # -BCEWithLogits
        return x * jax.nn.log_sigmoid(self.logits) + (1 - x) * jax.nn.log_sigmoid(-self.logits)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = sample_shape + self.logits.shape
        u = jax.random.uniform(key, shape)
        return (u < self.probs).astype(self.logits.dtype)

    @property
    def mean(self):
        return self.probs

    def entropy(self) -> jax.Array:
        p = self.probs
        return -(p * jax.nn.log_sigmoid(self.logits) + (1 - p) * jax.nn.log_sigmoid(-self.logits))


class BernoulliSafeMode(Bernoulli):
    """Bernoulli whose mode is the >0.5 indicator with no NaNs
    (reference utils/distribution.py:409-416; Dreamer continue model)."""

    @property
    def mode(self):
        return (self.probs > 0.5).astype(self.logits.dtype)


class SymlogDistribution(Distribution):
    """'Distribution' whose log_prob is -|symlog(x) - mode|^2 (MSE in symlog
    space), summed over event dims (reference utils/distribution.py:152-194)."""

    def __init__(self, mode: jax.Array, dims: int = 1, agg: str = "sum"):
        self._mode = mode
        self._dims = tuple(range(-dims, 0)) if dims else ()
        self._agg = agg

    @property
    def mode(self) -> jax.Array:
        return symexp(self._mode)

    @property
    def mean(self) -> jax.Array:
        return symexp(self._mode)

    def log_prob(self, value: jax.Array) -> jax.Array:
        distance = -((self._mode - symlog(value)) ** 2)
        if self._agg == "mean":
            return distance.mean(self._dims) if self._dims else distance
        return distance.sum(self._dims) if self._dims else distance


class MSEDistribution(Distribution):
    """-MSE log_prob in raw space (reference utils/distribution.py:196-222)."""

    def __init__(self, mode: jax.Array, dims: int = 1, agg: str = "sum"):
        self._mode = mode
        self._dims = tuple(range(-dims, 0)) if dims else ()
        self._agg = agg

    @property
    def mode(self) -> jax.Array:
        return self._mode

    @property
    def mean(self) -> jax.Array:
        return self._mode

    def log_prob(self, value: jax.Array) -> jax.Array:
        distance = -((self._mode - value) ** 2)
        if self._agg == "mean":
            return distance.mean(self._dims) if self._dims else distance
        return distance.sum(self._dims) if self._dims else distance


class TwoHotEncodingDistribution(Distribution):
    """Two-hot categorical over a symexp-spaced support in symlog space —
    DreamerV3's reward/critic head (reference utils/distribution.py:224-279;
    255 bins over [-20, 20])."""

    def __init__(self, logits: jax.Array, dims: int = 1, low: float = -20.0, high: float = 20.0):
        self._raw_logits = logits
        self._dims = tuple(range(-dims, 0))
        self.bins = jnp.linspace(low, high, logits.shape[-1])
        self.low, self.high = low, high

    # normalized logits / probs are LAZY: most call sites use only one of
    # .mean (probs) or .log_prob (logits), and each materializes a full
    # (..., num_buckets) pass — computing both eagerly doubled the head
    # read traffic of every train step
    @property
    def logits(self) -> jax.Array:
        return self._raw_logits - jax.scipy.special.logsumexp(
            self._raw_logits, -1, keepdims=True
        )

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self._raw_logits, -1)

    @property
    def mean(self) -> jax.Array:
        return symexp((self.probs * self.bins).sum(-1, keepdims=True))

    @property
    def mode(self) -> jax.Array:
        return self.mean

    def log_prob(self, x: jax.Array) -> jax.Array:
        """x: (..., 1) raw-space scalars; returns (...,) summed over event dims."""
        from sheeprl_tpu.utils.utils import two_hot_encoder

        target = two_hot_encoder(
            symlog(x), support_range=int(self.high), num_buckets=self.bins.shape[0]
        )
        return (target * self.logits).sum(-1, keepdims=True).sum(self._dims)


def kl_divergence(p: Distribution, q: Distribution) -> jax.Array:
    """KL(p || q) for the pairs the algorithms need."""
    if isinstance(p, Independent) and isinstance(q, Independent):
        base = kl_divergence(p.base, q.base)
        return base.sum(axis=tuple(range(-p.ndims, 0))) if p.ndims else base
    if isinstance(p, (OneHotCategorical, Categorical)) and isinstance(q, (OneHotCategorical, Categorical)):
        pl = p.logits if isinstance(p, Categorical) else p._cat.logits
        ql = q.logits if isinstance(q, Categorical) else q._cat.logits
        pp = jax.nn.softmax(pl, -1)
        return (pp * (pl - ql)).sum(-1)
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    raise NotImplementedError(f"KL({type(p).__name__} || {type(q).__name__})")
