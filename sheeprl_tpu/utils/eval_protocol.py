"""Multi-episode evaluation protocol: N greedy + N sampled episodes.

The reference's evaluation entrypoints (reference
sheeprl/algos/*/evaluate.py via sheeprl/algos/*/utils.py ``test``) roll a
single greedy episode and publish that one number.  Round 4 showed why
that is fragile: a solved ball_in_cup-catch run (sampled train mean 916)
greedy-evaluated to 0.0 on its single rollout and that zero headlined the
artifact.  Here every evaluation rolls ``episodes`` rollouts per mode
(greedy and sampled) with distinct per-episode seeds and reports the
per-episode lists plus summary stats, so no single rollout can headline.

The summary is printed as one machine-readable ``Eval protocol: {...}``
JSON line (parsed by ``scripts/finalize_curve.py``), followed by a final
``Test - Reward: <greedy median>`` line so older log parsers that take
the last ``Test - Reward:`` still see a robust statistic.
"""

from __future__ import annotations

import json
import os
import statistics
from typing import Any, Callable, Dict, Sequence

__all__ = ["run_eval_protocol"]


def _summary(vals: Sequence[float]) -> Dict[str, Any]:
    return {
        "mean": round(statistics.fmean(vals), 3),
        "median": round(statistics.median(vals), 3),
        "min": round(min(vals), 3),
        "max": round(max(vals), 3),
        "per_episode": [round(v, 3) for v in vals],
    }


def run_eval_protocol(
    test_fn: Callable[..., float],
    runtime,
    cfg,
    *,
    episodes: int | None = None,
    modes: Sequence[str] = ("greedy", "sampled"),
    headline_mode: str | None = None,
) -> Dict[str, Any]:
    """Roll ``episodes`` rollouts per mode and return the summary dict.

    ``test_fn(greedy=..., seed=..., test_name=...) -> float`` is one
    episode's return (each algo's ``test`` partial-applied over its
    player/cfg).  Episode i of every mode uses seed ``cfg.seed + i`` —
    distinct seeds are what make repeated greedy rollouts informative
    (same seed + deterministic policy = the same episode N times).

    ``episodes`` defaults to ``$SHEEPRL_EVAL_EPISODES``, else 1 under
    ``cfg.dry_run`` (CI), else 5.

    ``headline_mode`` picks which mode's median becomes the final
    ``Test - Reward:`` line (and the ``headline`` summary key).  Default:
    greedy when present.  DV3-family evaluates headline "sampled" — the
    reference's ``greedy=False`` mode — because a greedy DV3 rollout can
    misleadingly score ~0 on sparse tasks the sampled policy solves
    (observed round 4: a solved ball_in_cup run greedy-evaluated to 0.0).
    """
    if episodes is None:
        episodes = int(os.environ.get("SHEEPRL_EVAL_EPISODES", "0")) or (
            1 if cfg.dry_run else 5
        )
    if headline_mode is None:
        headline_mode = "greedy" if "greedy" in modes else modes[0]
    if headline_mode not in modes:
        raise ValueError(f"headline_mode '{headline_mode}' not in modes {tuple(modes)}")
    base_seed = int(cfg.seed or 0)
    out: Dict[str, Any] = {
        "episodes_per_mode": episodes,
        "seed_base": base_seed,
        "headline_mode": headline_mode,
    }
    for mode in modes:
        greedy = mode == "greedy"
        vals = [
            float(
                test_fn(
                    greedy=greedy,
                    seed=base_seed + i,
                    test_name=f"{mode}_ep{i}",
                )
            )
            for i in range(episodes)
        ]
        out[mode] = _summary(vals)
    headline = out[headline_mode]["median"]
    out["headline"] = headline
    runtime.print("Eval protocol:", json.dumps(out, sort_keys=True))
    runtime.print("Test - Reward:", headline)
    return out
