"""Optimizers: optax plus a TF-flavoured RMSProp.

``rmsprop_tf`` matches reference sheeprl/optim/rmsprop_tf.py:14 — epsilon
inside the sqrt, square-average accumulator initialized to ones, and
learning rate folded into the momentum buffer — which is what Dreamer
V1/V2 configs expect.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


def rmsprop_tf(
    learning_rate: float,
    decay: float = 0.9,
    eps: float = 1e-8,
    momentum: float = 0.0,
    centered: bool = False,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    def init_fn(params):
        acc = jax.tree_util.tree_map(jnp.ones_like, params)  # ones, not zeros
        mom = jax.tree_util.tree_map(jnp.zeros_like, params) if momentum > 0 else None
        mg = jax.tree_util.tree_map(jnp.zeros_like, params) if centered else None
        return {"acc": acc, "mom": mom, "mg": mg}

    def update_fn(updates, state, params=None):
        grads = updates
        if weight_decay and params is not None:
            grads = jax.tree_util.tree_map(lambda g, p: g + weight_decay * p, grads, params)
        acc = jax.tree_util.tree_map(
            lambda a, g: a * decay + (1 - decay) * (g * g), state["acc"], grads
        )
        if centered:
            mg = jax.tree_util.tree_map(lambda m, g: m * decay + (1 - decay) * g, state["mg"], grads)
            denom = jax.tree_util.tree_map(lambda a, m: jnp.sqrt(a - m * m + eps), acc, mg)
        else:
            mg = None
            denom = jax.tree_util.tree_map(lambda a: jnp.sqrt(a + eps), acc)  # eps inside sqrt
        if momentum > 0:
            mom = jax.tree_util.tree_map(
                lambda b, g, d: b * momentum + learning_rate * g / d, state["mom"], grads, denom
            )
            new_updates = jax.tree_util.tree_map(lambda m: -m, mom)
        else:
            mom = None
            new_updates = jax.tree_util.tree_map(lambda g, d: -learning_rate * g / d, grads, denom)
        return new_updates, {"acc": acc, "mom": mom, "mg": mg}

    return optax.GradientTransformation(init_fn, update_fn)


# the reference's torch optimizer argument names, mapped to optax's
# (reference configs/optim/*.yaml: lr / betas / alpha / weight_decay)
_TORCH_KEY_RENAMES = {"lr": "learning_rate", "alpha": "decay"}


# torch optimizer kwargs with NO optax counterpart: harmless at their torch
# defaults (dropped silently), an explicit error otherwise — better than the
# TypeError the optax factory would raise
_TORCH_NOOP_DEFAULTS = {
    "dampening": 0,
    "foreach": None,
    "fused": None,
    "maximize": False,
    "capturable": False,
    "differentiable": False,
    "amsgrad": False,
}


def normalize_optim_kwargs(kwargs: dict) -> dict:
    """Accept torch-style optimizer kwargs alongside optax-native ones so
    reference command lines (``algo.optimizer.lr=3e-4``) run unmodified.
    Also coerces yaml-1.1 scientific-notation strings ("3e-4") to floats,
    and drops torch-only kwargs left at their torch defaults (raising an
    actionable error when they are not)."""
    out = {}
    betas = kwargs.pop("betas", None)
    if betas is not None:
        out["b1"], out["b2"] = betas
    for k, v in kwargs.items():
        if isinstance(v, str):
            try:
                v = float(v)
            except ValueError:
                pass
        if k in _TORCH_NOOP_DEFAULTS:
            if v in (_TORCH_NOOP_DEFAULTS[k], None):
                continue
            raise ValueError(
                f"torch optimizer kwarg '{k}={v}' has no optax equivalent; remove it "
                f"from the optimizer config (only its torch default "
                f"{_TORCH_NOOP_DEFAULTS[k]!r} is accepted and ignored)."
            )
        out[_TORCH_KEY_RENAMES.get(k, k)] = v
    return out


def resolve_weight_decay(kwargs: dict, fn) -> float:
    """torch-L2 weight-decay resolution shared by every optimizer factory:
    when ``fn`` does not take ``weight_decay`` natively (optax.adam/sgd/
    rmsprop), pop it from ``kwargs`` and return the rate to chain as
    ``optax.add_decayed_weights`` BEFORE the transform — wd·param then
    enters the gradient moments exactly as torch.optim.Adam(weight_decay=)
    does. Targets with native handling (optax.adamw, rmsprop_tf) keep the
    kwarg and 0.0 is returned."""
    import inspect

    wd = float(kwargs.get("weight_decay", 0.0) or 0.0)
    if "weight_decay" in kwargs and "weight_decay" not in inspect.signature(fn).parameters:
        kwargs.pop("weight_decay")
        return wd
    return 0.0


class MasterWeightsState(NamedTuple):
    """State of :func:`master_weights`: inner optimizer state (moments etc.
    built on the f32 master copy) plus the f32 master parameters."""

    inner: optax.OptState
    master: optax.Params


def _f32_copy(tree):
    """f32 COPY of every float leaf: the master-weight synthesis rule shared
    by master_weights.init and restore_opt_states.  Always a copy — for
    leaves already f32 (e.g. excluded from the bf16 storage cast) astype
    would alias the parameter buffer, and the jitted train steps donate
    both params and opt state; aliased buffers trip "attempt to donate the
    same buffer twice"."""
    return jax.tree_util.tree_map(
        lambda p: jnp.array(p, dtype=jnp.float32, copy=True)
        if jnp.issubdtype(p.dtype, jnp.floating)
        else p,
        tree,
    )


def master_weights(tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """bf16-true training: keep a float32 master copy of the parameters in
    the optimizer state and run the whole update in f32.

    The model stores (and streams from HBM) bfloat16 parameters — half the
    weight traffic of f32 on the bandwidth-bound paths — while the update
    math keeps full precision: incoming (possibly bf16) gradients are
    upcast, the inner transform's moments live in f32, and the emitted
    update is ``new_master - f32(params)`` so that
    ``optax.apply_updates(params, updates)`` (which computes in the
    promoted dtype before casting back) lands on EXACTLY
    ``bf16(new_master)`` — no drift between master and stored params.

    The torch analogue is Lightning's bf16-true + master-weight optimizers;
    here it is a plain optax transformation, so every algo picks it up
    through ``build_optimizer(..., precision="bf16-true")``.
    """

    def init_fn(params):
        master = _f32_copy(params)
        return MasterWeightsState(inner=tx.init(master), master=master)

    def update_fn(updates, state, params=None):
        if not isinstance(state, MasterWeightsState):
            # a structure change here would break the scan-carried updates
            # (PPO minibatch scans, SAC G-step scans need a structure-stable
            # carry), so migration must happen at restore time instead
            raise TypeError(
                "master_weights.update received a plain opt state (e.g. restored from "
                "a checkpoint saved at a different precision); migrate it on the host "
                "with sheeprl_tpu.optim.restore_opt_states(...) before training."
            )
        grads32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) if jnp.issubdtype(g.dtype, jnp.floating) else g,
            updates,
        )
        inner_updates, new_inner = tx.update(grads32, state.inner, state.master)
        new_master = optax.apply_updates(state.master, inner_updates)
        if params is None:
            emitted = jax.tree_util.tree_map(lambda m, o: m - o, new_master, state.master)
        else:
            emitted = jax.tree_util.tree_map(
                lambda m, p: m - p.astype(jnp.float32), new_master, params
            )
        return emitted, MasterWeightsState(inner=new_inner, master=new_master)

    return optax.GradientTransformation(init_fn, update_fn)


def restore_opt_states(saved, params, precision: str, key_map: Optional[dict] = None):
    """Materialize a checkpointed opt state at restore time and migrate
    its STRUCTURE across precision changes — on the host, outside jit,
    because the scan-based train steps (PPO minibatch scans, SAC G-step
    scans) need a structure-stable opt-state carry:

    - ``precision="bf16-true"`` but ``saved`` has no master weights (a
      checkpoint from an older bf16-true run where params stayed f32, or
      a 32-true exploration run finetuned at bf16-true): wrap it in
      :class:`MasterWeightsState`, synthesizing the f32 master from the
      paired ``params``.
    - any other precision but ``saved`` IS a :class:`MasterWeightsState`
      (bf16-true checkpoint resumed at 32-true / bf16-mixed): unwrap to
      the inner state, whose f32 moments are exactly what the plain
      transform expects.

    ``saved`` is either one opt state or a (possibly nested) dict of
    per-component states; ``params`` pairs with it key-by-key, with
    ``key_map`` renaming saved keys to params keys (e.g. SAC's
    ``{"alpha": "log_alpha"}``).  Every path also runs the leaves through
    ``jnp.asarray`` (the plain-restore behavior this replaces)."""
    key_map = key_map or {}
    if isinstance(saved, dict):
        return {
            k: restore_opt_states(
                v,
                None if params is None else params.get(key_map.get(k, k)),
                precision,
                key_map=key_map,
            )
            for k, v in saved.items()
        }
    saved = jax.tree_util.tree_map(jnp.asarray, saved)
    wrapped = isinstance(saved, MasterWeightsState)
    if precision == "bf16-true" and not wrapped:
        if params is None:
            raise ValueError(
                "restore_opt_states needs the matching params to synthesize the f32 "
                "master weights when migrating a checkpoint to bf16-true."
            )
        return MasterWeightsState(inner=saved, master=_f32_copy(params))
    if precision != "bf16-true" and wrapped:
        return saved.inner
    return saved


def finalize_optimizer(
    tx: optax.GradientTransformation,
    weight_decay: float,
    max_grad_norm: Optional[float],
    precision: str,
) -> optax.GradientTransformation:
    """Shared tail of every optimizer build (plain and ppo-family):
    decoupled weight decay -> global-norm clip -> precision wrapper.
    Keeping it in one place means a precision or ordering tweak cannot
    silently diverge between ``build_optimizer`` and
    ``build_ppo_optimizer``."""
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    if max_grad_norm is not None and max_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(float(max_grad_norm)), tx)
    if precision == "bf16-true":
        tx = master_weights(tx)
    return tx


def build_optimizer(
    optim_cfg: dict,
    max_grad_norm: Optional[float] = None,
    precision: str = "32-true",
) -> optax.GradientTransformation:
    """Instantiate an optax optimizer from a ``_target_`` config node, with
    optional global-norm clipping chained in front (fabric.clip_gradients
    equivalent) and torch-style kwargs accepted (see
    ``normalize_optim_kwargs`` / ``resolve_weight_decay``).

    ``precision="bf16-true"`` wraps the transform in :func:`master_weights`
    (f32 master copy + f32 moments over bf16 stored params)."""
    from sheeprl_tpu.config.compose import _locate

    cfg = dict(optim_cfg)
    target = cfg.pop("_target_")
    kwargs = normalize_optim_kwargs(cfg)
    fn = _locate(target)
    wd = resolve_weight_decay(kwargs, fn)
    tx = fn(**kwargs)
    return finalize_optimizer(tx, wd, max_grad_norm, precision)
