"""Optimizers: optax plus a TF-flavoured RMSProp.

``rmsprop_tf`` matches reference sheeprl/optim/rmsprop_tf.py:14 — epsilon
inside the sqrt, square-average accumulator initialized to ones, and
learning rate folded into the momentum buffer — which is what Dreamer
V1/V2 configs expect.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax


def rmsprop_tf(
    learning_rate: float,
    decay: float = 0.9,
    eps: float = 1e-8,
    momentum: float = 0.0,
    centered: bool = False,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    def init_fn(params):
        acc = jax.tree_util.tree_map(jnp.ones_like, params)  # ones, not zeros
        mom = jax.tree_util.tree_map(jnp.zeros_like, params) if momentum > 0 else None
        mg = jax.tree_util.tree_map(jnp.zeros_like, params) if centered else None
        return {"acc": acc, "mom": mom, "mg": mg}

    def update_fn(updates, state, params=None):
        grads = updates
        if weight_decay and params is not None:
            grads = jax.tree_util.tree_map(lambda g, p: g + weight_decay * p, grads, params)
        acc = jax.tree_util.tree_map(
            lambda a, g: a * decay + (1 - decay) * (g * g), state["acc"], grads
        )
        if centered:
            mg = jax.tree_util.tree_map(lambda m, g: m * decay + (1 - decay) * g, state["mg"], grads)
            denom = jax.tree_util.tree_map(lambda a, m: jnp.sqrt(a - m * m + eps), acc, mg)
        else:
            mg = None
            denom = jax.tree_util.tree_map(lambda a: jnp.sqrt(a + eps), acc)  # eps inside sqrt
        if momentum > 0:
            mom = jax.tree_util.tree_map(
                lambda b, g, d: b * momentum + learning_rate * g / d, state["mom"], grads, denom
            )
            new_updates = jax.tree_util.tree_map(lambda m: -m, mom)
        else:
            mom = None
            new_updates = jax.tree_util.tree_map(lambda g, d: -learning_rate * g / d, grads, denom)
        return new_updates, {"acc": acc, "mom": mom, "mg": mg}

    return optax.GradientTransformation(init_fn, update_fn)


# the reference's torch optimizer argument names, mapped to optax's
# (reference configs/optim/*.yaml: lr / betas / alpha / weight_decay)
_TORCH_KEY_RENAMES = {"lr": "learning_rate", "alpha": "decay"}


def normalize_optim_kwargs(kwargs: dict) -> dict:
    """Accept torch-style optimizer kwargs alongside optax-native ones so
    reference command lines (``algo.optimizer.lr=3e-4``) run unmodified.
    Also coerces yaml-1.1 scientific-notation strings ("3e-4") to floats."""
    out = {}
    betas = kwargs.pop("betas", None)
    if betas is not None:
        out["b1"], out["b2"] = betas
    for k, v in kwargs.items():
        if isinstance(v, str):
            try:
                v = float(v)
            except ValueError:
                pass
        out[_TORCH_KEY_RENAMES.get(k, k)] = v
    return out


def resolve_weight_decay(kwargs: dict, fn) -> float:
    """torch-L2 weight-decay resolution shared by every optimizer factory:
    when ``fn`` does not take ``weight_decay`` natively (optax.adam/sgd/
    rmsprop), pop it from ``kwargs`` and return the rate to chain as
    ``optax.add_decayed_weights`` BEFORE the transform — wd·param then
    enters the gradient moments exactly as torch.optim.Adam(weight_decay=)
    does. Targets with native handling (optax.adamw, rmsprop_tf) keep the
    kwarg and 0.0 is returned."""
    import inspect

    wd = float(kwargs.get("weight_decay", 0.0) or 0.0)
    if "weight_decay" in kwargs and "weight_decay" not in inspect.signature(fn).parameters:
        kwargs.pop("weight_decay")
        return wd
    return 0.0


def build_optimizer(optim_cfg: dict, max_grad_norm: Optional[float] = None) -> optax.GradientTransformation:
    """Instantiate an optax optimizer from a ``_target_`` config node, with
    optional global-norm clipping chained in front (fabric.clip_gradients
    equivalent) and torch-style kwargs accepted (see
    ``normalize_optim_kwargs`` / ``resolve_weight_decay``)."""
    from sheeprl_tpu.config.compose import _locate

    cfg = dict(optim_cfg)
    target = cfg.pop("_target_")
    kwargs = normalize_optim_kwargs(cfg)
    fn = _locate(target)
    wd = resolve_weight_decay(kwargs, fn)
    tx = fn(**kwargs)
    if wd:
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    if max_grad_norm is not None and max_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(float(max_grad_norm)), tx)
    return tx
