"""``python -m sheeprl_tpu`` entry point (reference sheeprl/__main__.py:1-4)."""

from sheeprl_tpu.cli import run

if __name__ == "__main__":
    run()
