"""Sequence/context parallelism: train a causal transformer with the
SEQUENCE axis sharded over the device mesh.

The reference framework has no long-context support at all (SURVEY §5.7:
no attention anywhere, sequence scaling = truncated BPTT). This module is
the TPU-first extension that makes long context first-class:

- each device holds a contiguous ``S/n`` shard of every sequence;
- attention runs as a ring: K/V shards rotate over ICI with
  ``jax.lax.ppermute`` while an online softmax folds one block per hop
  (``sheeprl_tpu.ops.ring_attention``) — per-device memory stays
  O(S/n * block) even under gradients: a custom VJP re-rotates K/V
  around the ring in the backward pass instead of saving the forward
  scan's per-hop K/V carries (numbers in
  benchmarks/results/ring_attention_r4.json);
- gradients are ``pmean``-reduced across the ring, so the step is a drop-in
  SPMD train step: params replicated in, params replicated out.

Wrap-around targets: inputs/targets are pre-shifted HOST-side
(``inputs = tokens[:, :-1]``, ``targets = tokens[:, 1:]``) so no logits ever
need to cross a shard boundary.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from sheeprl_tpu.utils.jax_compat import shard_map


def make_sequence_parallel_train_step(
    mesh: Mesh,
    model,
    tx: optax.GradientTransformation,
    axis_name: str = "data",
) -> Tuple[Callable, NamedSharding]:
    """Build a jitted sequence-parallel LM train step over ``mesh``.

    ``model`` must be a flax module built with ``parallelism="ring"`` and
    the same ``axis_name`` (e.g. ``models.SequenceTransformer``). Returns
    ``(step, token_sharding)`` where ``step(params, opt_state, inputs,
    targets) -> (params, opt_state, loss)`` and inputs/targets are
    ``(B, S)`` int32 with S divisible by the axis size, placed with
    ``token_sharding``.
    """
    token_spec = P(None, axis_name)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), token_spec, token_spec),
        out_specs=(P(), P(), P()),
    )
    def step(params, opt_state, inputs, targets):
        def loss_fn(p):
            logits = model.apply(p, inputs)  # (B, S_local, V), ring attention inside
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
            return nll.mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # average across the ring: every device saw S/n of each sequence
        grads = jax.lax.pmean(grads, axis_name)
        loss = jax.lax.pmean(loss, axis_name)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step, NamedSharding(mesh, token_spec)
