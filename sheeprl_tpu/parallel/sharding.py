"""Canonical mesh-axis layout for pod-scale sharded training.

The runtime used to build a one-axis ``("data",)`` mesh; DP and FSDP both
laid everything over that single axis, which works but cannot express the
layouts a pod actually wants (batch over ICI, params over a separate
ZeRO axis, and eventually tensor axes).  This module owns the 2-D
``Mesh(..., ("data", "fsdp"))`` vocabulary (SNIPPETS.md [2]'s
``SpecLayout`` idea, PAPER.md §5.8's ``jax.lax`` collectives as the
NCCL-equivalent):

- the **batch** (a rollout's env columns, a replay draw's rows) is always
  sharded over BOTH axes flattened — every device is a data-parallel
  worker regardless of how the pod is split;
- **params/opt-state** are replicated under ``dp`` and sharded over the
  ``fsdp`` axis (largest divisible dim, ZeRO-style) under
  ``strategy=fsdp``;
- ``fabric.mesh_shape`` picks the split: ``auto`` reproduces the pre-2-D
  behavior bit-exactly (all devices on ``data`` for dp, all on ``fsdp``
  for fsdp — either way every device holds a batch shard), an explicit
  ``[d, f]`` (or ``"dxf"`` string) lays a pod as d-way data x f-way
  param sharding.

Everything here is pure layout bookkeeping: no jax dispatches happen at
import or construction time, so the module is free on the hot import
path.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "BATCH_AXES",
    "ShardingLayout",
    "parse_mesh_shape",
    "shard_dim_for",
    "shard_slice",
]

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
# the flattened batch axes: batch dims shard over data x fsdp together,
# so world_size (the number of batch shards) is always every device
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


def shard_dim_for(shape: Sequence[int], fsdp_size: int) -> Optional[int]:
    """The dim the fsdp axis shards for a leaf of ``shape``: its LARGEST
    dim divisible by ``fsdp_size`` (picking the first divisible dim can
    hit a small leading axis — e.g. a conv kernel's spatial dim —
    producing tiny shards and halo all-gathers); None when the leaf stays
    replicated (``fsdp_size`` 1, scalars, indivisible shapes).

    Pure and deterministic in (shape, fsdp_size) alone — the SAME rule
    drives :meth:`ShardingLayout.param_spec` (live placement) and the
    sharded checkpoint plane (resilience/sharded_ckpt.py), so a shard
    file written under one mesh maps onto any other mesh's layout
    without recording per-leaf placement decisions."""
    f = int(fsdp_size)
    shape = tuple(int(s) for s in shape)
    if f <= 1:
        return None
    return max(
        (d for d, s in enumerate(shape) if s >= f and s % f == 0),
        key=lambda d: shape[d],
        default=None,
    )


def shard_slice(shape: Sequence[int], dim: int, n_shards: int, rank: int) -> Tuple[slice, ...]:
    """Index tuple selecting shard ``rank`` of ``n_shards`` equal splits
    along ``dim`` of a leaf of ``shape`` (the slice a device on fsdp
    coordinate ``rank`` owns under :func:`shard_dim_for`'s layout)."""
    size = int(shape[dim])
    if size % int(n_shards):
        raise ValueError(f"dim {dim} of {tuple(shape)} does not split into {n_shards} shards")
    per = size // int(n_shards)
    idx = [slice(None)] * len(shape)
    idx[dim] = slice(int(rank) * per, (int(rank) + 1) * per)
    return tuple(idx)


def parse_mesh_shape(spec: Any, n_devices: int, strategy: str = "auto") -> Tuple[int, int]:
    """Resolve ``fabric.mesh_shape`` to ``(data, fsdp)`` axis sizes.

    ``auto`` (default) reproduces the pre-2-D-mesh layouts exactly:
    every device on ``data`` for dp/auto strategies, every device on
    ``fsdp`` for ``strategy=fsdp`` (the old code sharded params over the
    same axis the batch used — ZeRO — which in the 2-D vocabulary IS a
    ``(1, n)`` mesh).  Explicit shapes accept a 2-sequence ``[d, f]`` or
    a string ``"4x2"`` / ``"4,2"``; one entry may be ``-1`` (inferred).
    """
    n = int(n_devices)
    if spec is None or (isinstance(spec, str) and spec.strip().lower() in ("", "auto")):
        return (1, n) if strategy == "fsdp" else (n, 1)
    if isinstance(spec, str):
        parts = [p for p in spec.replace("x", ",").split(",") if p.strip()]
    else:
        try:
            parts = list(spec)
        except TypeError:
            raise ValueError(f"mesh_shape must be 'auto', 'DxF', or a [data, fsdp] pair; got {spec!r}")
    if len(parts) != 2:
        raise ValueError(f"mesh_shape needs exactly two entries (data, fsdp); got {spec!r}")
    d, f = (int(p) for p in parts)
    if d == -1 and f == -1:
        raise ValueError("mesh_shape may infer (-1) at most one axis")
    if d == -1:
        d = n // f if f > 0 else 0
    if f == -1:
        f = n // d if d > 0 else 0
    if d <= 0 or f <= 0 or d * f != n:
        raise ValueError(
            f"mesh_shape {spec!r} does not tile {n} device(s): data({d}) x fsdp({f}) != {n}"
        )
    return d, f


def build_mesh(devices: Sequence[Any], mesh_shape: Any, strategy: str = "auto") -> Mesh:
    """The 2-D device mesh every runtime owns (see :func:`parse_mesh_shape`)."""
    d, f = parse_mesh_shape(mesh_shape, len(devices), strategy)
    return Mesh(np.asarray(devices).reshape(d, f), axis_names=BATCH_AXES)


class ShardingLayout:
    """Canonical ``PartitionSpec``s for one mesh (SNIPPETS.md [2] style).

    One instance rides on :class:`~sheeprl_tpu.parallel.MeshRuntime` as
    ``runtime.layout`` — the single source of truth the train steps, the
    replay cache, and the telemetry all read, so the axis vocabulary
    cannot drift per subsystem.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    # ------------------------------------------------------------- sizes
    @property
    def data_size(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    @property
    def fsdp_size(self) -> int:
        return int(self.mesh.shape[FSDP_AXIS])

    @property
    def n_shards(self) -> int:
        """Batch shard count — every device, regardless of the d x f split."""
        return self.data_size * self.fsdp_size

    # ------------------------------------------------------------- specs
    def batch_spec(self, axis: int = 0) -> P:
        """Batch dim ``axis`` sharded over the flattened (data, fsdp) axes."""
        return P(*([None] * axis + [BATCH_AXES]))

    def batch_sharding(self, axis: int = 0) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(axis))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def param_spec(self, shape: Sequence[int]) -> P:
        """ZeRO layout for one leaf: :func:`shard_dim_for`'s pick sharded
        over ``fsdp``; scalars and indivisible leaves stay replicated.
        The dim rule lives in the module-level helper so the sharded
        checkpoint plane applies the identical rule without a mesh."""
        shape = tuple(shape)
        best = shard_dim_for(shape, self.fsdp_size)
        if best is None:
            return P()
        spec = [None] * len(shape)
        spec[best] = FSDP_AXIS
        return P(*spec)

    def param_sharding(self, leaf: Any) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(getattr(leaf, "shape", ())))

    # ------------------------------------------------- in-jit constraints
    def constrain_state(self, tree: Any, fsdp: bool) -> Any:
        """``with_sharding_constraint`` at the update boundary: pin every
        float/array leaf of a state tree (params, opt-state, moments) to
        its canonical layout — the fsdp ZeRO layout when ``fsdp``, else
        replicated.  This is what makes the mesh layout EXPLICIT in the
        lowered program (GSPMD otherwise may pick a different resolution
        per output, and the reduce-scatter/all-gather structure becomes an
        accident of propagation).  Only call inside jit."""
        import jax

        from sheeprl_tpu.utils.jax_compat import with_sharding_constraint

        def leaf_constraint(x):
            if not hasattr(x, "shape"):
                return x
            s = self.param_sharding(x) if fsdp else self.replicated
            return with_sharding_constraint(x, s)

        return jax.tree_util.tree_map(leaf_constraint, tree)

    def constrain_batch(self, tree: Any, axis: int = 0) -> Any:
        """Pin a batch pytree to the flattened batch-axes layout (in-jit)."""
        import jax

        from sheeprl_tpu.utils.jax_compat import with_sharding_constraint

        sharding = self.batch_sharding(axis)
        return jax.tree_util.tree_map(
            lambda x: with_sharding_constraint(x, sharding) if hasattr(x, "shape") else x,
            tree,
        )

    def flat_rank(self):
        """Flattened device index inside a ``shard_map`` body: the batch
        shard this device owns, row-major over (data, fsdp) — matches the
        device order :meth:`batch_spec` splits a batch in.  Built from two
        ``axis_index`` calls so it works on every jax in the support
        window (tuple-axis ``axis_index`` is newer than 0.4.x)."""
        from sheeprl_tpu.utils.jax_compat import flat_axis_index

        return flat_axis_index(BATCH_AXES, (self.data_size, self.fsdp_size))

    # ------------------------------------------------------------- telemetry
    def param_shard_bytes(self, tree: Any) -> int:
        """Per-device bytes of the fsdp-sharded param tree (telemetry:
        the ZeRO memory win actually achieved, given indivisible leaves
        stay replicated)."""
        import jax

        f = self.fsdp_size
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            shape = getattr(leaf, "shape", None)
            if shape is None:
                continue
            n = int(np.prod(shape, dtype=np.int64) or 1)
            itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
            sharded = self.param_spec(shape) != P()
            total += (n // f if sharded else n) * itemsize
        return int(total)

    def describe(self) -> Dict[str, Any]:
        """Telemetry stub: axis names/sizes for the ``mesh`` key."""
        return {
            "axes": {DATA_AXIS: self.data_size, FSDP_AXIS: self.fsdp_size},
            "devices": self.n_shards,
        }


def collective_bytes_estimate(compiled: Any) -> Optional[float]:
    """Best-effort per-update cross-device traffic estimate from XLA's
    ``Compiled.cost_analysis()`` (the ``bytes accessed`` breakdown carries
    operand traffic; collective-specific keys exist only on some
    backends).  Returns None when the backend exposes nothing usable —
    callers must treat this as advisory telemetry, never a gate."""
    try:
        costs = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(costs, (list, tuple)):
        costs = costs[0] if costs else None
    if not isinstance(costs, dict):
        return None
    # backend-dependent key spellings: TPU exposes dedicated cross-core /
    # network counters; CPU/GPU report only the aggregate operand traffic
    # ("bytes accessed"), which upper-bounds the collective term
    for key in (
        "bytes accessed cross-core",
        "network bytes accessed",
        "bytes accessed output",
        "bytes accessedout{}",
        "bytes accessed",
    ):
        if key in costs:
            try:
                return float(costs[key])
            except (TypeError, ValueError):
                continue
    return None
