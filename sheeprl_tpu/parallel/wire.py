"""Wire-format v2: pickle-free scatter-gather framing for the transport.

The v1 tcp wire format (``transport._send_frame``) pickles a per-frame
meta tuple — ``(tag, seq, extra, leaves, total)`` — for EVERY payload
frame and writes one ``sendall`` per leaf, so a rollout frame pays a
pickle of its full leaf table plus one syscall per array even though the
payload structure is identical round after round.  BENCH_r16's composed
superbench named transport the fleet bottleneck; SEED RL and IMPALA both
locate the actor→learner throughput fight exactly here, in the
serialization/framing layer.

v2 replaces the per-frame pickle with a binary header + a CACHED leaf
table and ships the payload with vectored I/O:

.. code-block:: text

    offset  size  field
    ------  ----  ------------------------------------------------------
    0       2     magic "S2"
    2       1     flags (1=compressed 2=integrity 4=has-table 8=coalesced)
    3       1     tag length T
    4       4     struct_id  (crc32 of the leaf-table bytes: content-
                  addressed, so a stale receiver cache can never decode
                  the wrong geometry)
    8       8     seq (signed)
    16      4     extra length E (pickled extras; empty tuple -> 0)
    20      4     table length L (0 when the receiver already holds
                  struct_id from an earlier frame of this connection)
    24      4     payload length P (compressed length when flag 1)
    28      8     integrity checksum (flag 2; 0 otherwise)
    36      T     tag bytes (ascii)
    36+T    E     extras (pickled tuple — control metadata, not payload)
    ...     L     leaf table: n_leaves, then per leaf key/dtype/shape
    ...     P     raw array bytes, leaves back-to-back (offsets/sizes are
                  DERIVED from the table — they never ride the wire)

The whole frame goes out as ONE ``socket.sendmsg`` gather call (header +
every leaf buffer), so the hot path serializes nothing but the extras
tuple and the first occurrence of each payload structure.  The receive
side lands the payload into a pooled buffer exactly like v1 and rebuilds
the leaf views zero-copy; a truncated or corrupt table raises
:class:`WireFormatError` (a typed stream-desync, recovered by the
existing reconnect machinery) — it can never mis-shape an array, because
the decoded geometry is cross-checked against the payload length before
any view is built.

Also here: the coalesced-batch payload codec (many small same-
destination frames inside one wire frame), the :class:`OverlappedSender`
pipeline (device→host snapshot / digest / socket write as overlapped
stages), and the ``algo.wire_format`` resolver.  The channel classes
that USE this codec live in ``transport.py`` (``wire_channel_cls``) so
the format layer stays import-light and socket-free.
"""

from __future__ import annotations

import os
import pickle
import queue as queue_mod
import socket
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

__all__ = [
    "COAL_TAG",
    "HDR2",
    "MAGIC_V2",
    "OverlappedSender",
    "WireFormatError",
    "build_leaves",
    "decode_coalesced",
    "compile_table",
    "decode_leaf_table",
    "encode_coalesced_entry",
    "encode_leaf_table",
    "leaf_views",
    "pack_header_v2",
    "read_payload_v2",
    "sendmsg_all",
    "wire_setting",
]


class WireFormatError(ConnectionResetError):
    """A structurally invalid v2 frame (truncated/corrupt leaf table,
    unknown struct_id, geometry/payload length mismatch).  Subclasses
    :class:`ConnectionResetError` on purpose: the reader loops already
    treat that as a stream desync and run the reconnect machinery, so a
    corrupted header degrades to a reconnect, never to a mis-shaped
    array or a crashed reader thread."""


MAGIC_V2 = b"S2"
# magic, flags, tag_len, struct_id, seq, extra_len, table_len,
# payload_len, crc — see the module docstring for the layout
HDR2 = struct.Struct("!2sBBIqIIIQ")

F2_COMPRESSED = 1
F2_INTEGRITY = 2
F2_TABLE = 4
F2_COALESCED = 8

# tag of a coalesced batch frame (flag 8); the subframes inside carry
# their own real tags
COAL_TAG = "__coal__"

_TABLE_HDR = struct.Struct("!H")  # n_leaves
_LEAF_HDR = struct.Struct("!HBB")  # key_len, dtype_len, ndim
_SUB_HDR = struct.Struct("!I")  # coalesced sub-entry length prefix

# decode-side sanity bounds — anything past these is a desync, not data
_MAX_LEAVES = 4096
_MAX_NDIM = 16
_MAX_EXTRA_BYTES = 64 << 20
_MAX_TABLE_BYTES = 16 << 20

# compression probe (adaptive tcp_compress): compress the first page and
# skip the full pass unless it shrank below this ratio — float rollout
# payloads are incompressible and v1 paid a full zlib pass to find out
_PROBE_BYTES = 4096
_PROBE_RATIO = 0.9


def wire_setting(cfg) -> str:
    """Resolve ``algo.wire_format`` (env override ``SHEEPRL_WIRE_FORMAT``)
    to ``v1`` or ``v2``; v1 — the bit-exact pre-v2 path — is the default
    until parity is proven per deployment."""
    val = cfg.algo.get("wire_format", "v1")
    env = os.environ.get("SHEEPRL_WIRE_FORMAT")
    if env is not None:
        val = env
    s = str(val).lower()
    if s in ("v2", "2", "sg", "scatter_gather"):
        return "v2"
    return "v1"


# --------------------------------------------------------------- leaf table
def build_leaves(
    arrays: Optional[Sequence[Tuple[str, np.ndarray]]],
) -> Tuple[List[Tuple], List[memoryview], int]:
    """Flatten ``arrays`` once into ``(leaves, byte_views, total_bytes)``
    with v1-compatible leaves ``(key, shape, dtype_str, offset, nbytes)``
    — the views are zero-copy for already-contiguous inputs, so the
    payload bytes are only ever touched by the socket."""
    leaves: List[Tuple] = []
    bufs: List[memoryview] = []
    off = 0
    for key, arr in arrays or []:
        a = np.ascontiguousarray(arr)
        nb = int(a.nbytes)
        leaves.append((key, tuple(a.shape), str(a.dtype), off, nb))
        if nb:
            bufs.append(memoryview(a.reshape(-1)).cast("B"))
        off += nb
    return leaves, bufs, off


def encode_leaf_table(leaves: Sequence[Tuple]) -> bytes:
    """Binary leaf table: per leaf ``key_len,dtype_len,ndim,key,dtype,
    dims`` — offsets and byte counts are derived at decode, so the table
    is a pure structure description (cacheable per struct_id)."""
    if len(leaves) > _MAX_LEAVES:
        raise ValueError(f"too many leaves for one frame: {len(leaves)}")
    parts = [_TABLE_HDR.pack(len(leaves))]
    for key, shape, dtype, _off, _nb in leaves:
        kb = str(key).encode("utf-8")
        db = str(dtype).encode("ascii")
        if len(kb) > 0xFFFF or len(db) > 0xFF or len(shape) > _MAX_NDIM:
            raise ValueError(f"leaf {key!r} does not fit the table encoding")
        parts.append(_LEAF_HDR.pack(len(kb), len(db), len(shape)))
        parts.append(kb)
        parts.append(db)
        if shape:
            parts.append(struct.pack(f"!{len(shape)}I", *shape))
    return b"".join(parts)


def decode_leaf_table(blob: bytes) -> List[Tuple]:
    """Inverse of :func:`encode_leaf_table`; raises
    :class:`WireFormatError` on ANY structural defect (truncation,
    trailing garbage, absurd counts, non-numeric dtypes) — corrupt
    metadata must surface as a typed stream error, never as an array of
    the wrong shape."""
    try:
        view = memoryview(blob)
        if len(view) < _TABLE_HDR.size:
            raise WireFormatError("leaf table truncated before the leaf count")
        (n_leaves,) = _TABLE_HDR.unpack_from(view, 0)
        if n_leaves > _MAX_LEAVES:
            raise WireFormatError(f"leaf table claims {n_leaves} leaves (cap {_MAX_LEAVES})")
        pos = _TABLE_HDR.size
        leaves: List[Tuple] = []
        off = 0
        for _ in range(n_leaves):
            if pos + _LEAF_HDR.size > len(view):
                raise WireFormatError("leaf table truncated inside a leaf header")
            key_len, dtype_len, ndim = _LEAF_HDR.unpack_from(view, pos)
            pos += _LEAF_HDR.size
            if ndim > _MAX_NDIM:
                raise WireFormatError(f"leaf claims {ndim} dims (cap {_MAX_NDIM})")
            end = pos + key_len + dtype_len + 4 * ndim
            if end > len(view):
                raise WireFormatError("leaf table truncated inside a leaf body")
            key = bytes(view[pos : pos + key_len]).decode("utf-8")
            pos += key_len
            dtype_str = bytes(view[pos : pos + dtype_len]).decode("ascii")
            pos += dtype_len
            shape = struct.unpack_from(f"!{ndim}I", view, pos) if ndim else ()
            pos += 4 * ndim
            try:
                dt = np.dtype(dtype_str)
            except Exception:
                raise WireFormatError(f"leaf {key!r} carries undecodable dtype {dtype_str!r}") from None
            if dt.hasobject:
                raise WireFormatError(f"leaf {key!r} carries an object dtype")
            count = 1
            for d in shape:
                count *= int(d)
            nb = count * dt.itemsize
            leaves.append((key, tuple(int(d) for d in shape), dtype_str, off, nb))
            off += nb
        if pos != len(view):
            raise WireFormatError(f"{len(view) - pos} trailing bytes after the leaf table")
        return leaves
    except (UnicodeDecodeError, struct.error) as e:
        raise WireFormatError(f"undecodable leaf table: {e}") from None


def leaf_views(leaves: Sequence[Tuple], buf) -> Dict[str, np.ndarray]:
    """Rebuild the payload dict as zero-copy VIEWS into ``buf`` (a
    pooled recv arena or a decompressed private bytes object).  Views
    are valid only until the frame's release — consumers that keep the
    data must cleanse first (``Frame.arrays_copy`` / ``np.array``; the
    jaxlint zero-copy-alias checker enforces this for device uploads)."""
    out: Dict[str, np.ndarray] = {}
    for key, shape, dtype, off, _nb in leaves:
        dt = np.dtype(dtype)
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out[key] = np.frombuffer(buf, dtype=dt, count=count, offset=off).reshape(shape)
    return out


class CompiledTable(list):
    """A decoded leaf table precompiled for the per-frame hot path: the
    list body is the plain v1-compatible leaves (so every generic
    consumer — retrans ring, coalesced delivery, tests — keeps working),
    plus a ``views_spec`` with the ``np.dtype`` objects and element
    counts already resolved and ``raw_len`` precomputed.  Tables are
    decoded once per (stream, struct_id); frames of that structure then
    build their views without re-parsing a dtype string or running
    ``np.prod`` per leaf — at params-tree leaf counts that parse work
    dominated the receive loop."""

    __slots__ = ("views_spec", "raw_len")


def compile_table(leaves: Sequence[Tuple]) -> CompiledTable:
    out = CompiledTable(leaves)
    out.views_spec = tuple(
        (key, shape, np.dtype(dtype), off, int(np.prod(shape, dtype=np.int64)) if shape else 1)
        for key, shape, dtype, off, _nb in leaves
    )
    out.raw_len = (leaves[-1][3] + leaves[-1][4]) if leaves else 0
    return out


# ------------------------------------------------------------ frame wire IO
def pack_header_v2(
    flags: int,
    tag: str,
    struct_id: int,
    seq: int,
    extra_blob: bytes,
    table_blob: bytes,
    payload_len: int,
    crc: Optional[int],
) -> bytes:
    tagb = tag.encode("ascii")
    if len(tagb) > 0xFF:
        raise ValueError(f"frame tag too long for the wire: {tag!r}")
    if crc is not None:
        flags |= F2_INTEGRITY
    hdr = HDR2.pack(
        MAGIC_V2,
        flags,
        len(tagb),
        struct_id & 0xFFFFFFFF,
        int(seq),
        len(extra_blob),
        len(table_blob),
        int(payload_len),
        (int(crc) if crc is not None else 0) & 0xFFFFFFFFFFFFFFFF,
    )
    return hdr + tagb + extra_blob + table_blob


_IOV_MAX = 512  # conservative vs the kernel's UIO_MAXIOV (1024)


def sendmsg_all(sock, bufs: Sequence) -> None:
    """Write every buffer with vectored I/O, handling partial sends —
    the v2 replacement for v1's one-``sendall``-per-leaf loop (one
    syscall per frame in the common case)."""
    mvs: List[memoryview] = []
    for b in bufs:
        mv = b if isinstance(b, memoryview) else memoryview(b)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        if len(mv):
            mvs.append(mv)
    while mvs:
        try:
            n = sock.sendmsg(mvs[:_IOV_MAX])
        except InterruptedError:
            continue
        while mvs and n >= len(mvs[0]):
            n -= len(mvs[0])
            mvs.pop(0)
        if n and mvs:
            mvs[0] = mvs[0][n:]


def recv_exact_into(sock, mv: memoryview) -> None:
    """Fill ``mv`` completely.  ``MSG_WAITALL`` asks the kernel to
    assemble the whole buffer in ONE syscall instead of a Python loop
    over socket-buffer-sized chunks — on a 1 MB payload that is the
    difference between ~1 and ~16 reader wakeups; a short return (signal
    delivery) falls back to the plain loop for the remainder."""
    want = len(mv)
    if not want:
        return
    try:
        got = sock.recv_into(mv, want, socket.MSG_WAITALL)
    except InterruptedError:
        got = 0
    if got == 0:
        raise ConnectionResetError("peer closed the stream")
    while got < want:
        n = sock.recv_into(mv[got:], want - got)
        if n == 0:
            raise ConnectionResetError("peer closed the stream")
        got += n


def probe_compress(bufs: Sequence[memoryview], total: int) -> Optional[bytes]:
    """Adaptive compression: zlib the first page and bail unless it
    shrank (``None`` = ship raw; callers count the skip).  A payload
    whose head page is incompressible (float rollouts) skips the full
    pass it would have paid for nothing under v1."""
    head = bytearray()
    for mv in bufs:
        take = min(len(mv), _PROBE_BYTES - len(head))
        head += mv[:take]
        if len(head) >= _PROBE_BYTES:
            break
    if len(head) >= 256 and len(zlib.compress(bytes(head), 1)) >= int(len(head) * _PROBE_RATIO):
        return None
    return zlib.compress(b"".join(bytes(mv) for mv in bufs), 1)


def read_payload_v2(sock, pool, payload_len: int, flags: int, raw_len: int):
    """Land the payload into a pooled buffer (decompressing to a private
    bytes object when flagged) and cross-check its length against the
    leaf-table geometry — the mis-shape guard."""
    buf: Any = None
    if payload_len:
        buf = pool.take(payload_len)
        recv_exact_into(sock, memoryview(buf)[:payload_len])
        if flags & F2_COMPRESSED:
            raw = zlib.decompress(memoryview(buf)[:payload_len])
            pool.give(buf)
            buf = raw
            if len(raw) != raw_len:
                raise WireFormatError(
                    f"decompressed payload is {len(raw)} bytes, leaf table says {raw_len}"
                )
        elif payload_len != raw_len:
            raise WireFormatError(
                f"payload length {payload_len} does not match leaf-table geometry {raw_len}"
            )
    elif raw_len:
        raise WireFormatError(f"empty payload for a {raw_len}-byte leaf table")
    return buf


# ----------------------------------------------------------- coalesced codec
def encode_coalesced_entry(tag: str, seq: int, extra: Tuple, items) -> bytes:
    """One subframe of a coalesced batch: a length-prefixed pickle of the
    full frame tuple.  Subframes are SMALL by construction (heartbeats,
    live summaries, fused-collector inserts below the coalesce gate), so
    pickling them is not the hot path the v2 format removes — the win is
    one wire frame + one syscall for the whole batch."""
    if items is not None:
        items = [(k, np.ascontiguousarray(a)) for k, a in items]
    blob = pickle.dumps((tag, int(seq), tuple(extra), items), protocol=pickle.HIGHEST_PROTOCOL)
    return _SUB_HDR.pack(len(blob)) + blob


def decode_coalesced(payload) -> List[Tuple]:
    """Parse a coalesced batch payload into v1-shaped frame tuples
    ``(tag, seq, extra, leaves, buf, crc)`` — each subframe gets a
    PRIVATE contiguous buffer (the batch buffer returns to the pool
    immediately), so delivery and release need no special casing."""
    mv = memoryview(payload)
    out: List[Tuple] = []
    pos = 0
    while pos < len(mv):
        if pos + _SUB_HDR.size > len(mv):
            raise WireFormatError("coalesced batch truncated inside a length prefix")
        (blen,) = _SUB_HDR.unpack_from(mv, pos)
        pos += _SUB_HDR.size
        if pos + blen > len(mv):
            raise WireFormatError("coalesced batch truncated inside a subframe")
        try:
            tag, seq, extra, items = pickle.loads(bytes(mv[pos : pos + blen]))
        except Exception as e:
            raise WireFormatError(f"undecodable coalesced subframe: {e}") from None
        pos += blen
        leaves, bufs, total = build_leaves(items)
        buf = b"".join(bytes(b) for b in bufs) if total else b""
        out.append((str(tag), int(seq), tuple(extra), leaves, buf, None))
    return out


# --------------------------------------------------------- overlapped sender
class OverlappedSender:
    """The player's device→wire pipeline (3 overlapped stages inside the
    existing ``collect`` span):

    1. ``submit`` SNAPSHOTS the payload synchronously — the device→host
       materialization plus a private copy of any leaf that aliases a
       rollout buffer the next collect step will scribble over;
    2./3. a worker thread runs the integrity digest and the socket write
       (both live inside ``channel.send``) while the caller is already
       collecting the next rollout.

    Double-buffered by construction: at most one frame in flight on the
    worker plus one being snapshotted by the caller; a second ``submit``
    while one is queued blocks (the transport's credit window stays the
    real backpressure).  ``flush()`` drains the pipeline and re-raises
    any send failure — call it before anything that must order after the
    data frame (checkpoint barriers, stop frames, direct sends on the
    same channel)."""

    def __init__(self, channel, name: str = "sheeprl-wire-sender"):
        self._chan = channel
        self._q: "queue_mod.Queue[Optional[tuple]]" = queue_mod.Queue(maxsize=1)
        self._err: Optional[BaseException] = None
        self._cond = threading.Condition()
        self._pending = 0  # submitted, not yet fully sent
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()
        self.frames = 0

    def _loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            tag, arrays, extra, seq, timeout = job
            try:
                self._chan.send(tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)
            except BaseException as e:  # re-raised at the next submit/flush
                self._err = e
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def _raise_pending(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def submit(self, tag, arrays, extra=(), seq=-1, timeout: float = 600.0) -> None:
        """Stage 1 (synchronous snapshot) + enqueue for stages 2-3."""
        self._raise_pending()
        # the snapshot: np.asarray materializes device/lazy leaves; leaves
        # that are views of live buffers are copied so the next rollout
        # step cannot mutate bytes the worker has not written yet
        snap = []
        for k, v in arrays or []:
            a = np.asarray(v)
            snap.append((k, np.array(a) if a.base is not None else a))
        with self._cond:
            self._pending += 1
        self._q.put((tag, snap, tuple(extra), seq, timeout))
        self.frames += 1

    def flush(self, timeout: float = 600.0) -> None:
        """Drain the pipeline; re-raises the worker's failure if any."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._pending == 0, timeout=timeout):
                raise TimeoutError("overlapped sender did not drain")
        self._raise_pending()

    def close(self) -> None:
        try:
            with self._cond:
                self._cond.wait_for(lambda: self._pending == 0, timeout=5.0)
        finally:
            self._q.put(None)
            self._thread.join(timeout=5.0)
