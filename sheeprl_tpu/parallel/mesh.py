"""MeshRuntime — the TPU-native replacement for Lightning Fabric.

The reference wraps torch.distributed in Fabric (per-process DDP launcher,
NCCL/Gloo collectives, precision plugins — SURVEY.md §2.7/§5.8). On TPU the
idiomatic equivalent is single-controller SPMD:

- ``jax.distributed.initialize`` (multi-host) instead of TCPStore rendezvous;
- a ``jax.sharding.Mesh`` whose axes replace process groups: the ``data``
  axis is DDP, a ``model`` axis gives fsdp/tensor sharding;
- gradient all-reduce disappears: batches are sharded over ``data`` and XLA
  inserts the ``psum`` inside the jitted update (``NamedSharding`` + jit);
- precision plugins become a dtype policy (params fp32, compute bf16 on the
  MXU by default).

One MeshRuntime instance plays the roles of reference cli.py's
``hydra.utils.instantiate(cfg.fabric)`` object and utils/fabric.py:8's
single-device clone (``runtime.single_device()``).
"""

from __future__ import annotations

import os
import random
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.parallel.sharding import BATCH_AXES, ShardingLayout, build_mesh


def _sanitize_enabled() -> bool:
    """Local alias kept import-lazy: the sanitizers module pulls in the
    analysis package, which mesh must not pay for on the hot import path."""
    return os.environ.get("SHEEPRL_SANITIZE", "").strip().lower() in ("1", "true", "yes", "on")


_PRECISIONS = ("32-true", "bf16-mixed", "bf16-true")
_STRATEGIES = ("auto", "dp", "ddp", "fsdp")
_PLAYER_DEVICES = ("auto", "cpu", "accelerator")


class MeshRuntime:
    """Owns device selection, the device mesh, dtype policy and RNG keys."""

    def __init__(
        self,
        devices: int = 1,
        num_nodes: int = 1,
        strategy: str = "auto",
        accelerator: str = "auto",
        precision: str = "32-true",
        player_device: str = "auto",
        player_params_cutoff_mb: float = 4.0,
        mesh_shape: Any = "auto",
        **kwargs: Any,
    ):
        if precision not in _PRECISIONS:
            raise ValueError(f"precision must be one of {_PRECISIONS}, got '{precision}'")
        if strategy not in _STRATEGIES:
            raise ValueError(f"strategy must be one of {_STRATEGIES}, got '{strategy}'")
        if player_device not in _PLAYER_DEVICES:
            raise ValueError(
                f"player_device must be one of {_PLAYER_DEVICES}, got '{player_device}'"
            )
        self._requested_devices = devices
        self._num_nodes = num_nodes
        self._strategy = strategy
        self._accelerator = accelerator
        self._precision = precision
        self._player_device = player_device
        self._player_cutoff_mb = float(player_params_cutoff_mb)
        self._mesh_shape = mesh_shape
        self._player_choice_logged = False
        self._launched = False
        self._mesh: Optional[Mesh] = None
        self._layout: Optional[ShardingLayout] = None
        self._key: Optional[jax.Array] = None

    # ------------------------------------------------------------------ #
    # device / mesh setup
    # ------------------------------------------------------------------ #
    def _resolve_backend(self) -> str:
        if self._accelerator in ("auto", None):
            return jax.default_backend()
        if self._accelerator in ("tpu", "cpu", "gpu"):
            return self._accelerator
        raise ValueError(f"Unknown accelerator '{self._accelerator}'")

    def launch(self) -> "MeshRuntime":
        """Initialize (multi-host if configured) runtime and build the mesh.

        Unlike Fabric there is no process spawning: SPMD means one python
        process per host drives all local devices.
        """
        if self._launched:
            return self
        # persistent XLA compilation cache: repeat runs skip the multi-second
        # compile of the jitted train/policy steps
        try:
            cache_dir = os.environ.get(
                "SHEEPRL_COMPILATION_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "sheeprl_tpu_xla")
            )
            if cache_dir and cache_dir.lower() != "off":
                os.makedirs(cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", cache_dir)
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        except Exception:
            pass
        # NOTE: the guard must not call jax.process_count() — that would
        # initialize the XLA backend, after which distributed.initialize()
        # refuses to run
        if self._num_nodes > 1 and not jax.distributed.is_initialized():
            # multi-host rendezvous. Cloud TPU / SLURM / MPI environments
            # auto-detect coordinator settings; plain CPU/GPU clusters (and
            # the 2-process test in tests/test_parallel) pass them
            # explicitly via SHEEPRL_COORDINATOR_ADDRESS / _NUM_PROCESSES /
            # _PROCESS_ID.  Counterpart of the reference's
            # TorchCollective.setup + env:// init (SURVEY.md §5.8).
            init_kwargs = {}
            addr = os.environ.get("SHEEPRL_COORDINATOR_ADDRESS")
            if addr:
                missing = [
                    k
                    for k in ("SHEEPRL_NUM_PROCESSES", "SHEEPRL_PROCESS_ID")
                    if k not in os.environ
                ]
                if missing:
                    raise RuntimeError(
                        "SHEEPRL_COORDINATOR_ADDRESS is set but "
                        + " and ".join(missing)
                        + " is not; the three variables must be set together "
                        "for an explicit multi-host rendezvous."
                    )
                init_kwargs = dict(
                    coordinator_address=addr,
                    num_processes=int(os.environ["SHEEPRL_NUM_PROCESSES"]),
                    process_id=int(os.environ["SHEEPRL_PROCESS_ID"]),
                )
            jax.distributed.initialize(**init_kwargs)
        backend = self._resolve_backend()
        try:
            devices = jax.devices(backend)
        except RuntimeError:
            devices = jax.devices()
        n = self._requested_devices
        if n in (-1, "auto", None):
            n = len(devices)
        n = int(n)
        if n > len(devices):
            raise RuntimeError(f"Requested {n} devices but only {len(devices)} are available")
        devices = devices[:n]

        # Two mesh axes (parallel/sharding.py): batches shard over the
        # flattened ("data", "fsdp") axes — every device is a DP worker —
        # while params/opt-state replicate under dp and shard ZeRO-style
        # over "fsdp" under ``strategy=fsdp``.  ``mesh_shape=auto``
        # reproduces the pre-2-D layouts bit-exactly (all devices on one
        # axis); explicit ``[d, f]`` shapes lay a pod as d-way data x
        # f-way param sharding, with jit lowering the cross-shard
        # reductions to ``jax.lax`` collectives over ICI/DCN.
        self._mesh = build_mesh(devices, self._mesh_shape, self._strategy)
        self._layout = ShardingLayout(self._mesh)
        self._launched = True
        return self

    @property
    def mesh(self) -> Mesh:
        if not self._launched:
            self.launch()
        return self._mesh

    @property
    def world_size(self) -> int:
        """Number of data-parallel workers (batch shards) — the flattened
        (data x fsdp) device count: the batch sharding always covers both
        axes, so every device owns a batch shard."""
        return self.layout.n_shards

    @property
    def layout(self) -> ShardingLayout:
        """Canonical PartitionSpec vocabulary for this mesh."""
        if not self._launched:
            self.launch()
        return self._layout

    @property
    def data_size(self) -> int:
        return self.layout.data_size

    @property
    def fsdp_size(self) -> int:
        return self.layout.fsdp_size

    @property
    def device_count(self) -> int:
        return len(self.mesh.devices.ravel())

    @property
    def global_rank(self) -> int:
        return jax.process_index()

    @property
    def node_rank(self) -> int:
        return jax.process_index()

    @property
    def is_global_zero(self) -> bool:
        return jax.process_index() == 0

    @property
    def strategy(self) -> str:
        return self._strategy

    @property
    def precision(self) -> str:
        return self._precision

    @property
    def device(self):
        return self.mesh.devices.ravel()[0]

    # ------------------------------------------------------------------ #
    # dtype policy
    # ------------------------------------------------------------------ #
    @property
    def compute_dtype(self):
        return jnp.float32 if self._precision == "32-true" else jnp.bfloat16

    @property
    def param_dtype(self):
        return jnp.bfloat16 if self._precision == "bf16-true" else jnp.float32

    def to_param_dtype(self, tree: Any, exclude: Tuple[str, ...] = ()) -> Any:
        """Cast float32 leaves to the parameter STORAGE dtype.

        Under ``bf16-true`` parameters live in bfloat16 — half the HBM
        footprint and half the weight traffic on bandwidth-bound paths
        (e.g. the RSSM scan's per-step matmuls) — while flax modules
        promote them to each module's compute dtype on use, and the
        optimizer keeps an f32 master copy
        (``sheeprl_tpu.optim.master_weights``).  Dict keys in ``exclude``
        match at ANY nesting depth (e.g. an EMA ``target_critic`` at the
        top level, or each ensemble member's ``target_module`` inside
        p2e's ``critics_exploration``): the whole subtree under a matched
        key keeps f32 storage — EMA targets' small per-step updates would
        drown in bf16 rounding.  No-op for other precisions, so call
        sites are unconditional."""
        if self.param_dtype == jnp.float32:
            return tree
        cast = lambda t: jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if getattr(x, "dtype", None) == jnp.float32
            else x,
            t,
        )
        if not exclude:
            return cast(tree)
        ex = frozenset(exclude)

        def rec(node):
            if isinstance(node, dict):
                return {k: (v if k in ex else rec(v)) for k, v in node.items()}
            return cast(node)

        return rec(tree)

    # ------------------------------------------------------------------ #
    # RNG
    # ------------------------------------------------------------------ #
    def seed_everything(self, seed: int) -> jax.Array:
        """Seed python/numpy and derive the root PRNG key (replaces Fabric's
        seed_everything + torch cudnn flags).

        ``next_key`` draws raw uint32 key DATA from a seeded host-side
        numpy stream: generating keys costs microseconds, while any eager
        jax op in the env hot loop pays a per-dispatch toll (and, on
        tunneled-TPU setups, a device round trip per step)."""
        random.seed(seed)
        np.random.seed(seed)
        os.environ["PYTHONHASHSEED"] = str(seed)
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(seed)
        self._np_key_rng = np.random.Generator(np.random.PCG64(seed))
        return self._key

    def reseed_key_stream(self, salt: int) -> None:
        """Re-derive the host key stream deterministically from the run
        seed and ``salt`` (the sentinel's rollback ordinal): after a
        rollback-to-last-good, replaying the exact keys would re-draw the
        same sample indices/noise that fed the anomaly."""
        base = int(getattr(self, "_seed", 0) or 0)
        self._np_key_rng = np.random.Generator(np.random.PCG64([base, 0x5E47, int(salt)]))

    def next_key(self, num: int = 1):
        """Fresh independent PRNG keys for the host-side loop (jitted code
        threads keys explicitly). Raw uint32[2] key data drawn from a seeded
        host RNG — no device computation per call."""
        if self._key is None:
            self.seed_everything(0)
        data = self._np_key_rng.integers(0, 2**32, size=(num, 2), dtype=np.uint32)
        # retain the buffer until the NEXT draw: keys are usually passed as
        # call-expression temporaries, and CPU device_put may zero-copy
        # alias the numpy memory — freeing it before the async consumer
        # executes lets the allocator recycle it mid-computation
        self._live_key = data
        # returned as UNCOMMITTED numpy key data: jit places it with the
        # computation (replicated over the mesh for train steps, pinned by
        # the player's device_put for the env hot loop)
        return data[0] if num == 1 else [row for row in data]

    # ------------------------------------------------------------------ #
    # shardings
    # ------------------------------------------------------------------ #
    def sharding(self, *axes: Optional[str]) -> NamedSharding:
        """NamedSharding with the given axis names over array dims."""
        return NamedSharding(self.mesh, P(*axes))

    def ddp_gate(self, batch_axis_size: int, algo: str = "") -> bool:
        """Whether a rank-local DDP ``shard_map`` core applies: multi-device,
        evenly divisible batch axis, and replicated (non-fsdp) params — the
        shard_map cores declare params/opt-state replicated, which would
        all-gather and destroy a ZeRO (fsdp) layout.  When it returns False
        on a multi-device mesh, warns that the update runs on the
        replicated GSPMD fallback (correct, but every device computes the
        FULL update) — except under fsdp, where the GSPMD path with the
        layout constraints IS the intended ZeRO program, not a fallback.
        One gate shared by ppo/a2c/ppo_recurrent/sac/droq so the fsdp
        guard and the warning cannot drift per algo."""
        if self.world_size == 1:
            return False
        if self._strategy == "fsdp":
            # not a fallback: the jit path with guard_update's boundary
            # constraints lowers to the ZeRO all-gather/reduce-scatter
            # program — silence here, the layout is by design
            return False
        if batch_axis_size % self.world_size == 0:
            return True
        import warnings

        warnings.warn(
            f"multi-device {algo or 'train'} update falling back to the replicated GSPMD "
            f"path (correct, but every device computes the FULL update — no DP speedup): "
            f"batch axis {batch_axis_size} is not divisible by world_size={self.world_size}."
        )
        return False

    def batch_sharding(self, axis: int = 0) -> NamedSharding:
        """Sharding that splits ``axis`` over the flattened batch axes
        (data x fsdp — one shard per device; pass to device_put /
        DevicePrefetcher so batches land already distributed)."""
        return self.layout.batch_sharding(axis)

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_batch(self, batch: Any, axis: int = 0) -> Any:
        """device_put a host pytree, splitting ``axis`` over the data axis.

        Every leaf's ``axis`` dim must be divisible by world_size.
        """
        if _sanitize_enabled():
            from sheeprl_tpu.analysis.sanitizers import check_host_sources

            check_host_sources(batch, "shard_batch")
        return jax.device_put(batch, self.batch_sharding(axis))

    def replicate(self, tree: Any) -> Any:
        """Place params/opt-state on the mesh.

        Default strategies replicate every leaf. Under ``strategy="fsdp"``
        each leaf is sharded over the **fsdp** axis on its LARGEST
        dimension divisible by the axis size (scalars and indivisible
        leaves stay replicated): the ZeRO-3 layout, with XLA inserting the
        weight all-gathers and gradient reduce-scatters during jit.  The
        per-leaf rule lives in :meth:`ShardingLayout.param_spec` so the
        in-jit boundary constraints agree with this placement by
        construction."""
        if _sanitize_enabled():
            from sheeprl_tpu.analysis.sanitizers import check_host_sources

            check_host_sources(tree, "replicate")
        if self._strategy != "fsdp" or self.fsdp_size == 1:
            if self._strategy == "fsdp" and self.world_size > 1:
                import warnings

                warnings.warn(
                    "strategy=fsdp with a size-1 'fsdp' mesh axis keeps params "
                    "replicated (plain DP); set fabric.mesh_shape to give the "
                    "fsdp axis a real size (auto puts every device on it)."
                )
            return jax.device_put(tree, self.replicated)
        layout = self.layout
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, layout.param_sharding(leaf)), tree
        )

    def mesh_telemetry(self, params: Any = None, compiled: Any = None) -> Dict[str, Any]:
        """The telemetry record's ``mesh`` key (howto/observability.md):
        axis names/sizes, the achieved per-device FSDP param-shard bytes
        (when ``params`` is passed), and a best-effort per-update
        cross-device traffic estimate from ``Compiled.cost_analysis()``
        (when a compiled update is passed)."""
        out: Dict[str, Any] = dict(self.layout.describe())
        out["strategy"] = self._strategy
        # extras stashed by the first guarded-update dispatch (sentinel.py):
        # param bytes, FSDP shard bytes, opt-in collective-bytes estimate
        out.update(getattr(self, "_mesh_extra", None) or {})
        if params is not None:
            total = self._player_params_nbytes(params)
            out["param_bytes_total"] = int(total)
            if self._strategy == "fsdp" and self.fsdp_size > 1:
                out["param_bytes_per_device"] = self.layout.param_shard_bytes(params)
        if compiled is not None:
            from sheeprl_tpu.parallel.sharding import collective_bytes_estimate

            est = collective_bytes_estimate(compiled)
            if est is not None:
                out["collective_bytes_estimate"] = est
        return out

    def setup_step(
        self,
        fn: Callable,
        donate_argnums: Tuple[int, ...] = (),
        static_argnums: Tuple[int, ...] = (),
    ) -> Callable:
        """jit-compile a step function under this mesh.

        With inputs placed via ``shard_batch``/``replicate``, XLA lays out
        the computation SPMD over the mesh and inserts the cross-device
        collectives (the DDP grad all-reduce equivalent) automatically.
        """
        from sheeprl_tpu.utils.jax_compat import set_mesh

        jitted = jax.jit(fn, donate_argnums=donate_argnums, static_argnums=static_argnums)

        def wrapped(*args, **kw):
            with set_mesh(self.mesh):
                return jitted(*args, **kw)

        wrapped._jitted = jitted
        if donate_argnums and _sanitize_enabled():
            # donation sanitizer (SHEEPRL_SANITIZE=1): deletes/poisons the
            # donated inputs after each dispatch so a use-after-donate
            # fails deterministically at the offending line on EVERY
            # backend — on CPU/GPU unhonored donation otherwise turns the
            # same bug into timing-dependent memory recycling.  Off path:
            # this branch is never entered, the returned callable is the
            # exact pre-sanitizer object (zero overhead).
            from sheeprl_tpu.analysis.sanitizers import guard_donation

            return guard_donation(wrapped, donate_argnums, where=getattr(fn, "__name__", "step"))
        return wrapped

    # ------------------------------------------------------------------ #
    # single-device view (players / target critics)
    # ------------------------------------------------------------------ #
    def single_device(self) -> "MeshRuntime":
        """A 1-device runtime on the same backend (reference
        utils/fabric.py:8-35): used for env-interaction players so inference
        never pays mesh collectives."""
        rt = MeshRuntime(
            devices=1,
            num_nodes=1,
            strategy="auto",
            accelerator=self._accelerator,
            precision=self._precision,
        )
        rt.launch()
        rt._key = self._key
        return rt

    def _device_is_remote(self) -> bool:
        """True when the training device sits behind a network tunnel
        (remote PJRT plugins like axon report a plain accelerator
        ``platform`` but stamp the plugin into ``platform_version``)."""
        version = str(getattr(self.device.client, "platform_version", "")).lower()
        platforms = str(getattr(jax.config, "jax_platforms", "") or "").lower()
        return any(marker in version or marker in platforms for marker in ("axon", "proxy"))

    def player_device(self, params: Any = None):
        """Device for env-interaction policies.

        "auto"/"cpu" (default): the host CPU backend when training runs on
        an accelerator — the env hot loop then avoids a device round trip
        per step (tiny policy nets, CPU-actor/TPU-learner split).
        "accelerator": keep the player on the training device — the right
        call when the accelerator sits behind a high-latency link, where
        re-downloading the params tree to the host after every train
        dispatch costs seconds per leaf. Configured via
        ``fabric.player_device``; the SHEEPRL_PLAYER_DEVICE env var
        overrides the config.

        ``params`` (the player's weight pytree, when the caller has it)
        lets "auto" weigh the two costs on tunneled accelerators: a
        CPU player re-downloads those weights after every training
        iteration (measured ~3-4 s/iter for DreamerV3-S's ~40 MB at
        ~33 MB/s link bandwidth — 5x the rest of the loop), while an
        on-accelerator player pays one action-fetch RTT (~0.1 s) per env
        step. Big trees (world models) therefore stay on the training
        device; small ones (PPO/SAC MLPs, whose refresh is a few hundred
        KB per rollout) stay on the CPU where actions are free."""
        choice = os.environ.get("SHEEPRL_PLAYER_DEVICE", self._player_device)
        if choice not in _PLAYER_DEVICES:
            raise ValueError(
                f"player_device must be one of {_PLAYER_DEVICES}, got '{choice}'"
            )
        device, why = self._player_device_decision(choice, params)
        if not self._player_choice_logged:
            # the heuristic is load-bearing (a wrong pick costs ~5x loop
            # throughput on tunneled links) — make the decision visible once
            self._player_choice_logged = True
            self.print(f"Player device: {device if device is not None else 'training device'} ({why})")
        return device

    def _player_params_nbytes(self, params: Any) -> int:
        return sum(
            int(np.prod(np.shape(leaf))) * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
            for leaf in jax.tree_util.tree_leaves(params)
        )

    def _player_device_decision(self, choice: str, params: Any):
        """(device-or-None, reason) per the decision table; None = stay on
        the training device.  Pure given (choice, backend platform,
        remoteness, params size) — pinned by tests/test_parallel/test_mesh.py."""
        cutoff_mb = float(os.environ.get("SHEEPRL_PLAYER_CUTOFF_MB", self._player_cutoff_mb))
        if choice == "accelerator":
            return None, "player_device=accelerator"
        if self.device.platform == "cpu":
            return None, "training backend is already the host CPU"
        if choice == "auto" and self._device_is_remote():
            if params is None:
                return None, "remote link + unknown params size: assume refresh-heavy"
            nbytes = self._player_params_nbytes(params)
            if nbytes >= cutoff_mb * 1024 * 1024:
                return None, (
                    f"remote link + params {nbytes / 1e6:.1f} MB >= cutoff {cutoff_mb} MB: "
                    "per-iteration weight refresh would dominate"
                )
            why = f"remote link + params {nbytes / 1e6:.1f} MB < cutoff {cutoff_mb} MB"
        elif choice == "cpu":
            why = "player_device=cpu (explicit; size gate bypassed)"
        else:
            why = "local accelerator: host CPU actions are free"
        try:
            return jax.local_devices(backend="cpu")[0], why
        except RuntimeError:
            return None, "no host CPU backend available"

    # ------------------------------------------------------------------ #
    # host-side collectives (metrics, small objects)
    # ------------------------------------------------------------------ #
    def all_gather_object(self, obj: Any) -> list:
        """Gather a picklable object from every process (multi-host); on a
        single process returns [obj]. Replacement for TorchCollective
        broadcast/gather of config/metric dicts."""
        if jax.process_count() == 1:
            return [obj]
        import pickle

        from jax.experimental import multihost_utils

        # process_allgather only moves numeric arrays, so arbitrary objects
        # ride as pickled uint8 payloads padded to the global max length
        # (same trick as torch.distributed.all_gather_object)
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        sizes = np.asarray(multihost_utils.process_allgather(np.asarray([payload.size]))).reshape(-1)
        padded = np.zeros((int(sizes.max()),), np.uint8)
        padded[: payload.size] = payload
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        return [pickle.loads(gathered[i, : int(sizes[i])].tobytes()) for i in range(len(sizes))]

    def barrier(self) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("sheeprl_tpu_barrier")

    def print(self, *args: Any, **kwargs: Any) -> None:
        if self.is_global_zero:
            print(*args, **kwargs)
