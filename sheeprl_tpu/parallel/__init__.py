from sheeprl_tpu.parallel.mesh import MeshRuntime

__all__ = ["MeshRuntime"]
