from sheeprl_tpu.parallel.mesh import MeshRuntime
from sheeprl_tpu.parallel.pipeline import (
    KeyStream,
    OnPolicyCollector,
    PipelinedCollector,
    RolloutPayload,
    credit_timer,
    detach_copy,
)
from sheeprl_tpu.parallel.shm_ring import (
    ShmArena,
    ShmReceiver,
    ShmSender,
    decoupled_transport_setting,
)

__all__ = [
    "MeshRuntime",
    "KeyStream",
    "OnPolicyCollector",
    "PipelinedCollector",
    "RolloutPayload",
    "credit_timer",
    "detach_copy",
    "ShmArena",
    "ShmReceiver",
    "ShmSender",
    "decoupled_transport_setting",
]
