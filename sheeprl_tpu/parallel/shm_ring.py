"""Zero-copy rollout transport for the decoupled topologies.

The decoupled PPO/SAC pairs originally shipped every rollout (and every
params refresh) as a pickled ``multiprocessing.Queue`` payload: pickle
serializes each array into the pipe, the OS copies the bytes through a
socketpair, and the receiver deserializes into fresh allocations — three
copies plus a feeder-thread hop per direction per iteration.  BENCH_r05
measured decoupled PPO at 0.319x coupled on this host, the opposite of
the topology's purpose.

This module replaces the payload path with a POSIX shared-memory ring:

- a :class:`ShmArena` is one ``multiprocessing.shared_memory`` segment
  divided into ``n_slots`` fixed-size slots (sized once from the first
  payload's byte count plus headroom — the rollout spec is fixed for
  on-policy loops and bounded for SAC's ratio-granted batches);
- the WRITER packs a payload's arrays back-to-back into a free slot (one
  memcpy) and sends only **metadata** over the existing control queue:
  slot index + per-array ``(key, shape, dtype, offset)`` — the queue
  pickle stays O(100) bytes regardless of rollout size (the pickle-5
  out-of-band idea: buffers ride the segment, the pickled message is
  pure metadata);
- the READER maps the segment once and reconstructs zero-copy numpy
  views; it returns the slot via a pre-seeded free-slot queue after the
  payload has been consumed (flow control = ring occupancy);
- payloads that do not fit a slot fall back to the plain pickled-queue
  path transparently (``ShmSender.send`` returns False), so a burst
  (e.g. SAC's first ratio grant after ``learning_starts``) degrades
  gracefully instead of failing;
- cleanup is two-sided: both endpoints ``close()`` their mapping and
  attempt ``unlink`` (idempotent) in their teardown paths, so a reader
  OR writer death leaves no orphaned ``/dev/shm`` segment behind — the
  surviving side unlinks on its own exit.

Config: ``algo.decoupled_transport`` (``shm`` default / ``queue``), env
override ``SHEEPRL_DECOUPLED_TRANSPORT``.
"""

from __future__ import annotations

import atexit
import os
import secrets
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ShmArena", "ShmSender", "ShmReceiver", "decoupled_transport_setting"]


def decoupled_transport_setting(cfg) -> str:
    """Resolve ``algo.decoupled_transport`` with its env override to
    "shm", "queue" or "tcp" (kept for backward compatibility — the
    canonical resolver is :func:`sheeprl_tpu.parallel.transport.transport_setting`)."""
    val = cfg.algo.get("decoupled_transport", "shm")
    env = os.environ.get("SHEEPRL_DECOUPLED_TRANSPORT")
    if env is not None:
        val = env
    s = str(val).lower()
    if s in ("queue", "pickle", "off", "0", "false", "no"):
        return "queue"
    if s in ("tcp", "socket", "net"):
        return "tcp"
    return "shm"


def _payload_nbytes(arrays: Sequence[Tuple[str, np.ndarray]]) -> int:
    return sum(int(a.nbytes) for _, a in arrays)


class ShmArena:
    """One shared-memory segment of ``n_slots`` fixed-size slots.

    Create on the writer side with :meth:`create`; attach on the reader
    side with :meth:`attach` using the writer's :attr:`info` (a tiny
    picklable dict that rides the control queue).
    """

    def __init__(self, shm: shared_memory.SharedMemory, n_slots: int, slot_bytes: int, owner: bool):
        self._shm = shm
        self.n_slots = int(n_slots)
        self.slot_bytes = int(slot_bytes)
        self._owner = owner
        self._closed = False
        # leak accounting (analysis/sanitizers.py): a segment that never
        # reaches close() shows up by NAME in the suite-wide sweep (and,
        # independently, as a /dev/shm orphan)
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        self._leak_token = leak_registry.register(
            "shm", shm.name, self, where="owner" if owner else "attached"
        )
        # belt-and-braces: a process killed by an unhandled exception still
        # unlinks (SIGKILL can't run this — the surviving peer's close does)
        atexit.register(self.close)

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def create(cls, n_slots: int, slot_bytes: int) -> "ShmArena":
        if n_slots < 1 or slot_bytes < 1:
            raise ValueError(f"need n_slots>=1 and slot_bytes>=1, got {n_slots}x{slot_bytes}")
        name = f"sheeprl_ring_{os.getpid():x}_{secrets.token_hex(4)}"
        shm = shared_memory.SharedMemory(create=True, size=n_slots * slot_bytes, name=name)
        return cls(shm, n_slots, slot_bytes, owner=True)

    @classmethod
    def attach(cls, info: Dict[str, Any]) -> "ShmArena":
        shm = shared_memory.SharedMemory(name=info["name"])
        return cls(shm, info["n_slots"], info["slot_bytes"], owner=False)

    @property
    def info(self) -> Dict[str, Any]:
        return {"name": self._shm.name, "n_slots": self.n_slots, "slot_bytes": self.slot_bytes}

    def close(self) -> None:
        """Close the local mapping and try to unlink the segment.

        Unlink is attempted from BOTH endpoints (first wins, the second
        sees FileNotFoundError): on Linux the segment stays usable for
        already-attached processes until the last close, and this way a
        single surviving endpoint is enough to avoid an orphan.
        """
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.close()
        except BufferError:
            # zero-copy views of a slot are still alive somewhere; the
            # mapping stays until they die (SharedMemory.__del__ retries),
            # but the NAME can and must still be unlinked below
            pass
        except (OSError, ValueError):
            pass
        try:
            self._shm.unlink()
        except (FileNotFoundError, OSError, ValueError):
            pass
        try:
            atexit.unregister(self.close)
        except Exception:
            pass
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        leak_registry.unregister(getattr(self, "_leak_token", None))
        self._leak_token = None

    # ------------------------------------------------------------- pack/read
    def pack(self, slot: int, arrays: Sequence[Tuple[str, np.ndarray]]) -> Optional[List[Tuple]]:
        """Copy ``arrays`` back-to-back into ``slot``; returns the leaves
        metadata ``[(key, shape, dtype_str, offset), ...]`` or None when
        the payload does not fit (caller falls back to the queue path)."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range 0..{self.n_slots - 1}")
        base = slot * self.slot_bytes
        off = 0
        leaves: List[Tuple] = []
        buf = self._shm.buf
        for key, arr in arrays:
            arr = np.ascontiguousarray(arr)
            if arr.dtype == object:
                return None
            nbytes = int(arr.nbytes)
            if off + nbytes > self.slot_bytes:
                return None
            if nbytes:
                dst = np.frombuffer(buf, dtype=np.uint8, count=nbytes, offset=base + off)
                dst[:] = arr.view(np.uint8).reshape(-1)
            leaves.append((key, tuple(arr.shape), str(arr.dtype), off))
            off += nbytes
        return leaves

    def region(self, slot: int, nbytes: int) -> memoryview:
        """Raw byte view of ``slot``'s first ``nbytes`` — the integrity
        layer's receive-side fast path (one contiguous checksum instead
        of a per-leaf walk; resilience/integrity.py:region_digest)."""
        base = slot * self.slot_bytes
        return memoryview(self._shm.buf)[base : base + int(nbytes)]

    def unpack(self, slot: int, leaves: Sequence[Tuple], copy: bool = False) -> Dict[str, np.ndarray]:
        """Rebuild the payload from ``slot``.  ``copy=False`` returns
        zero-copy views INTO the slot — valid only until the slot is
        released; ``copy=True`` materializes private arrays."""
        base = slot * self.slot_bytes
        out: Dict[str, np.ndarray] = {}
        for key, shape, dtype, off in leaves:
            dt = np.dtype(dtype)
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            view = np.frombuffer(self._shm.buf, dtype=dt, count=count, offset=base + off).reshape(shape)
            out[key] = np.array(view) if copy else view
        return out


class ShmSender:
    """Writer endpoint: lazily sizes the arena from the first payload and
    ships subsequent payloads as metadata-only queue messages.

    ``free_q`` must be an ``mp.Queue`` created by the process that spawned
    both endpoints (queues cannot ride other queues); the sender seeds it
    with the slot indices when the arena is created.
    """

    def __init__(self, free_q, n_slots: int = 2, headroom: float = 1.5, min_bytes: int = 65536):
        self._free_q = free_q
        self._n_slots = int(n_slots)
        self._headroom = float(headroom)
        self._min_bytes = int(min_bytes)
        self._arena: Optional[ShmArena] = None
        self._disabled = False
        self.fallbacks = 0  # payloads that did not fit and went over the queue
        # wire-format v2 hook (parallel/transport.py): maps the packed
        # leaves to a cached-table reference before they ride the control
        # queue; None ships the full per-leaf list (v1)
        self.encode_leaves = None

    def _ensure_arena(self, arrays: Sequence[Tuple[str, np.ndarray]]) -> None:
        if self._arena is not None or self._disabled:
            return
        nbytes = _payload_nbytes(arrays)
        if nbytes < self._min_bytes:
            # adaptive gate, decided once on the first (spec-sized) payload:
            # below ~64 KB the ring's extra free-slot queue round trip per
            # send costs more than pickling the payload outright (measured
            # 0.85x on KB-scale CartPole rollouts), so small-payload pairs
            # keep the legacy path and the ring engages only where
            # zero-copy pays — pixel rollouts, big batches, params trees
            self._disabled = True
            return
        slot_bytes = max(int(nbytes * self._headroom), 4096)
        self._arena = ShmArena.create(self._n_slots, slot_bytes)
        for i in range(self._n_slots):
            self._free_q.put(i)

    def send(self, put_fn, tag: str, arrays: Sequence[Tuple[str, np.ndarray]], extra: Tuple, acquire_slot) -> bool:
        """Pack ``arrays`` into a free slot and ``put_fn`` the metadata
        message ``(tag, arena_info, slot, leaves, *extra)``.

        ``acquire_slot()`` blocks for a free slot (callers wrap the free
        queue with their peer-liveness helper).  Returns False when the
        payload does not fit the slot OR the sender decided the payload
        class is too small for the ring to pay (``min_bytes``) — the
        caller sends its legacy pickled message instead (nothing was
        consumed: any briefly-held slot is returned).
        """
        self._ensure_arena(arrays)
        if self._arena is None:  # small-payload pair: ring disabled
            self.fallbacks += 1
            return False
        slot = acquire_slot()
        leaves = self._arena.pack(slot, arrays)
        if leaves is None:
            self._free_q.put(slot)  # slot unused; hand it back
            self.fallbacks += 1
            return False
        if self.encode_leaves is not None:
            leaves = self.encode_leaves(leaves)
        put_fn((tag, self._arena.info, slot, leaves) + tuple(extra))
        return True

    def close(self) -> None:
        if self._arena is not None:
            self._arena.close()
            self._arena = None


class ShmReceiver:
    """Reader endpoint: attaches from the first message's arena info and
    reconstructs payload views; ``release`` returns the slot."""

    def __init__(self, free_q):
        self._free_q = free_q
        self._arena: Optional[ShmArena] = None

    def unpack(self, info: Dict[str, Any], slot: int, leaves: Sequence[Tuple], copy: bool = False):
        if self._arena is None or self._arena.info["name"] != info["name"]:
            if self._arena is not None:
                self._arena.close()
            self._arena = ShmArena.attach(info)
        return self._arena.unpack(slot, leaves, copy=copy)

    def region(self, slot: int, nbytes: int) -> Optional[memoryview]:
        """Contiguous byte view of an attached slot (integrity layer)."""
        return self._arena.region(slot, nbytes) if self._arena is not None else None

    def release(self, slot: int) -> None:
        self._free_q.put(slot)

    def close(self) -> None:
        if self._arena is not None:
            self._arena.close()
            self._arena = None
