"""Pluggable rollout transport + N-player fan-in for the decoupled topologies.

The decoupled PPO/SAC pairs were hard-wired to exactly ONE player process
feeding one trainer over same-host ``multiprocessing`` primitives, so
aggregate env throughput could never scale past a single host's cores no
matter how fast the trainer got (BENCH_r05: the trainer starved at 0.573x
coupled on 1 host core).  IMPALA (Espeholt et al., 2018) and SEED RL
(Espeholt et al., 2020) establish the fix — many actor processes
streaming rollouts into one centralized learner — and this module
supplies the plumbing:

- :class:`Channel` — one duplex player<->trainer link with a uniform
  frame API (``send(tag, arrays, extra, seq)`` / ``recv() -> Frame``)
  over three interchangeable backends (``algo.decoupled_transport``):

  * ``queue`` — the legacy pickled ``mp.Queue`` pair, now BOUNDED so a
    fast sender backpressures instead of ballooning the pipe;
  * ``shm``   — the PR-3 SharedMemory ring (zero-copy payloads, queue
    messages carry metadata only, ring occupancy = flow control);
  * ``tcp``   — NEW: a socket stream of length-prefixed frames with
    ``recv_into`` preallocated buffers, credit-window backpressure and
    an optional compression gate.  Works on localhost today and across
    hosts unchanged (``algo.tcp_host``/``algo.tcp_port``).

- :class:`TcpListener` — the trainer's accept endpoint: players identify
  themselves with a hello frame, and a player that loses its connection
  reconnects with exponential backoff and is re-adopted in place (the
  trainer resends its last params broadcast; both directions dedupe by
  ``(tag, seq)``, so a frame lost mid-flight is retried, never skipped).

- :class:`FanIn` — the trainer-side N-player assembly: one ``data``
  frame per alive player per round, deterministic arrival-order-
  independent layout (shards concatenated in player-id order), per-player
  liveness, and graceful degradation — a crashed player SHRINKS the
  fan-in (recorded in the transport stats that ride telemetry) instead of
  killing the run; only the death of the LAST player is fatal.

- :class:`ParamsFollower` — the player-side half of the seq-numbered
  trainer->players params broadcast: rollout k acts on EXACTLY the params
  of update ``k - 1 - lag`` (``algo.decoupled_params_lag``), reusing
  PR 3's fixed-lag idea so per-player staleness is bounded AND
  deterministic (never a race on "whatever arrived last").
"""

from __future__ import annotations

import os
import pickle
import queue as queue_mod
import socket
import struct
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.obs import flight
from sheeprl_tpu.parallel import wire
from sheeprl_tpu.parallel.shm_ring import ShmReceiver, ShmSender
from sheeprl_tpu.parallel.wire import COAL_TAG, WireFormatError, wire_setting
from sheeprl_tpu.replay.service import RB_CREDIT_TAG, RB_INSERT_TAG
from sheeprl_tpu.resilience.faults import get_injector, maybe_drop_or_delay_send
from sheeprl_tpu.resilience.integrity import (
    FrameCorruptError,
    content_digest,
    default_coverage,
    integrity_stats,
    maybe_bit_flip,
    maybe_bit_flip_region,
    region_digest,
    stream_digest,
)
from sheeprl_tpu.resilience.peer import PeerDiedError, queue_get_from_peer

# frame-tag vocabulary over these channels: "init"/"data"/"params"/
# "ckpt_req"/"ckpt_state"/"stop" (the fan-in protocol) plus the replay
# service's RB_INSERT_TAG/RB_CREDIT_TAG (player→trainer raw-experience
# inserts and the trainer's rate-limiter credit grants; replay/service.py)
# and the inference service's INFER_REQ_TAG/INFER_REP_TAG (env-worker
# observation frames and the server's action replies; serve/service.py)
__all__ = [
    "Channel",
    "ChannelSpec",
    "CrcQueueChannel",
    "CrcShmChannel",
    "CrcTcpChannel",
    "FanIn",
    "Frame",
    "FrameCorruptError",
    "HB_TAG",
    "HeartbeatSender",
    "INFER_REP_TAG",
    "INFER_REQ_TAG",
    "JOIN_TAG",
    "ParamsFollower",
    "QueueChannel",
    "RB_CREDIT_TAG",
    "RB_INSERT_TAG",
    "ShmChannel",
    "TCP_MAX_FRAME_BYTES",
    "TcpChannel",
    "TcpListener",
    "TransportHub",
    "WireFormatError",
    "assemble_shards",
    "assemble_shards_padded",
    "make_transport",
    "split_envs",
    "transport_setting",
    "wire_channel_cls",
    "wire_setting",
]

# elastic-pool control tags: a (re)joining player announces itself with a
# JOIN_TAG frame and waits for the trainer's "assign" reply (env shard +
# round clock); HB_TAG frames are array-less liveness heartbeats a player
# thread emits so the supervisor can see silence, not just process death
JOIN_TAG = "join"
HB_TAG = "hb"

# inference-service tags (serve/): an env worker ships one observation
# frame per request (seq = its monotonic request id — the dedupe key on
# BOTH sides), the server answers with the action arrays under the same
# seq; late/duplicate replies drop by id, duplicate requests answer from
# the server's acted cache
INFER_REQ_TAG = "infer_req"
INFER_REP_TAG = "infer_rep"

_BACKENDS = ("queue", "shm", "tcp")


def transport_setting(cfg) -> str:
    """Resolve ``algo.decoupled_transport`` (env override
    ``SHEEPRL_DECOUPLED_TRANSPORT``) to one of ``queue|shm|tcp``."""
    val = cfg.algo.get("decoupled_transport", "shm")
    env = os.environ.get("SHEEPRL_DECOUPLED_TRANSPORT")
    if env is not None:
        val = env
    s = str(val).lower()
    if s in ("queue", "pickle", "off", "0", "false", "no"):
        return "queue"
    if s in ("tcp", "socket", "net"):
        return "tcp"
    return "shm"


def split_envs(total: int, num_players: int) -> List[Tuple[int, int]]:
    """Deterministic env sharding: ``[(offset, count), ...]`` per player,
    remainder distributed to the first players."""
    if num_players < 1:
        raise ValueError(f"num_players must be >= 1, got {num_players}")
    if total < num_players:
        raise ValueError(f"cannot split {total} envs across {num_players} players")
    base, rem = divmod(total, num_players)
    out, off = [], 0
    for p in range(num_players):
        n = base + (1 if p < rem else 0)
        out.append((off, n))
        off += n
    return out


def assemble_shards(
    arrays_by_pid: Dict[int, Dict[str, np.ndarray]], axis: int = 1
) -> Dict[str, np.ndarray]:
    """Concatenate per-player shards in PLAYER-ID order: the global batch
    layout depends only on which players contributed, never on shard
    arrival order."""
    pids = sorted(arrays_by_pid)
    first = arrays_by_pid[pids[0]]
    if len(pids) == 1:
        return dict(first)
    return {k: np.concatenate([arrays_by_pid[p][k] for p in pids], axis=axis) for k in first}


def assemble_shards_padded(
    arrays_by_pid: Dict[int, Dict[str, np.ndarray]],
    env_shards: Sequence[Tuple[int, int]],
    axis: int = 1,
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """Fixed-width fan-in assembly for the elastic pool: every key gets the
    FULL env-axis width (the sum of ALL shard counts, present or not) with
    each player's columns written at its deterministic ``env_shards``
    offset and missing players' columns zero-filled.  Returns
    ``(arrays, env_mask)`` where ``env_mask`` is a float32 ``(total,)``
    validity vector (1 = a live player contributed that column).

    The point is the SHAPE: a pool shrink or grow changes only the mask,
    never the batch layout, so the jitted update is traced once and never
    recompiles on churn (the pre-elastic concat-of-survivors assembly paid
    one full XLA retrace per pool-size change)."""
    if not arrays_by_pid:
        raise ValueError("assemble_shards_padded needs at least one shard")
    total = sum(count for _, count in env_shards)
    first = arrays_by_pid[min(arrays_by_pid)]
    out: Dict[str, np.ndarray] = {}
    for k, v in first.items():
        shape = list(v.shape)
        shape[axis] = total
        out[k] = np.zeros(shape, dtype=v.dtype)
    env_mask = np.zeros((total,), np.float32)
    for pid in sorted(arrays_by_pid):
        offset, count = env_shards[pid]
        idx = (slice(None),) * axis + (slice(offset, offset + count),)
        for k, v in arrays_by_pid[pid].items():
            out[k][idx] = v
        env_mask[offset : offset + count] = 1.0
    return out, env_mask


# --------------------------------------------------------------------- frames
class Frame:
    """One received transport message.

    ``arrays`` values may be VIEWS into transport-owned buffers (a shm
    slot, a tcp receive buffer): valid only until :meth:`release`.  Call
    sites that keep data past the release must copy (``np.array``).
    Array-less frames auto-release.
    """

    __slots__ = ("tag", "seq", "extra", "arrays", "_release_cb")

    def __init__(self, tag: str, seq: int = -1, extra: Tuple = (), arrays=None, release_cb=None):
        self.tag = tag
        self.seq = int(seq)
        self.extra = tuple(extra)
        self.arrays: Dict[str, np.ndarray] = arrays or {}
        self._release_cb = release_cb

    def release(self) -> None:
        cb, self._release_cb = self._release_cb, None
        if cb is not None:
            cb()

    def arrays_copy(self) -> Dict[str, np.ndarray]:
        return {k: np.array(v) for k, v in self.arrays.items()}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Frame({self.tag!r}, seq={self.seq}, keys={list(self.arrays)})"


class Channel:
    """One duplex link between a player and the trainer.

    ``peer_alive``/``who`` configure the liveness polling used by every
    blocking operation (see :func:`~sheeprl_tpu.resilience.peer.queue_get_from_peer`);
    the trainer attaches them after the spawn via :meth:`set_peer`.
    """

    def __init__(
        self,
        peer_alive: Optional[Callable[[], bool]] = None,
        who: str = "peer",
        poll_s: float = 0.5,
    ):
        self.peer_alive = peer_alive or (lambda: True)
        self.who = who
        # liveness poll cadence while blocked on the peer (the PR-2
        # hard-coded _PEER_POLL_S, now configurable: algo.liveness_interval)
        self.poll_s = float(poll_s)
        self.detail_fn: Optional[Callable[[], str]] = None
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.frames_sent = 0
        self.frames_recv = 0
        # per-stream accounting (ISSUE 19): which TAG dominates the wire,
        # not just "transport" — FanIn.stats merges these across channels
        # into the telemetry transport key for obs.top / critical-path
        self.bytes_by_tag: Dict[str, int] = {}
        self.frames_by_tag: Dict[str, int] = {}
        # adaptive tcp_compress: payloads whose probe page did not shrink
        # and therefore skipped the full zlib pass
        self.compress_skipped = 0
        # leak accounting (analysis/sanitizers.py): a channel that is never
        # close()d and never collected shows up in the suite-wide sweep
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        self._leak_token = leak_registry.register(
            "channel", type(self).__name__, self, where=who
        )

    def _leak_unregister(self) -> None:
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        leak_registry.unregister(getattr(self, "_leak_token", None))
        self._leak_token = None

    def set_peer(self, peer_alive, who: str, detail_fn=None) -> None:
        self.peer_alive = peer_alive
        self.who = who
        self.detail_fn = detail_fn

    # subclass API -----------------------------------------------------
    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0) -> None:
        raise NotImplementedError

    def recv(self, timeout: float) -> Frame:
        raise NotImplementedError

    def depth(self) -> Optional[int]:
        """Receive-side fan-in queue depth (None when unknowable)."""
        return None

    def reset_for_rejoin(self) -> None:
        """Clear dead-peer state ahead of a supervised player restart (the
        fresh process is about to take this endpoint over).  Base channels
        keep no such state."""

    def close(self) -> None:
        self._leak_unregister()

    # helpers ----------------------------------------------------------
    def _note_send(self, tag, nbytes: int) -> None:
        self.bytes_sent += nbytes
        self.frames_sent += 1
        if tag and not tag.startswith("__"):
            self.bytes_by_tag[tag] = self.bytes_by_tag.get(tag, 0) + nbytes
            self.frames_by_tag[tag] = self.frames_by_tag.get(tag, 0) + 1

    def _note_recv(self, tag, nbytes: int) -> None:
        self.bytes_recv += nbytes
        self.frames_recv += 1
        if tag and not tag.startswith("__"):
            self.bytes_by_tag[tag] = self.bytes_by_tag.get(tag, 0) + nbytes
            self.frames_by_tag[tag] = self.frames_by_tag.get(tag, 0) + 1

    def _count_payload(self, arrays, tag=None) -> int:
        n = sum(int(np.asarray(a).nbytes) for _, a in arrays) if arrays else 0
        self._note_send(tag, n)
        return n  # callers on the integrity path reuse this total


def _put_with_peer(q, item, timeout: float, peer_alive, who: str) -> None:
    """Bounded-queue put with peer-liveness polling (backpressure that
    notices a dead peer instead of hanging on a full pipe)."""
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise queue_mod.Full
        try:
            q.put(item, timeout=min(0.5, remaining))
            return
        except queue_mod.Full:
            if not peer_alive():
                raise PeerDiedError(who) from None


def _cancel_queue_join(q) -> None:
    """Detach an ``mp.Queue``'s feeder thread from interpreter exit.

    A peer that died mid-stream leaves buffered frames in the pipe that
    nobody will ever read; without this, ``multiprocessing``'s atexit
    finalizer joins the feeder thread — blocked forever in ``_send`` on
    the full pipe — and the WHOLE process hangs at shutdown (observed on
    the elastic-pool respawn path, which abandons the dead player's
    queue pair wholesale).  No-op for plain ``queue.Queue`` test doubles."""
    cancel = getattr(q, "cancel_join_thread", None)
    if cancel is not None:
        try:
            cancel()
        except (OSError, ValueError):
            pass


class QueueChannel(Channel):
    """Legacy pickled-queue backend over a BOUNDED ``mp.Queue`` pair."""

    _PICKLED = "__frame__"

    def __init__(self, send_q, recv_q, **kw):
        super().__init__(**kw)
        self._send_q = send_q
        self._recv_q = recv_q

    def _wrap_payload(self, arrays):
        """Queue-message payload container (v1: a dict; the v2 variant
        ships the buffer-donating items tuple unchanged)."""
        return {k: np.asarray(v) for k, v in arrays} if arrays else None

    def _wire_payload(self, items):
        """Integrity-path payload container for an already-normalized
        ``[(key, array), ...]`` list (v1: a dict; v2: a donating tuple)."""
        return dict(items)

    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0) -> None:
        payload = self._wrap_payload(arrays)
        self._count_payload(arrays, tag)
        maybe_drop_or_delay_send(
            lambda m: _put_with_peer(self._send_q, m, timeout, self.peer_alive, self.who),
            (self._PICKLED, tag, seq, tuple(extra), payload),
        )

    def _raw_recv(self, timeout: float):
        return queue_get_from_peer(
            self._recv_q,
            timeout=timeout,
            peer_alive=self.peer_alive,
            who=self.who,
            detail_fn=self.detail_fn,
            poll_s=self.poll_s,
        )

    def recv(self, timeout: float) -> Frame:
        msg = self._raw_recv(timeout)
        return self._decode(msg)

    def _decode(self, msg) -> Frame:
        assert msg[0] == self._PICKLED, f"unexpected message {msg[0]!r}"
        _, tag, seq, extra, payload = msg
        if payload is not None and not isinstance(payload, dict):
            payload = dict(payload)  # v2 buffer-donating items tuple
        self._note_recv(tag, sum(int(v.nbytes) for v in payload.values()) if payload else 0)
        return Frame(tag, seq, extra, payload)

    def depth(self) -> Optional[int]:
        try:
            return self._recv_q.qsize()
        except (NotImplementedError, OSError):
            return None

    def close(self) -> None:
        # by close time the protocol is done (or the peer is dead):
        # undelivered frames must not wedge interpreter exit
        _cancel_queue_join(self._send_q)
        _cancel_queue_join(self._recv_q)
        self._leak_unregister()


class ShmChannel(QueueChannel):
    """SharedMemory-ring backend: payloads ride the PR-3 fixed-slot ring,
    the bounded control queue carries metadata only; payloads below the
    64 KB gate (or over the slot size) fall back to the pickled path
    transparently."""

    _SHM = "__shm_frame__"

    def __init__(self, send_q, recv_q, tx_free_q, rx_free_q, *, window=2, min_bytes=65536, **kw):
        super().__init__(send_q, recv_q, **kw)
        # ring slots == credit window: both mean "payloads in flight"
        self._tx = ShmSender(tx_free_q, n_slots=max(2, int(window)), min_bytes=min_bytes)
        self._rx = ShmReceiver(rx_free_q)

    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0) -> None:
        if arrays:
            arrays = [(k, np.asarray(v)) for k, v in arrays]
            sent = self._tx.send(
                lambda m: maybe_drop_or_delay_send(
                    lambda mm: _put_with_peer(self._send_q, mm, timeout, self.peer_alive, self.who),
                    m,
                ),
                self._SHM,
                arrays,
                (tag, seq, tuple(extra)),
                acquire_slot=lambda: queue_get_from_peer(
                    self._tx._free_q, timeout=timeout, peer_alive=self.peer_alive, who=self.who
                ),
            )
            if sent:
                self._count_payload(arrays, tag)
                return
        super().send(tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)

    def _resolve_leaves(self, leaves):
        """Leaf metadata as shipped on the control queue (v1: the full
        per-leaf list; the v2 variant resolves a cached-table reference)."""
        return leaves

    def recv(self, timeout: float) -> Frame:
        msg = self._raw_recv(timeout)
        if msg[0] != self._SHM:
            return self._decode(msg)
        _, info, slot, leaves, tag, seq, extra = msg
        views = self._rx.unpack(info, slot, self._resolve_leaves(leaves), copy=False)
        self._note_recv(tag, sum(int(v.nbytes) for v in views.values()))
        return Frame(tag, seq, extra, views, release_cb=lambda: self._rx.release(slot))

    def close(self) -> None:
        super().close()
        self._tx.close()
        self._rx.close()


# ----------------------------------------------------------------- tcp wire
_HDR = struct.Struct("!2sBII")  # magic, flags, meta_len, payload_len
_MAGIC = b"SR"
_FLAG_COMPRESSED = 1
# integrity wire version 1 (resilience/integrity.py): the frame's meta
# tuple carries a 6th element — the sender-computed payload checksum —
# and the receiver verifies before delivering
_FLAG_INTEGRITY = 2
_CREDIT_TAG = "__credit__"
_HELLO_TAG = "__hello__"
# integrity-layer control tag: a receiver that detected a corrupt frame
# asks the sender to retransmit it (extra = the corrupt frame's
# (tag, seq); the sender answers from its bounded resend ring)
_RETRANS_TAG = "__retrans__"
# how long a receiver waits for a requested retransmission before giving
# up loudly (FrameCorruptError), and how many re-requests it makes when
# the retransmission itself arrives corrupt
_RETRANS_TIMEOUT_S = 30.0
_RETRANS_MAX_RETRIES = 3
# length-prefix sanity bound: a corrupted tcp length prefix must be
# rejected with a clear stream-desync error instead of attempting a
# multi-GB recv_into allocation.  1 GiB comfortably exceeds any real
# credit-window payload (the windows are 2-8 frames of at most tens of
# MB); configurable per channel via ``algo.tcp_max_frame_mb``.
TCP_MAX_FRAME_BYTES = 1 << 30
_MAX_META_BYTES = 64 << 20


def _shutdown_close(sock: Optional[socket.socket]) -> None:
    """Shutdown THEN close: a plain ``close`` does not wake a thread
    blocked in ``recv`` on the same socket; the shutdown does."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact_into(sock: socket.socket, mv: memoryview) -> None:
    """Fill ``mv`` from the socket (sockets here are BLOCKING: a frame is
    read whole; ``close()`` from another thread is the wakeup)."""
    got = 0
    while got < len(mv):
        n = sock.recv_into(mv[got:], len(mv) - got)
        if n == 0:
            raise ConnectionResetError("peer closed the stream")
        got += n


def _send_frame(
    sock, lock, tag, seq, extra, arrays, compress_min: int, crc: Optional[int] = None, owner=None
) -> int:
    """Serialize + write one frame under ``lock``; returns payload bytes.
    ``crc`` (integrity mode) rides the meta tuple and flips the
    :data:`_FLAG_INTEGRITY` header bit — it covers the UNCOMPRESSED
    payload, so the receiver verifies after any decompression.

    Compression is ADAPTIVE when ``owner`` is supplied: a zlib probe of
    the first page decides whether the payload shrinks at all (float
    rollout data is incompressible — paying zlib to gain nothing was the
    ISSUE-19 satellite); skips are counted on ``owner.compress_skipped``."""
    leaves: List[Tuple] = []
    bufs: List[np.ndarray] = []
    off = 0
    for key, arr in arrays or []:
        a = np.ascontiguousarray(arr)
        leaves.append((key, tuple(a.shape), str(a.dtype), off, int(a.nbytes)))
        bufs.append(a.reshape(-1))  # 1-d view: 0-d scalars have no byte view
        off += int(a.nbytes)
    flags = 0
    blob: Optional[bytes] = None
    if compress_min and 0 < compress_min <= off:
        byte_views = [memoryview(b).cast("B") for b in bufs]
        if owner is not None:
            blob = wire.probe_compress(byte_views, off)
            if blob is None:
                owner.compress_skipped += 1
        else:
            blob = zlib.compress(b"".join(byte_views), 1)
        if blob is not None:
            flags |= _FLAG_COMPRESSED
    meta_tuple: Tuple = (tag, int(seq), tuple(extra), leaves, off)
    if crc is not None:
        flags |= _FLAG_INTEGRITY
        meta_tuple = meta_tuple + (int(crc),)
    meta = pickle.dumps(meta_tuple, protocol=pickle.HIGHEST_PROTOCOL)
    payload_len = len(blob) if blob is not None else off
    header = _HDR.pack(_MAGIC, flags, len(meta), payload_len)
    with lock:
        sock.sendall(header + meta)
        if blob is not None:
            sock.sendall(blob)
        else:
            for b in bufs:
                if b.nbytes:
                    sock.sendall(memoryview(b).cast("B"))
    return off


class _BufferPool:
    """Reusable receive buffers (the ``recv_into`` targets): frames borrow
    a buffer and hand it back on release, so steady state allocates
    nothing — the pool grows to credit-window depth and stops."""

    def __init__(self):
        self._bufs: List[bytearray] = []
        self._lock = threading.Lock()

    def take(self, nbytes: int) -> bytearray:
        with self._lock:
            for i, b in enumerate(self._bufs):
                if len(b) >= nbytes:
                    return self._bufs.pop(i)
        return bytearray(max(nbytes, 4096))

    def give(self, buf: bytearray) -> None:
        with self._lock:
            if len(self._bufs) < 8:
                self._bufs.append(buf)


def _read_frame(
    sock, pool: _BufferPool, max_frame_bytes: int = TCP_MAX_FRAME_BYTES, prefix: bytes = b""
) -> Tuple[str, int, Tuple, List[Tuple], Any, Optional[int]]:
    """Read one frame; returns ``(tag, seq, extra, leaves, buffer, crc)``
    where ``buffer`` backs the array views (return it to ``pool`` on
    release; decompressed frames own a private bytes object instead) and
    ``crc`` is the integrity checksum (None for plain frames).

    ``prefix`` is header bytes the caller already consumed — the v2
    reader peeks the 2-byte magic to dispatch between wire formats and
    hands the peeked bytes back here for the v1 path.

    The length prefix is SANITY-BOUNDED before any allocation: a single
    corrupted prefix byte can otherwise ask for a multi-GB ``recv_into``
    buffer; an absurd length is treated as a stream desync (the existing
    reconnect machinery recovers)."""
    hdr = bytearray(_HDR.size)
    if prefix:
        hdr[: len(prefix)] = prefix
    _recv_exact_into(sock, memoryview(hdr)[len(prefix) :])
    magic, flags, meta_len, payload_len = _HDR.unpack(bytes(hdr))
    if magic != _MAGIC:
        raise ConnectionResetError(f"bad frame magic {magic!r} (stream desync)")
    if meta_len > _MAX_META_BYTES or payload_len > max_frame_bytes:
        raise ConnectionResetError(
            f"frame length prefix asks for meta={meta_len} payload={payload_len} bytes "
            f"(cap {max_frame_bytes}): corrupted length prefix / stream desync"
        )
    meta_buf = bytearray(meta_len)
    _recv_exact_into(sock, memoryview(meta_buf))
    meta = pickle.loads(bytes(meta_buf))
    tag, seq, extra, leaves, raw_len = meta[:5]
    crc = int(meta[5]) if flags & _FLAG_INTEGRITY and len(meta) > 5 else None
    buf: Any = None
    if payload_len:
        buf = pool.take(payload_len)
        _recv_exact_into(sock, memoryview(buf)[:payload_len])
        if flags & _FLAG_COMPRESSED:
            raw = zlib.decompress(memoryview(buf)[:payload_len])
            assert len(raw) == raw_len
            pool.give(buf)
            buf = raw  # private bytes: not pooled, release is a no-op
    return tag, seq, extra, leaves, buf, crc


def _views_from(leaves: Sequence[Tuple], buf) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for key, shape, dtype, off, nbytes in leaves:
        dt = np.dtype(dtype)
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out[key] = np.frombuffer(buf, dtype=dt, count=count, offset=off).reshape(shape)
    return out


class TcpChannel(Channel):
    """Socket-stream backend: length-prefixed frames, ``recv_into``
    preallocated buffers, credit-window backpressure, optional
    compression, and reconnect-with-backoff (player side) / re-adoption
    (trainer side, via :class:`TcpListener`).

    A background reader thread drains the socket continuously —
    dispatching credit frames to the send window and queueing payload
    frames for :meth:`recv` — so a sender blocked on credit can never
    deadlock against an unread inbound credit.
    """

    # integrity hook slot: the Crc subclass binds a method here; the base
    # class pays one attribute test per send (see CrcTcpChannel)
    _integrity_send = None

    def __init__(
        self,
        *,
        sock: Optional[socket.socket] = None,
        address: Optional[Tuple[str, int]] = None,
        player_id: int = -1,
        window: int = 2,
        compress_min: int = 0,
        reconnect: bool = False,
        reconnect_timeout: float = 10.0,
        track_resend: bool = False,
        max_frame_bytes: int = TCP_MAX_FRAME_BYTES,
        **kw,
    ):
        super().__init__(**kw)
        self._max_frame_bytes = int(max_frame_bytes)
        self._address = address
        self._player_id = int(player_id)
        self._window = max(1, int(window))
        self._compress_min = int(compress_min)
        self._reconnect = bool(reconnect)
        self._reconnect_timeout = float(reconnect_timeout)
        self._track_resend = bool(track_resend)
        self._sock: Optional[socket.socket] = sock
        self._send_lock = threading.RLock()
        self._cond = threading.Condition()
        self._credits = self._window
        self._gen = 0
        self._dead: Optional[str] = None
        self._inbox: "queue_mod.Queue[Frame]" = queue_mod.Queue()
        self._pool = _BufferPool()
        self._last_seq: Dict[str, int] = {}
        self._last_broadcast: Optional[Tuple[str, int, Tuple, List[Tuple[str, np.ndarray]]]] = None
        self._stop = threading.Event()
        self._reader: Optional[threading.Thread] = None
        if self._sock is None:
            if address is None:
                raise ValueError("TcpChannel needs a socket or an address")
            self._sock = self._dial()
        self._configure(self._sock)
        self._start_reader()

    # ------------------------------------------------------------ lifecycle
    @staticmethod
    def _configure(sock: socket.socket) -> None:
        # BLOCKING sockets: frames are read whole (a read timeout mid-frame
        # would desync the stream); close() from another thread unblocks
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(self._address, timeout=10.0)
        _send_frame(sock, self._send_lock, _HELLO_TAG, 0, (self._player_id,), None, 0)
        self._configure(sock)
        return sock

    def _start_reader(self) -> None:
        self._reader = threading.Thread(
            target=self._reader_loop, name=f"sheeprl-tcp-reader-{self._player_id}", daemon=True
        )
        self._reader.start()

    def adopt_socket(self, sock: socket.socket) -> None:
        """Trainer side: swap in a reconnected player's fresh socket (the
        listener calls this from its accept thread), reset the credit
        window and re-send the last tracked broadcast frame (the one that
        may have died with the old connection — the peer dedupes).

        Also the REVIVAL path for a player that died outright and was
        restarted by the supervisor minutes later: by then the reader
        thread has marked the channel dead and exited, so the dead state
        is cleared (stale ``__dead__`` markers drained from the inbox)
        and a fresh reader started."""
        if self._stop.is_set():
            _shutdown_close(sock)
            return
        self._configure(sock)
        with self._cond:
            old, self._sock = self._sock, sock
            self._gen += 1
            self._credits = self._window
            self._dead = None
            self._cond.notify_all()
        _shutdown_close(old)
        # drain dead-markers queued while the connection was down, keeping
        # any real frames (there should be none, but order is preserved)
        survivors = []
        while True:
            try:
                f = self._inbox.get_nowait()
            except queue_mod.Empty:
                break
            if f.tag != "__dead__":
                survivors.append(f)
        for f in survivors:
            self._inbox.put(f)
        if self._reader is None or not self._reader.is_alive():
            self._start_reader()
        flight.fleet_event("readopt", player=self._player_id)
        self._resend_last_broadcast(sock)

    def _resend_last_broadcast(self, sock: socket.socket) -> None:
        if self._last_broadcast is None:
            return
        tag, seq, extra, arrays = self._last_broadcast
        try:
            self._wire_send(sock, tag, seq, extra, arrays)
        except OSError:
            pass  # the reader notices and the next adoption retries

    # ------------------------------------------------------------- wire hooks
    # The payload-bearing data path funnels through these two methods so
    # ``algo.wire_format=v2`` can swap the framing without touching the
    # credit/reconnect/integrity machinery around it (``wire_channel_cls``).
    # Control frames (hello, credit, retrans) stay on the module-level v1
    # helpers: they are arrayless, rare, and the listener's hello parse
    # must work before it knows the peer's wire format.
    def _wire_send(self, sock, tag, seq, extra, arrays, crc: Optional[int] = None) -> int:
        return _send_frame(
            sock, self._send_lock, tag, seq, extra, arrays, self._compress_min, crc=crc, owner=self
        )

    def _wire_read(self, sock) -> Tuple[str, int, Tuple, List[Tuple], Any, Optional[int]]:
        return _read_frame(sock, self._pool, self._max_frame_bytes)

    def _make_views(self, leaves, buf) -> Dict[str, np.ndarray]:
        # hook: the v2 mixin substitutes precompiled view specs here
        return _views_from(leaves, buf)

    def _mark_dead(self, reason: str) -> None:
        with self._cond:
            self._dead = reason
            self._cond.notify_all()
        self._inbox.put(Frame("__dead__", extra=(reason,)))

    def _handle_disconnect(self, err: Exception) -> bool:
        """Reader-thread recovery. True = a fresh socket is live (resume
        reading), False = channel is dead."""
        if self._stop.is_set():
            return False
        if self._reconnect:
            delay = 0.1
            deadline = time.monotonic() + self._reconnect_timeout
            while not self._stop.is_set() and time.monotonic() < deadline:
                if not self.peer_alive():
                    break
                try:
                    sock = self._dial()
                except OSError:
                    time.sleep(delay)
                    delay = min(delay * 2, 2.0)
                    continue
                with self._cond:
                    old, self._sock = self._sock, sock
                    self._gen += 1
                    self._credits = self._window
                    self._cond.notify_all()
                _shutdown_close(old)
                flight.fleet_event("reconnect", who=self.who)
                return True
            self._mark_dead(f"reconnect failed after {type(err).__name__}: {err}")
            return False
        # trainer side: wait for the listener to adopt a reconnection
        gen = self._gen
        with self._cond:
            self._cond.wait_for(
                lambda: self._gen != gen or self._stop.is_set() or self._dead,
                timeout=self._reconnect_timeout,
            )
            if self._gen != gen and self._dead is None:
                return True
        self._mark_dead(f"connection lost ({type(err).__name__}: {err})")
        return False

    def _reader_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            try:
                tag, seq, extra, leaves, buf, _ = self._wire_read(sock)
            except (OSError, ConnectionError, EOFError, pickle.UnpicklingError, zlib.error) as e:
                if self._stop.is_set():
                    return
                if sock is not self._sock:
                    continue  # a newer socket was adopted while we were blocked
                if not self._handle_disconnect(e):
                    # channel is dead: PARK instead of exiting — a
                    # supervisor revival adopts a fresh socket (bumping
                    # _gen, clearing _dead) and this same thread resumes;
                    # close() sets _stop and notifies
                    gen = self._gen
                    with self._cond:
                        self._cond.wait_for(lambda: self._stop.is_set() or self._gen != gen)
                    if self._stop.is_set():
                        return
                continue
            if tag == _CREDIT_TAG:
                with self._cond:
                    self._credits += 1
                    self._cond.notify_all()
                continue
            if seq >= 0 and seq <= self._last_seq.get(tag, -1):
                # duplicate after a reconnect replay — drop (credits were
                # reset on both sides when the connection swapped)
                if buf is not None and isinstance(buf, bytearray):
                    self._pool.give(buf)
                continue
            if seq >= 0:
                self._last_seq[tag] = seq
            arrays = self._make_views(leaves, buf if buf is not None else b"") if leaves else {}
            self._note_recv(tag, sum(int(v.nbytes) for v in arrays.values()))
            release_cb = None
            if arrays:
                pooled = buf if isinstance(buf, bytearray) else None

                def release_cb(pooled=pooled):
                    if pooled is not None:
                        self._pool.give(pooled)
                    self._send_credit()

            self._inbox.put(Frame(tag, seq, extra, arrays, release_cb=release_cb))

    def _send_credit(self) -> None:
        try:
            _send_frame(self._sock, self._send_lock, _CREDIT_TAG, 0, (), None, 0)
        except OSError:
            pass  # the reconnect path resets the window wholesale

    # ------------------------------------------------------------------ api
    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0) -> None:
        inj = get_injector()
        if inj.armed:
            # qualifier = the frame tag, so a fault spec can target one
            # traffic class (``net_delay@data:5:0.3`` delays only the
            # rollout shards — the critical-path attribution tests)
            if inj.fire("net_delay", qualifier=tag):
                time.sleep(inj.arg("net_delay"))
            if inj.fire("net_drop"):
                flight.fleet_event("net_drop", who=self.who)
                self._drop_connection()
        arrays = [(k, np.asarray(v)) for k, v in arrays] if arrays else None
        crc: Optional[int] = None
        if self._integrity_send is not None and arrays:
            crc, arrays = self._integrity_send(tag, seq, extra, arrays)
        needs_credit = bool(arrays)
        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                if needs_credit:
                    while self._credits <= 0 and self._dead is None:
                        if time.monotonic() > deadline:
                            raise queue_mod.Full
                        if not self.peer_alive():
                            raise PeerDiedError(self.who)
                        self._cond.wait(timeout=0.2)
                if self._dead is not None:
                    raise PeerDiedError(self.who, self._dead)
                gen = self._gen
                sock = self._sock
                if needs_credit:
                    self._credits -= 1
            try:
                nbytes = self._wire_send(sock, tag, seq, extra, arrays, crc=crc)
            except OSError:
                # wait for the reader's reconnect/adoption, then retry the
                # WHOLE frame (the peer dedupes a frame that did land)
                with self._cond:
                    ok = self._cond.wait_for(
                        lambda: self._gen != gen or self._dead is not None,
                        timeout=max(deadline - time.monotonic(), 0.0),
                    )
                    if self._dead is not None or not ok:
                        raise PeerDiedError(self.who, self._dead or "send timeout") from None
                continue
            self._note_send(tag, nbytes)
            if self._track_resend and arrays and seq >= 0:
                self._last_broadcast = (tag, int(seq), tuple(extra), arrays)
            return

    def _drop_connection(self) -> None:
        """``net_drop`` fault: sever the live connection abruptly (the
        reader sees the reset and runs the reconnect/adoption path).
        ``self._sock`` is read ONCE: the reader can reconnect and swap in
        a fresh socket between two statements, and closing the fresh one
        by accident would strand the reader in a recv that nothing wakes."""
        _shutdown_close(self._sock)

    def reset_for_rejoin(self) -> None:
        """Supervisor revival: forget the old connection's death (the
        restarted player has not dialed yet — until it does, ``recv`` must
        report Empty against the new process's liveness predicate instead
        of replaying the stale ``__dead__`` marker)."""
        with self._cond:
            self._dead = None
            self._credits = self._window  # the fresh peer's window is full
            self._cond.notify_all()
        survivors = []
        while True:
            try:
                f = self._inbox.get_nowait()
            except queue_mod.Empty:
                break
            if f.tag != "__dead__":
                survivors.append(f)
        for f in survivors:
            self._inbox.put(f)

    def recv(self, timeout: float) -> Frame:
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise queue_mod.Empty
            try:
                frame = self._inbox.get(timeout=min(self.poll_s, remaining))
            except queue_mod.Empty:
                if not self.peer_alive():
                    detail = self.detail_fn() if self.detail_fn else ""
                    raise PeerDiedError(self.who, detail) from None
                continue
            if frame.tag == "__dead__":
                self._inbox.put(frame)  # keep surfacing for later callers
                raise PeerDiedError(self.who, frame.extra[0] if frame.extra else "")
            return frame

    def depth(self) -> Optional[int]:
        return self._inbox.qsize()

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        _shutdown_close(self._sock)
        if self._reader is not None and self._reader is not threading.current_thread():
            self._reader.join(timeout=5.0)
        self._leak_unregister()


# ------------------------------------------------- integrity channel layer
# ``algo.transport_integrity = crc|digest`` swaps these subclasses in for
# the plain backends (``off`` constructs the UNDECORATED classes above —
# zero overhead by construction, asserted by test).  The contract, shared
# by all three backends:
#
# - every payload-bearing frame carries a content checksum computed at
#   ``send`` (resilience/integrity.py: sampled CRC32C) and verified at
#   the receiver BEFORE delivery — a flipped bit is never silently
#   accepted;
# - the sender keeps a bounded RESEND RING of its recent seq-numbered
#   frames; a receiver that detects corruption drops the frame (shm: the
#   slot is released, re-granting the ring credit; tcp: the buffer goes
#   back to the pool and the retransmission inherits the window slot;
#   queue: the message is simply discarded), counts it, and sends a
#   ``__retrans__`` control frame naming ``(tag, seq)``;
# - while a retransmission is in flight, later frames of the SAME tag are
#   HELD BACK so per-tag seq order is preserved end-to-end (the fan-in's
#   round assembly and the params walk both rely on it); other tags flow;
# - recovery is transparent to callers.  :class:`FrameCorruptError`
#   surfaces only when recovery is impossible: the frame has no seq to
#   re-request, the resend ring no longer holds it, the retransmission
#   itself kept arriving corrupt, or the wait timed out.
class _ResendRing:
    """Sender-side bounded ring of recent seq-numbered frames + the
    retransmit server shared by all integrity backends.

    ``_payload_digest`` is the per-backend checksum scheme: the queue
    backend uses the per-leaf :func:`content_digest` (its payload is a
    pickled dict, and its baseline cost dwarfs the checksum); shm and
    tcp use the frame-level :func:`stream_digest` over the concatenated
    payload bytes (their payloads ARE one contiguous region — the
    packed slot / the wire buffer — and the per-leaf scheme's python
    overhead was the measured bulk of crc-mode cost at 1 MB)."""

    _payload_digest = staticmethod(content_digest)

    def _init_integrity(self, resend_depth: int = 4) -> None:
        self._istats = integrity_stats()
        self._coverage = default_coverage()
        self._resend: "OrderedDict[Tuple[str, int], Tuple[Tuple, list, int]]" = OrderedDict()
        self._resend_depth = max(2, int(resend_depth))

    def _store_resend(self, tag: str, seq: int, extra, arrays, crc: int) -> None:
        if seq < 0 or not arrays:
            return
        # snapshot semantics: leaves that do NOT own their memory (zero-
        # copy views of jax device buffers, replay/rollout buffer slices)
        # are copied here — their backing storage is donated or
        # overwritten within a round, and a retransmission must serve the
        # ORIGINAL bytes (found live: a params broadcast stored by
        # reference was recycled by the next donating update before the
        # retransmit request arrived).  Arrays that own their data are
        # stored by reference — the protocol paths rebuild payloads every
        # round — and every resend re-verifies the stored checksum first,
        # so a mutated owner turns into a refused resend (loud give-up at
        # the receiver), never a silent resend of different bytes.
        stored = [(k, a if a.base is None else np.array(a)) for k, a in arrays]
        self._resend[(tag, int(seq))] = (tuple(extra), stored, int(crc))
        while len(self._resend) > self._resend_depth:
            self._resend.popitem(last=False)

    def _serve_retrans(self, tag: str, seq: int) -> None:
        entry = self._resend.get((tag, int(seq)))
        if entry is None:
            return  # evicted: the receiver's wait gives up loudly
        extra, arrays, crc = entry
        if self._payload_digest(arrays, self._coverage) != crc:
            return  # mutated since the original send: refuse (see above)
        self._istats.retrans_served += 1
        flight.fleet_event("retrans_serve", tag=tag, seq=int(seq))
        self._resend_now(tag, int(seq), extra, arrays, crc)

    def _resend_now(self, tag: str, seq: int, extra, arrays, crc: int) -> None:
        raise NotImplementedError


class _QueueIntegrityMixin(_ResendRing):
    """Receive-side integrity protocol for the queue-message backends
    (queue, shm): verification, retransmit requests, and held-back
    ordering, all inside ``recv`` (these backends have no reader thread —
    the recv loop IS the drain point, which also means a peer blocked on
    our retransmission is served the moment we next wait for anything)."""

    def _init_integrity(self, resend_depth: int = 4) -> None:
        super()._init_integrity(resend_depth)
        self._iq_ready: deque = deque()  # verified frames awaiting delivery
        self._awaiting: Optional[list] = None  # [tag, seq, deadline, retries]
        self._held: List[Frame] = []  # same-tag frames parked behind a retrans

    def _verify_frame(self, frame: Frame, crc: int) -> bool:
        return self._payload_digest(list(frame.arrays.items()), self._coverage) == crc

    # ------------------------------------------------------------- sending
    def _request_retrans(self, tag: str, seq: int) -> None:
        self._istats.retrans_requested += 1
        flight.fleet_event("retrans_request", tag=tag, seq=int(seq))
        self._awaiting = [tag, int(seq), time.monotonic() + _RETRANS_TIMEOUT_S, 0]
        try:
            _put_with_peer(
                self._send_q,
                (QueueChannel._PICKLED, _RETRANS_TAG, -1, (tag, int(seq)), None, None),
                10.0,
                self.peer_alive,
                self.who,
            )
        except (queue_mod.Full, PeerDiedError):
            pass  # the await deadline gives up loudly

    # ------------------------------------------------------------ receiving
    def _give_up_awaiting(self) -> Tuple[str, int]:
        tag, seq = self._awaiting[0], self._awaiting[1]
        self._awaiting = None
        self._istats.retrans_failed += 1
        flight.fleet_event("retrans_failed", tag=tag, seq=int(seq))
        self._held.sort(key=lambda f: f.seq)
        self._iq_ready.extend(self._held)
        self._held = []
        return tag, seq

    def _finish_awaiting(self, frame: Frame) -> None:
        self._awaiting = None
        self._istats.retrans_recovered += 1
        self._iq_ready.append(frame)
        self._held.sort(key=lambda f: f.seq)
        self._iq_ready.extend(self._held)
        self._held = []

    def _ingest_frame(self, frame: Frame, crc: Optional[int]) -> None:
        """Verify one decoded frame and route it: deliver, hold back, or
        drop + request retransmission."""
        ok = True
        if frame.arrays:
            self._istats.frames_checked += 1
            if crc is not None:
                ok = self._verify_frame(frame, crc)
        aw = self._awaiting
        if aw is not None and frame.tag == aw[0]:
            if frame.seq == aw[1]:
                if ok:
                    self._finish_awaiting(frame)
                else:
                    self._istats.frames_corrupt += 1
                    frame.release()
                    aw[3] += 1
                    if aw[3] >= _RETRANS_MAX_RETRIES:
                        tag, seq = self._give_up_awaiting()
                        raise FrameCorruptError(
                            tag, seq, "every retransmission arrived corrupt"
                        )
                    self._awaiting = None
                    self._request_retrans(frame.tag, frame.seq)
                    self._awaiting[3] = aw[3]
                return
            if frame.seq > aw[1]:
                if ok:
                    self._held.append(frame)
                else:
                    # second corruption while one retransmission is in
                    # flight: dropped + counted, no nested protocol round
                    self._istats.frames_corrupt += 1
                    frame.release()
                return
            frame.release()  # stale duplicate below the awaited seq
            return
        if ok:
            self._iq_ready.append(frame)
            return
        self._istats.frames_corrupt += 1
        tag, seq = frame.tag, frame.seq
        frame.release()  # shm: the corrupt slot is dropped + credit re-granted
        if seq < 0:
            raise FrameCorruptError(
                tag, seq, "checksum mismatch (frame has no seq: cannot re-request)"
            )
        self._request_retrans(tag, seq)

    def recv(self, timeout: float) -> Frame:
        deadline = time.monotonic() + timeout
        while True:
            if self._iq_ready:
                return self._iq_ready.popleft()
            if self._awaiting is not None and time.monotonic() > self._awaiting[2]:
                tag, seq = self._give_up_awaiting()
                raise FrameCorruptError(tag, seq, "retransmission never arrived")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise queue_mod.Empty
            chunk = min(remaining, 0.25) if self._awaiting is not None else remaining
            try:
                msg = self._raw_recv(chunk)
            except queue_mod.Empty:
                continue  # re-check the await deadline / caller deadline
            decoded = self._decode_integrity(msg)
            if decoded is None:
                continue  # consumed control (a served retransmit request)
            self._ingest_frame(*decoded)
            if self._iq_ready and self._awaiting is None:
                return self._iq_ready.popleft()

    # ------------------------------------------------------------- decoding
    def _decode_queue_msg(self, msg) -> Optional[Tuple[Frame, Optional[int]]]:
        assert msg[0] == QueueChannel._PICKLED, f"unexpected message {msg[0]!r}"
        _, tag, seq, extra, payload = msg[:5]
        crc = msg[5] if len(msg) > 5 else None
        if tag == _RETRANS_TAG:
            self._serve_retrans(*extra[:2])
            return None
        if payload is not None and not isinstance(payload, dict):
            payload = dict(payload)  # v2 buffer-donating items tuple
        self._note_recv(tag, sum(int(v.nbytes) for v in payload.values()) if payload else 0)
        return Frame(tag, seq, extra, payload), crc


class CrcQueueChannel(_QueueIntegrityMixin, QueueChannel):
    """Integrity variant of the pickled-queue backend."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._init_integrity()

    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0) -> None:
        if not arrays:
            return QueueChannel.send(self, tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)
        items = [(k, np.asarray(v)) for k, v in arrays]
        crc = self._payload_digest(items, self._coverage)
        self._store_resend(tag, seq, extra, items, crc)
        wire = maybe_bit_flip(items, tag)  # fault site: AFTER the checksum
        self._count_payload(items, tag)
        maybe_drop_or_delay_send(
            lambda m: _put_with_peer(self._send_q, m, timeout, self.peer_alive, self.who),
            (self._PICKLED, tag, seq, tuple(extra), self._wire_payload(wire), crc),
        )

    def _resend_now(self, tag, seq, extra, arrays, crc) -> None:
        try:
            _put_with_peer(
                self._send_q,
                (self._PICKLED, tag, seq, extra, self._wire_payload(list(arrays)), crc),
                10.0,
                self.peer_alive,
                self.who,
            )
        except (queue_mod.Full, PeerDiedError):
            pass

    def _decode_integrity(self, msg) -> Optional[Tuple[Frame, Optional[int]]]:
        return self._decode_queue_msg(msg)


class CrcShmChannel(_QueueIntegrityMixin, ShmChannel):
    """Integrity variant of the SharedMemory-ring backend.  The checksum
    is computed over the JUST-PACKED slot region (contiguous and
    cache-hot — measured ~3x cheaper than walking the source arrays)
    right before the metadata message ships, and verified over the same
    region at the receiver, so it covers the slot's whole lifetime
    (residence, a peer death scribbling /dev/shm, unpack).  The
    ``bit_flip`` fault flips a SLOT byte after the checksum — literally
    the "corrupt shm slot" failure mode.  A corrupt slot is dropped and
    immediately released; the re-granted ring credit carries the
    retransmission.  Payloads that fall back to the pickled path
    (oversize / below the ring gate) are checksummed with the same
    stream scheme over the arrays instead."""

    _payload_digest = staticmethod(stream_digest)

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._init_integrity(resend_depth=self._tx._n_slots + 2)
        self._slot_region = None

    def _send_items(
        self, tag, seq, extra, items, timeout, faultable: bool, store: bool, total: Optional[int] = None
    ) -> None:
        """Pack into a slot, checksum the slot region, ship the metadata
        (queue fallback for oversize/gated payloads, array-checksummed)."""

        def _put(m):
            _put_with_peer(self._send_q, m, timeout, self.peer_alive, self.who)

        base_put = (lambda m: maybe_drop_or_delay_send(_put, m)) if faultable else _put

        def put_slot_msg(m):
            # m = (_SHM, info, slot, leaves, tag, seq, extra): the slot
            # is packed but the receiver cannot see it until this
            # message lands — checksum it now, then let the fault flip
            # slot bytes, then append the crc
            slot = m[2]
            nbytes = total if total is not None else sum(int(a.nbytes) for _, a in items)
            region = self._tx._arena.region(slot, nbytes)
            crc = region_digest(region, nbytes, self._coverage)
            if store:
                self._store_resend(tag, seq, extra, items, crc)
            if faultable:
                maybe_bit_flip_region(region, tag)  # fault site: AFTER the checksum
            base_put(m + (crc,))

        sent = self._tx.send(
            put_slot_msg,
            self._SHM,
            items,
            (tag, seq, tuple(extra)),
            acquire_slot=lambda: queue_get_from_peer(
                self._tx._free_q, timeout=timeout, peer_alive=self.peer_alive, who=self.who
            ),
        )
        if not sent:
            crc = self._payload_digest(items, self._coverage)
            if store:
                self._store_resend(tag, seq, extra, items, crc)
            wire = maybe_bit_flip(items, tag) if faultable else items
            base_put((QueueChannel._PICKLED, tag, seq, tuple(extra), self._wire_payload(list(wire)), crc))

    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0) -> None:
        if not arrays:
            return QueueChannel.send(self, tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)
        items = [(k, np.asarray(v)) for k, v in arrays]
        total = self._count_payload(items, tag)
        self._send_items(tag, seq, extra, items, timeout, faultable=True, store=True, total=total)

    def _resend_now(self, tag, seq, extra, arrays, crc) -> None:
        try:
            self._send_items(tag, seq, extra, list(arrays), 10.0, faultable=False, store=False)
        except (queue_mod.Full, queue_mod.Empty, PeerDiedError):
            pass

    def _decode_integrity(self, msg) -> Optional[Tuple[Frame, Optional[int]]]:
        if msg[0] != self._SHM:
            self._slot_region = None
            return self._decode_queue_msg(msg)
        _, info, slot, leaves = msg[:4]
        rest = msg[4:]
        tag, seq, extra = rest[:3]
        crc = rest[3] if len(rest) > 3 else None
        views = self._rx.unpack(info, slot, self._resolve_leaves(leaves), copy=False)
        nbytes = sum(int(v.nbytes) for v in views.values())
        self._note_recv(tag, nbytes)
        # receive-side fast path: the slot IS the concatenated stream —
        # _verify_frame checksums it in one contiguous pass
        self._slot_region = self._rx.region(slot, nbytes)
        return Frame(tag, seq, extra, views, release_cb=lambda: self._rx.release(slot)), crc

    def _verify_frame(self, frame: Frame, crc: int) -> bool:
        region, self._slot_region = self._slot_region, None
        if region is None:
            return super()._verify_frame(frame, crc)
        return region_digest(region, coverage=self._coverage) == crc


class CrcTcpChannel(_ResendRing, TcpChannel):
    """Integrity variant of the socket backend: the checksum rides the
    frame header (:data:`_FLAG_INTEGRITY`), verification happens in the
    reader thread before the frame reaches the inbox, and the
    retransmit/held-back protocol runs entirely inside the reader so a
    blocked consumer can never stall recovery.  A corrupt frame does NOT
    return a window credit — the retransmission (sent credit-free by the
    peer) inherits the original frame's window slot, keeping the credit
    ledger balanced."""

    _payload_digest = staticmethod(stream_digest)

    def __init__(self, **kw):
        # reader-thread state must exist before super().__init__ starts
        # the reader
        self._init_integrity()
        self._await_lock = threading.Lock()
        self._tcp_await: Optional[list] = None  # [tag, seq, deadline, retries]
        self._tcp_held: List[Frame] = []
        super().__init__(**kw)
        self._resend_depth = self._window + 2

    # ------------------------------------------------------------- sending
    def _integrity_send(self, tag, seq, extra, arrays):
        crc = self._payload_digest(arrays, self._coverage)
        self._store_resend(tag, seq, extra, arrays, crc)
        return crc, maybe_bit_flip(arrays, tag)  # fault site: AFTER the checksum

    def _resend_now(self, tag, seq, extra, arrays, crc) -> None:
        try:
            self._wire_send(self._sock, tag, seq, extra, arrays, crc=crc)
        except OSError:
            pass  # reconnect resets the window wholesale

    def _resend_last_broadcast(self, sock: socket.socket) -> None:
        """Reconnect replay must carry a VALID checksum: replay from the
        resend ring (the clean arrays), not from the wire copy a
        bit-flip fault may have poisoned."""
        if self._last_broadcast is None:
            return
        tag, seq, extra, arrays = self._last_broadcast
        entry = self._resend.get((tag, int(seq)))
        crc = None
        if entry is not None:
            extra, arrays, crc = entry
        try:
            self._wire_send(sock, tag, seq, extra, arrays, crc=crc)
        except OSError:
            pass

    # ------------------------------------------------------------ receiving
    def _request_tcp_retrans(self, tag: str, seq: int, retries: int = 0) -> None:
        self._istats.retrans_requested += 1
        flight.fleet_event("retrans_request", tag=tag, seq=int(seq))
        with self._await_lock:
            self._tcp_await = [tag, int(seq), time.monotonic() + _RETRANS_TIMEOUT_S, retries]
        try:
            _send_frame(self._sock, self._send_lock, _RETRANS_TAG, -1, (tag, int(seq)), None, 0)
        except OSError:
            pass  # the await deadline gives up loudly

    def _flush_tcp_held(self) -> None:
        self._tcp_held.sort(key=lambda f: f.seq)
        for f in self._tcp_held:
            if f.seq >= 0:
                self._last_seq[f.tag] = f.seq
            self._inbox.put(f)
        self._tcp_held = []

    def _check_tcp_await(self) -> None:
        """Give up on an expired retransmission wait (called from both
        the reader loop and the consumer's recv poll)."""
        with self._await_lock:
            aw = self._tcp_await
            if aw is None or time.monotonic() <= aw[2]:
                return
            self._tcp_await = None
        self._istats.retrans_failed += 1
        flight.fleet_event("retrans_failed", tag=aw[0], seq=int(aw[1]))
        self._flush_tcp_held()
        self._inbox.put(
            Frame("__corrupt__", extra=(aw[0], aw[1], "retransmission never arrived"))
        )

    def _deliver_frame(self, tag, seq, extra, arrays, buf) -> None:
        if seq >= 0:
            self._last_seq[tag] = seq
        self._note_recv(tag, sum(int(v.nbytes) for v in arrays.values()))
        release_cb = None
        if arrays:
            pooled = buf if isinstance(buf, bytearray) else None

            def release_cb(pooled=pooled):
                if pooled is not None:
                    self._pool.give(pooled)
                self._send_credit()

        self._inbox.put(Frame(tag, seq, extra, arrays, release_cb=release_cb))

    def recv(self, timeout: float) -> Frame:
        deadline = time.monotonic() + timeout
        while True:
            self._check_tcp_await()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise queue_mod.Empty
            try:
                frame = self._inbox.get(timeout=min(self.poll_s, remaining))
            except queue_mod.Empty:
                if not self.peer_alive():
                    detail = self.detail_fn() if self.detail_fn else ""
                    raise PeerDiedError(self.who, detail) from None
                continue
            if frame.tag == "__dead__":
                self._inbox.put(frame)  # keep surfacing for later callers
                raise PeerDiedError(self.who, frame.extra[0] if frame.extra else "")
            if frame.tag == "__corrupt__":
                raise FrameCorruptError(*frame.extra[:3])
            return frame

    def _reader_loop(self) -> None:  # noqa: C901 - mirrors the base loop + verify
        while not self._stop.is_set():
            sock = self._sock
            try:
                tag, seq, extra, leaves, buf, crc = self._wire_read(sock)
            except (OSError, ConnectionError, EOFError, pickle.UnpicklingError, zlib.error) as e:
                if self._stop.is_set():
                    return
                if sock is not self._sock:
                    continue  # a newer socket was adopted while we were blocked
                if not self._handle_disconnect(e):
                    gen = self._gen
                    with self._cond:
                        self._cond.wait_for(lambda: self._stop.is_set() or self._gen != gen)
                    if self._stop.is_set():
                        return
                continue
            self._check_tcp_await()
            if tag == _CREDIT_TAG:
                with self._cond:
                    self._credits += 1
                    self._cond.notify_all()
                continue
            if tag == _RETRANS_TAG:
                self._serve_retrans(str(extra[0]), int(extra[1]))
                continue
            if seq >= 0 and seq <= self._last_seq.get(tag, -1):
                if buf is not None and isinstance(buf, bytearray):
                    self._pool.give(buf)
                continue
            arrays = self._make_views(leaves, buf if buf is not None else b"") if leaves else {}
            ok = True
            if arrays:
                self._istats.frames_checked += 1
                if crc is not None:
                    # the wire buffer is the concatenated stream: one
                    # contiguous checksum pass (leaves carry offsets +
                    # sizes, so the stream length is the last leaf's end)
                    total = leaves[-1][3] + leaves[-1][4]
                    ok = region_digest(buf, total, self._coverage) == crc
            with self._await_lock:
                aw = self._tcp_await
            if not ok:
                self._istats.frames_corrupt += 1
                if isinstance(buf, bytearray):
                    self._pool.give(buf)
                # no credit for the dropped frame: the retransmission
                # (credit-free at the sender) inherits its window slot
                if seq < 0:
                    self._inbox.put(
                        Frame(
                            "__corrupt__",
                            extra=(tag, seq, "checksum mismatch (frame has no seq)"),
                        )
                    )
                elif aw is None:
                    self._request_tcp_retrans(tag, seq)
                elif aw[0] == tag and aw[1] == seq:
                    if aw[3] + 1 >= _RETRANS_MAX_RETRIES:
                        with self._await_lock:
                            self._tcp_await = None
                        self._istats.retrans_failed += 1
                        self._flush_tcp_held()
                        self._inbox.put(
                            Frame(
                                "__corrupt__",
                                extra=(tag, seq, "every retransmission arrived corrupt"),
                            )
                        )
                    else:
                        self._request_tcp_retrans(tag, seq, retries=aw[3] + 1)
                # else: second corruption while awaiting — dropped + counted
                continue
            if aw is not None and tag == aw[0] and seq > aw[1]:
                # hold back: per-tag seq order is preserved across the
                # retransmission (the fan-in round assembly relies on it)
                self._note_recv(tag, sum(int(v.nbytes) for v in arrays.values()))
                pooled = buf if isinstance(buf, bytearray) else None

                def release_cb(pooled=pooled):
                    if pooled is not None:
                        self._pool.give(pooled)
                    self._send_credit()

                self._tcp_held.append(
                    Frame(tag, seq, extra, arrays, release_cb=release_cb if arrays else None)
                )
                continue
            if aw is not None and tag == aw[0] and seq == aw[1]:
                with self._await_lock:
                    self._tcp_await = None
                self._istats.retrans_recovered += 1
                self._deliver_frame(tag, seq, extra, arrays, buf)
                self._flush_tcp_held()
                continue
            self._deliver_frame(tag, seq, extra, arrays, buf)


# ------------------------------------------------------- wire-format v2 layer
# ``algo.wire_format = v2`` swaps these mixins over the plain/integrity
# backends (``wire_channel_cls``, same construction-time pattern as the
# integrity and tracing layers: ``v1`` returns the UNDECORATED class, so
# the default path is bit-identical to the pre-v2 tree by construction).
# The codec itself lives in ``parallel/wire.py``; this layer binds it to
# the channel machinery: sent-table caching keyed to the connection
# generation, dual-magic read dispatch, coalesced-batch delivery, and
# the shm leaf-table reference scheme.
#
# Coalescing batches small same-destination frames (heartbeats, live
# summaries, fused-collector inserts under the size gate) into one wire
# frame under a size/deadline gate.  Batches are CREDIT-EXEMPT on both
# sides — the batch is bounded by the coalescer's size gate, and the
# subframes' consumers (fan-in bookkeeping, replay ingest credit flow)
# provide their own backpressure — so a released subframe must never
# return a window credit: delivery bypasses the pooled-buffer path
# entirely (each subframe owns a private buffer).
_COAL_ITEM_MAX_BYTES = 16 << 10  # a frame above this never coalesces
_COAL_BATCH_MAX_BYTES = 64 << 10  # size gate: flush when the batch reaches this
_V2_SOCK_BUF_BYTES = 8 << 20


class _Coalescer:
    """Size/deadline-gated batcher for one channel's small frames."""

    def __init__(self, chan, deadline_s: float, max_bytes: int = _COAL_BATCH_MAX_BYTES):
        self._chan = chan
        self._deadline_s = max(float(deadline_s), 1e-4)
        self._max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._parts: List[bytes] = []
        self._bytes = 0
        self._oldest: Optional[float] = None
        self._stop = threading.Event()
        self.batches = 0
        self._thread = threading.Thread(
            target=self._tick, name="sheeprl-wire-coalesce", daemon=True
        )
        self._thread.start()

    def add(self, tag, seq, extra, items) -> None:
        entry = wire.encode_coalesced_entry(tag, seq, extra, items)
        with self._lock:
            self._parts.append(entry)
            self._bytes += len(entry)
            if self._oldest is None:
                self._oldest = time.monotonic()
            due = self._bytes >= self._max_bytes
        if due:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            parts, self._parts = self._parts, []
            self._bytes = 0
            self._oldest = None
        if parts:
            self._chan._send_coal(b"".join(parts))
            self.batches += 1

    def _tick(self) -> None:
        while not self._stop.wait(self._deadline_s):
            with self._lock:
                due = (
                    self._oldest is not None
                    and time.monotonic() - self._oldest >= self._deadline_s
                )
            if due:
                try:
                    self.flush()
                except Exception:
                    pass  # peer death surfaces loudly on the direct send path

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            self.flush()
        except Exception:
            pass


class _WireV2TcpMixin:
    """v2 framing over the socket backend: cached leaf tables, one
    ``sendmsg`` gather write per frame, dual-magic read dispatch (control
    frames stay v1), and optional frame coalescing."""

    def __init__(self, *a, coalesce_ms: float = 0.0, **kw):
        # reader-thread state must exist before super().__init__ starts
        # the reader
        self._v2_rx_tables: Dict[int, wire.CompiledTable] = {}
        self._v2_sent: Optional[Tuple[int, set]] = None
        self._v2_coal: Optional[_Coalescer] = None
        # send-side caches, keyed on payload structure: the encoded leaf
        # table + struct_id (the "cached per (tag, structure)" half of
        # the v2 design) and the adaptive-compression verdict
        self._v2_tx_cache: Dict[Tuple, Tuple[bytes, int]] = {}
        # credit batching: releases accumulate and ship as ONE compact
        # binary credit frame carrying the count (see _send_credit)
        self._v2_credit_pend = 0
        self._v2_credit_lock = threading.Lock()
        super().__init__(*a, **kw)
        self._v2_credit_k = max(1, self._window // 3)
        if coalesce_ms and float(coalesce_ms) > 0:
            self._v2_coal = _Coalescer(self, float(coalesce_ms) / 1000.0)

    # ------------------------------------------------------------ socket tune
    @staticmethod
    def _configure(sock: socket.socket) -> None:
        TcpChannel._configure(sock)
        # large kernel buffers: the v2 rationale is one gather write per
        # frame — on a loopback/1-core host that only pays off when a
        # 1 MB frame fits the socket buffer instead of ping-ponging
        # fill/drain context switches with the peer
        for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
            try:
                sock.setsockopt(socket.SOL_SOCKET, opt, _V2_SOCK_BUF_BYTES)
            except OSError:
                pass

    # --------------------------------------------------------------- sending
    def _wire_send(self, sock, tag, seq, extra, arrays, crc: Optional[int] = None) -> int:
        if not arrays:
            # arrayless frames keep the v1 format: nothing to scatter-
            # gather, and the listener/het-peer hello parse stays simple
            return _send_frame(
                sock, self._send_lock, tag, seq, extra, arrays, self._compress_min, crc=crc, owner=self
            )
        # one flatten pass builds the byte views AND the structure key;
        # the encoded table + struct_id come from the per-structure cache
        # (steady-state streams repeat one geometry frame after frame, so
        # re-encoding the table per send was pure hot-path overhead)
        skey: List[Tuple] = []
        bufs: List[memoryview] = []
        total = 0
        for key, a in arrays:
            if not a.flags["C_CONTIGUOUS"]:
                a = np.ascontiguousarray(a)
            skey.append((key, a.shape, a.dtype.str))
            if a.nbytes:
                bufs.append(memoryview(a.reshape(-1)).cast("B"))
            total += a.nbytes
        cached = self._v2_tx_cache.get(tuple(skey))
        if cached is None:
            leaves, _bufs, _total = wire.build_leaves(arrays)
            table_bytes = wire.encode_leaf_table(leaves)
            if len(self._v2_tx_cache) >= 256:
                self._v2_tx_cache.clear()  # unbounded-structure guard
            cached = (table_bytes, zlib.crc32(table_bytes))
            self._v2_tx_cache[tuple(skey)] = cached
        table, struct_id = cached
        extra_blob = (
            pickle.dumps(tuple(extra), protocol=pickle.HIGHEST_PROTOCOL) if extra else b""
        )
        flags = 0
        payload: List = bufs
        payload_len = total
        if self._compress_min and 0 < self._compress_min <= total:
            # the probe is per-frame on purpose: compressibility is a
            # CONTENT property (a zeroed buffer and a noise buffer share
            # one geometry), so only the table/struct work is cacheable
            blob = wire.probe_compress(bufs, total)
            if blob is None:
                self.compress_skipped += 1
            else:
                flags |= wire.F2_COMPRESSED
                payload = [memoryview(blob)]
                payload_len = len(blob)
        with self._send_lock:
            # sent-table cache is keyed to the connection generation: a
            # reconnected/adopted socket starts a fresh stream, so the
            # first frame of each structure re-ships its table
            if self._v2_sent is None or self._v2_sent[0] != self._gen:
                self._v2_sent = (self._gen, set())
            sent_ids = self._v2_sent[1]
            tbl = b"" if struct_id in sent_ids else table
            if tbl:
                flags |= wire.F2_TABLE
            hdr = wire.pack_header_v2(flags, tag, struct_id, seq, extra_blob, tbl, payload_len, crc)
            wire.sendmsg_all(sock, [hdr] + payload)
            sent_ids.add(struct_id)  # only after the table actually landed
        return total

    def _coal_eligible(self, tag, arrays) -> bool:
        if tag.startswith("__"):
            return False  # control/protocol frames never coalesce
        if not arrays:
            return True
        if self._integrity_send is not None:
            # coalesced subframes carry no transport checksum: with
            # integrity on, only arrayless frames batch (the replay
            # layer's IngestGuard still validates rb_insert content)
            return False
        return sum(int(np.asarray(a).nbytes) for _, a in arrays) <= _COAL_ITEM_MAX_BYTES

    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0) -> None:
        coal = self._v2_coal
        if coal is not None:
            if self._coal_eligible(tag, arrays):
                items = [(k, np.asarray(v)) for k, v in arrays] if arrays else None
                self._count_payload(items, tag)
                coal.add(tag, seq, extra, items)
                return
            # a direct frame must not overtake batched small ones:
            # flush first so global send order is preserved
            coal.flush()
        super().send(tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)

    def _send_coal(self, payload: bytes) -> None:
        """Ship one coalesced batch (credit-exempt, best-effort across a
        reconnect: heartbeats/summaries are refreshed by their senders)."""
        deadline = time.monotonic() + 30.0
        while True:
            with self._cond:
                if self._dead is not None:
                    return
                gen = self._gen
                sock = self._sock
            hdr = wire.pack_header_v2(
                wire.F2_COALESCED, COAL_TAG, 0, -1, b"", b"", len(payload), None
            )
            try:
                with self._send_lock:
                    wire.sendmsg_all(sock, [hdr, payload])
                return
            except OSError:
                with self._cond:
                    ok = self._cond.wait_for(
                        lambda: self._gen != gen or self._dead is not None,
                        timeout=max(deadline - time.monotonic(), 0.0),
                    )
                    if self._dead is not None or not ok:
                        return  # dropped with the connection

    # -------------------------------------------------------------- receiving
    def _make_views(self, leaves, buf) -> Dict[str, np.ndarray]:
        spec = getattr(leaves, "views_spec", None)
        if spec is None:
            return _views_from(leaves, buf)
        # precompiled per-structure spec: no dtype-string parse, no
        # np.prod — just one frombuffer per leaf into the pooled arena
        return {
            key: np.frombuffer(buf, dtype=dt, count=count, offset=off).reshape(shape)
            for key, shape, dt, off, count in spec
        }

    def _send_credit(self) -> None:
        """Batched compact credits: releases accumulate until the batch
        threshold (window//3), then ship as ONE fixed-size v2 header
        whose ``seq`` field carries the count — no pickle, one write,
        and a third of the peer's reader wakeups.  Holding back up to
        k-1 credits shrinks the sender's effective window by at most
        k-1 < window slots, so the flow can never deadlock."""
        with self._v2_credit_lock:
            self._v2_credit_pend += 1
            if self._v2_credit_pend < self._v2_credit_k:
                return
            n, self._v2_credit_pend = self._v2_credit_pend, 0
        try:
            hdr = wire.pack_header_v2(0, _CREDIT_TAG, 0, n, b"", b"", 0, None)
            with self._send_lock:
                self._sock.sendall(hdr)
        except OSError:
            pass  # the reconnect path resets the window wholesale

    def _deliver_sub(self, tag, seq, extra, leaves, buf) -> None:
        """Deliver one coalesced subframe: normal per-tag dedupe, NO
        credit and no pooled buffer (the subframe owns ``buf``)."""
        if seq >= 0 and seq <= self._last_seq.get(tag, -1):
            return
        if seq >= 0:
            self._last_seq[tag] = seq
        arrays = _views_from(leaves, buf) if leaves else {}
        self._note_recv(tag, sum(int(v.nbytes) for v in arrays.values()))
        self._inbox.put(Frame(tag, seq, extra, arrays, release_cb=None))

    def _wire_read(self, sock) -> Tuple[str, int, Tuple, List[Tuple], Any, Optional[int]]:
        while True:
            magic = bytearray(2)
            _recv_exact_into(sock, memoryview(magic))
            magic = bytes(magic)
            if magic == _MAGIC:
                return _read_frame(sock, self._pool, self._max_frame_bytes, prefix=magic)
            if magic != wire.MAGIC_V2:
                raise WireFormatError(f"bad frame magic {magic!r} (stream desync)")
            hdr = bytearray(wire.HDR2.size)
            hdr[:2] = magic
            wire.recv_exact_into(sock, memoryview(hdr)[2:])
            _, flags, tag_len, struct_id, seq, extra_len, table_len, payload_len, crc_u = (
                wire.HDR2.unpack(bytes(hdr))
            )
            if (
                extra_len > wire._MAX_EXTRA_BYTES
                or table_len > wire._MAX_TABLE_BYTES
                or payload_len > self._max_frame_bytes
            ):
                raise WireFormatError(
                    f"v2 header asks for extra={extra_len} table={table_len} "
                    f"payload={payload_len} bytes (cap {self._max_frame_bytes}): "
                    "corrupted header / stream desync"
                )
            head = bytearray(tag_len + extra_len + table_len)
            wire.recv_exact_into(sock, memoryview(head))
            try:
                tag = bytes(head[:tag_len]).decode("ascii")
            except UnicodeDecodeError as e:
                raise WireFormatError(f"undecodable v2 tag: {e}") from None
            if tag == _CREDIT_TAG:
                # compact batched credit: the count rides the seq field
                # of a bodyless header — consumed here, never surfaced
                with self._cond:
                    self._credits += max(int(seq), 1)
                    self._cond.notify_all()
                continue
            if extra_len:
                try:
                    extra = pickle.loads(bytes(head[tag_len : tag_len + extra_len]))
                except Exception as e:
                    raise WireFormatError(f"undecodable v2 extras: {e}") from None
            else:
                extra = ()
            if flags & wire.F2_COALESCED:
                buf = wire.read_payload_v2(sock, self._pool, payload_len, flags, payload_len)
                try:
                    # slice: pooled buffers can be LARGER than the payload
                    subs = wire.decode_coalesced(
                        memoryview(buf)[:payload_len] if buf is not None else b""
                    )
                finally:
                    if isinstance(buf, bytearray):
                        self._pool.give(buf)  # subframes copied out their bytes
                for stag, sseq, sextra, sleaves, sbuf, _scrc in subs:
                    self._deliver_sub(stag, sseq, sextra, sleaves, sbuf)
                continue  # keep reading: the batch never surfaces as a frame
            if table_len:
                table = bytes(head[tag_len + extra_len :])
                if zlib.crc32(table) != struct_id:
                    # content-addressing check: a corrupt table must not
                    # poison the cache under a valid id
                    raise WireFormatError("leaf-table bytes do not match their struct_id")
                leaves = wire.compile_table(wire.decode_leaf_table(table))
                self._v2_rx_tables[struct_id] = leaves
            else:
                leaves = self._v2_rx_tables.get(struct_id)
                if leaves is None:
                    raise WireFormatError(
                        f"unknown struct_id {struct_id:#x} (table never seen on this stream)"
                    )
            buf = wire.read_payload_v2(sock, self._pool, payload_len, flags, leaves.raw_len)
            crc = int(crc_u) if flags & wire.F2_INTEGRITY else None
            return tag, seq, extra, leaves, buf, crc

    def close(self) -> None:
        coal, self._v2_coal = self._v2_coal, None
        if coal is not None:
            coal.close()
        super().close()


class _WireV2QueueMixin:
    """v2 over the pickled-queue backend: payloads ride as the buffer-
    donating ``((key, array), ...)`` items tuple instead of a rebuilt
    dict — the send side hands its normalized items straight to the
    queue's out-of-band pickling with no container copy."""

    def _wrap_payload(self, arrays):
        return tuple((k, np.asarray(v)) for k, v in arrays) if arrays else None

    def _wire_payload(self, items):
        return tuple(items)


class _WireV2ShmMixin(_WireV2QueueMixin):
    """v2 over the shm ring: the payload bytes already ship zero-copy
    through the slot, so v2 caches the per-structure LEAF TABLE — the
    control-queue message carries a ``("__tbl__", struct_id[, table])``
    reference instead of re-pickling the full per-leaf list each frame
    (same content-addressed scheme as tcp, minus the socket)."""

    def __init__(self, *a, **kw):
        kw.pop("coalesce_ms", None)  # queue/shm sends are already one hop
        self._v2_rx_tables: Dict[int, List[Tuple]] = {}
        super().__init__(*a, **kw)
        sent: set = set()

        def encode_leaves(leaves):
            # arena leaves are 4-tuples (key, shape, dtype, offset); the
            # table codec derives offsets itself, in pack order
            table = wire.encode_leaf_table([(k, s, d, 0, 0) for (k, s, d, _o) in leaves])
            sid = zlib.crc32(table)
            if sid in sent:
                return ("__tbl__", sid)
            sent.add(sid)
            return ("__tbl__", sid, table)

        self._tx.encode_leaves = encode_leaves

    def _resolve_leaves(self, leaves):
        if not (isinstance(leaves, tuple) and leaves and leaves[0] == "__tbl__"):
            return leaves  # oversize fallback frames keep the plain list
        sid = int(leaves[1])
        if len(leaves) > 2:
            table = leaves[2]
            if zlib.crc32(table) != sid:
                raise WireFormatError("shm leaf-table bytes do not match their struct_id")
            self._v2_rx_tables[sid] = wire.decode_leaf_table(table)
        decoded = self._v2_rx_tables.get(sid)
        if decoded is None:
            raise WireFormatError(f"unknown shm struct_id {sid:#x} (table never seen)")
        return [(k, s, d, o) for (k, s, d, o, _nb) in decoded]


_WIRE_CLS_CACHE: Dict[Tuple[type, str], type] = {}


def wire_channel_cls(base: type, wire_format: str) -> type:
    """Map a channel class to its ``wire_format`` variant.  ``v1``
    returns ``base`` UNDECORATED — the off-path is type-identical to the
    pre-v2 tree (the PR-9/10/13 zero-overhead-by-construction pattern,
    asserted by test)."""
    if wire_format != "v2":
        return base
    cached = _WIRE_CLS_CACHE.get((base, wire_format))
    if cached is not None:
        return cached
    if issubclass(base, TcpChannel):
        mixin: type = _WireV2TcpMixin
    elif issubclass(base, ShmChannel):  # before QueueChannel: Shm subclasses it
        mixin = _WireV2ShmMixin
    elif issubclass(base, QueueChannel):
        mixin = _WireV2QueueMixin
    else:
        raise ValueError(f"no v2 wire variant for {base.__name__}")
    cls = type("V2" + base.__name__, (mixin, base), {"__module__": __name__})
    _WIRE_CLS_CACHE[(base, wire_format)] = cls
    return cls


class TcpListener:
    """Trainer-side accept endpoint: players greet with a hello frame
    carrying their player id; a known id reconnecting is adopted into its
    existing channel (see :meth:`TcpChannel.adopt_socket`)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        window: int = 2,
        compress_min: int = 0,
        integrity: str = "off",
        max_frame_bytes: int = TCP_MAX_FRAME_BYTES,
        tracing: str = "off",
        wire_format: str = "v1",
        coalesce_ms: float = 0.0,
    ):
        self._srv = socket.create_server((host, port), backlog=64)
        self._srv.settimeout(0.5)
        self.address: Tuple[str, int] = self._srv.getsockname()[:2]
        self._window = window
        self._compress_min = compress_min
        self._integrity = str(integrity)
        self._tracing = str(tracing)
        self._wire_format = str(wire_format)
        self._coalesce_ms = float(coalesce_ms)
        self._max_frame_bytes = int(max_frame_bytes)
        self._channels: Dict[int, TcpChannel] = {}
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, name="sheeprl-tcp-accept", daemon=True)
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        self._leak_token = leak_registry.register(
            "thread", "sheeprl-tcp-accept", self._thread, where=f"TcpListener {self.address}"
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        pool = _BufferPool()
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                sock.settimeout(10.0)
                tag, _, extra, _, _, _ = _read_frame(sock, pool, self._max_frame_bytes)
                if tag != _HELLO_TAG:
                    raise ConnectionResetError(f"expected hello, got {tag!r}")
                pid = int(extra[0])
            except (OSError, ConnectionError, pickle.UnpicklingError, IndexError, ValueError):
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._cond:
                existing = self._channels.get(pid)
                if existing is not None:
                    existing.adopt_socket(sock)
                else:
                    base = CrcTcpChannel if self._integrity != "off" else TcpChannel
                    base = wire_channel_cls(base, self._wire_format)
                    cls = flight.channel_cls(base, self._tracing)
                    kw = {}
                    if self._wire_format == "v2":
                        kw["coalesce_ms"] = self._coalesce_ms
                    self._channels[pid] = cls(
                        sock=sock,
                        player_id=pid,
                        window=self._window,
                        compress_min=self._compress_min,
                        reconnect=False,
                        track_resend=True,
                        max_frame_bytes=self._max_frame_bytes,
                        **kw,
                    )
                self._cond.notify_all()

    def channel(self, player_id: int, timeout: float = 60.0, peer_alive=None) -> TcpChannel:
        """Block until ``player_id`` has connected (polling ``peer_alive``
        so a player that died before dialing surfaces as such)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while player_id not in self._channels:
                if peer_alive is not None and not peer_alive():
                    raise PeerDiedError(f"player[{player_id}]", "died before connecting")
                if not self._cond.wait(timeout=min(0.5, max(deadline - time.monotonic(), 0.01))):
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"player {player_id} never connected")
            return self._channels[player_id]

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)
        for ch in self._channels.values():
            ch.close()
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        leak_registry.unregister(getattr(self, "_leak_token", None))


# ------------------------------------------------------------ spec + hub
class ChannelSpec:
    """Picklable recipe for the PLAYER side of one channel (rides the
    spawn args; sockets cannot, queues can only as Process arguments)."""

    def __init__(
        self,
        backend: str,
        player_id: int,
        *,
        to_trainer_q=None,
        to_player_q=None,
        data_free_q=None,
        resp_free_q=None,
        address: Optional[Tuple[str, int]] = None,
        window: int = 2,
        min_bytes: int = 65536,
        compress_min: int = 0,
        poll_s: float = 0.5,
        integrity: str = "off",
        max_frame_bytes: int = TCP_MAX_FRAME_BYTES,
        tracing: str = "off",
        wire_format: str = "v1",
        coalesce_ms: float = 0.0,
    ):
        self.backend = backend
        self.player_id = int(player_id)
        self.to_trainer_q = to_trainer_q
        self.to_player_q = to_player_q
        self.data_free_q = data_free_q
        self.resp_free_q = resp_free_q
        self.address = address
        self.window = window
        self.min_bytes = min_bytes
        self.compress_min = compress_min
        self.poll_s = poll_s
        self.integrity = integrity
        self.max_frame_bytes = int(max_frame_bytes)
        self.tracing = tracing
        self.wire_format = str(wire_format)
        self.coalesce_ms = float(coalesce_ms)

    def player_channel(self, peer_alive=None, who: str = "trainer") -> Channel:
        """Build the player-side endpoint (call INSIDE the child).  With
        ``integrity=off`` the UNDECORATED pre-integrity classes are
        constructed — zero overhead by construction (PR-9 pattern); the
        same holds for ``tracing=off`` vs the flight-traced variants and
        ``wire_format=v1`` vs the v2 wire classes."""
        crc = getattr(self, "integrity", "off") != "off"
        tracing = getattr(self, "tracing", "off")
        wf = getattr(self, "wire_format", "v1")
        if self.backend == "tcp":
            base = wire_channel_cls(CrcTcpChannel if crc else TcpChannel, wf)
            cls = flight.channel_cls(base, tracing)
            kw = {"coalesce_ms": getattr(self, "coalesce_ms", 0.0)} if wf == "v2" else {}
            return cls(
                address=self.address,
                player_id=self.player_id,
                window=self.window,
                compress_min=self.compress_min,
                reconnect=True,
                peer_alive=peer_alive,
                who=who,
                poll_s=self.poll_s,
                max_frame_bytes=getattr(self, "max_frame_bytes", TCP_MAX_FRAME_BYTES),
                **kw,
            )
        if self.backend == "shm":
            base = wire_channel_cls(CrcShmChannel if crc else ShmChannel, wf)
            cls = flight.channel_cls(base, tracing)
            return cls(
                self.to_trainer_q,
                self.to_player_q,
                self.data_free_q,
                self.resp_free_q,
                window=self.window,
                min_bytes=self.min_bytes,
                peer_alive=peer_alive,
                who=who,
                poll_s=self.poll_s,
            )
        base = wire_channel_cls(CrcQueueChannel if crc else QueueChannel, wf)
        cls = flight.channel_cls(base, tracing)
        return cls(
            self.to_trainer_q, self.to_player_q, peer_alive=peer_alive, who=who, poll_s=self.poll_s
        )


class TransportHub:
    """Trainer-side owner of all per-player channels."""

    def __init__(
        self,
        backend: str,
        listener: Optional[TcpListener],
        channels: Dict[int, Channel],
        *,
        ctx=None,
        window: int = 2,
        min_bytes: int = 65536,
        compress_min: int = 0,
        poll_s: float = 0.5,
        integrity: str = "off",
        max_frame_bytes: int = TCP_MAX_FRAME_BYTES,
        tracing: str = "off",
        wire_format: str = "v1",
        coalesce_ms: float = 0.0,
    ):
        self.backend = backend
        self._listener = listener
        self._channels = channels
        self._ctx = ctx
        self._window = window
        self._min_bytes = min_bytes
        self._compress_min = compress_min
        self._poll_s = poll_s
        self._integrity = integrity
        self._max_frame_bytes = int(max_frame_bytes)
        self._tracing = tracing
        self._wire_format = str(wire_format)
        self._coalesce_ms = float(coalesce_ms)

    def channel(self, player_id: int, timeout: float = 120.0, peer_alive=None) -> Channel:
        if self._listener is not None and player_id not in self._channels:
            ch = self._listener.channel(player_id, timeout=timeout, peer_alive=peer_alive)
            self._channels[player_id] = ch
        return self._channels[player_id]

    def respawn_spec(self, player_id: int) -> ChannelSpec:
        """A fresh :class:`ChannelSpec` for restarting player
        ``player_id`` after its process died (the supervisor's half of the
        rejoin path).

        - ``tcp``: the spec just names the listener address — the restarted
          player dials in and the listener adopts the fresh socket into the
          EXISTING trainer channel (reviving it if it was marked dead);
        - ``queue``/``shm``: the dead process may have left half-consumed
          frames (or, for shm, leaked ring slots it held) in the old
          endpoints, so those are torn down and a brand-new queue/ring pair
          is built; callers must re-fetch :meth:`channel` afterwards."""
        if self.backend == "tcp":
            return ChannelSpec(
                "tcp",
                player_id,
                address=self._listener.address,
                window=self._window,
                compress_min=self._compress_min,
                poll_s=self._poll_s,
                integrity=self._integrity,
                max_frame_bytes=self._max_frame_bytes,
                tracing=self._tracing,
                wire_format=self._wire_format,
                coalesce_ms=self._coalesce_ms,
            )
        old = self._channels.pop(player_id, None)
        if old is not None:
            try:
                old.close()
            except Exception:
                pass
        to_t = self._ctx.Queue(maxsize=self._window + 2)
        to_p = self._ctx.Queue(maxsize=self._window + 2)
        data_free = self._ctx.Queue() if self.backend == "shm" else None
        resp_free = self._ctx.Queue() if self.backend == "shm" else None
        spec = ChannelSpec(
            self.backend,
            player_id,
            to_trainer_q=to_t,
            to_player_q=to_p,
            data_free_q=data_free,
            resp_free_q=resp_free,
            window=self._window,
            min_bytes=self._min_bytes,
            poll_s=self._poll_s,
            integrity=self._integrity,
            tracing=self._tracing,
            wire_format=self._wire_format,
            coalesce_ms=self._coalesce_ms,
        )
        crc = self._integrity != "off"
        if self.backend == "shm":
            base = wire_channel_cls(CrcShmChannel if crc else ShmChannel, self._wire_format)
            cls = flight.channel_cls(base, self._tracing)
            self._channels[player_id] = cls(
                to_p,
                to_t,
                resp_free,
                data_free,
                window=self._window,
                min_bytes=self._min_bytes,
                who=f"player[{player_id}]",
                poll_s=self._poll_s,
            )
        else:
            base = wire_channel_cls(CrcQueueChannel if crc else QueueChannel, self._wire_format)
            cls = flight.channel_cls(base, self._tracing)
            self._channels[player_id] = cls(
                to_p, to_t, who=f"player[{player_id}]", poll_s=self._poll_s
            )
        return spec

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        if self._listener is not None:
            self._listener.close()


def make_transport(
    ctx,
    backend: str,
    num_players: int,
    *,
    window: int = 2,
    min_bytes: int = 65536,
    compress_min: int = 0,
    host: str = "127.0.0.1",
    port: int = 0,
    poll_s: float = 0.5,
    integrity: str = "off",
    max_frame_bytes: int = TCP_MAX_FRAME_BYTES,
    tracing: str = "off",
    wire_format: str = "v1",
    coalesce_ms: float = 0.0,
) -> Tuple[TransportHub, List[ChannelSpec]]:
    """Create the trainer hub + per-player specs for ``backend``.

    Queues must exist before the spawn (they cannot ride another queue),
    so this runs in the trainer before any player process starts.
    ``integrity`` (``algo.transport_integrity``) selects the checksummed
    channel variants; ``tracing`` (``metric.tracing``) the flight-traced
    ones; ``wire_format`` (``algo.wire_format``) the v2 scatter-gather
    wire classes; ``off``/``v1`` constructs the undecorated classes.
    """
    if backend not in _BACKENDS:
        raise ValueError(f"unknown transport backend {backend!r}; known: {_BACKENDS}")
    crc = integrity != "off"
    specs: List[ChannelSpec] = []
    channels: Dict[int, Channel] = {}
    listener = None
    if backend == "tcp":
        listener = TcpListener(
            host,
            port,
            window=window,
            compress_min=compress_min,
            integrity=integrity,
            max_frame_bytes=max_frame_bytes,
            tracing=tracing,
            wire_format=wire_format,
            coalesce_ms=coalesce_ms,
        )
        for pid in range(num_players):
            specs.append(
                ChannelSpec(
                    "tcp",
                    pid,
                    address=listener.address,
                    window=window,
                    compress_min=compress_min,
                    poll_s=poll_s,
                    integrity=integrity,
                    max_frame_bytes=max_frame_bytes,
                    tracing=tracing,
                    wire_format=wire_format,
                    coalesce_ms=coalesce_ms,
                )
            )
    else:
        for pid in range(num_players):
            to_t = ctx.Queue(maxsize=window + 2)
            to_p = ctx.Queue(maxsize=window + 2)
            data_free = ctx.Queue() if backend == "shm" else None
            resp_free = ctx.Queue() if backend == "shm" else None
            specs.append(
                ChannelSpec(
                    backend,
                    pid,
                    to_trainer_q=to_t,
                    to_player_q=to_p,
                    data_free_q=data_free,
                    resp_free_q=resp_free,
                    window=window,
                    min_bytes=min_bytes,
                    poll_s=poll_s,
                    integrity=integrity,
                    tracing=tracing,
                    wire_format=wire_format,
                )
            )
            if backend == "shm":
                # trainer sends through ITS ring (resp_free) and releases
                # rollout slots back into the player's ring (data_free)
                base = wire_channel_cls(CrcShmChannel if crc else ShmChannel, wire_format)
                cls = flight.channel_cls(base, tracing)
                channels[pid] = cls(
                    to_p,
                    to_t,
                    resp_free,
                    data_free,
                    window=window,
                    min_bytes=min_bytes,
                    who=f"player[{pid}]",
                    poll_s=poll_s,
                )
            else:
                base = wire_channel_cls(CrcQueueChannel if crc else QueueChannel, wire_format)
                qcls = flight.channel_cls(base, tracing)
                channels[pid] = qcls(to_p, to_t, who=f"player[{pid}]", poll_s=poll_s)
    hub = TransportHub(
        backend,
        listener,
        channels,
        ctx=ctx,
        window=window,
        min_bytes=min_bytes,
        compress_min=compress_min,
        poll_s=poll_s,
        integrity=integrity,
        max_frame_bytes=max_frame_bytes,
        tracing=tracing,
        wire_format=wire_format,
        coalesce_ms=coalesce_ms,
    )
    return hub, specs


# ------------------------------------------------------------------ fan-in
class FanIn:
    """Trainer-side N-player shard assembly with per-player liveness AND
    runtime pool membership.

    ``gather`` returns one ``data`` frame per live player for the next
    round (FIFO per channel keeps per-player rounds ordered; cross-player
    arrival order does not matter — callers assemble in player-id order).
    A player death SHRINKS the fan-in: the pid moves to ``dead``, a shrink
    event is recorded for telemetry, and the round completes with the
    survivors.  Only losing the LAST live player raises (and even that is
    survivable while a rejoin is pending).

    The pool GROWS through :meth:`begin_join`: a (re)started player is
    polled opportunistically — its data frames are stashed, never awaited
    — until one lands whose seq matches the round being assembled; that
    round it GRADUATES to full membership (a ``player_rejoin`` event).
    Joiners therefore can never stall the survivors, and a joiner that
    came up mid-round simply lands one round later."""

    def __init__(self, channels: Dict[int, Channel], *, env_steps_per_frame: Optional[Dict[int, int]] = None):
        self.channels = dict(channels)
        self.stopped: set = set()
        self.dead: Dict[int, str] = {}
        self.joining: Dict[int, float] = {}  # pid -> join start (monotonic)
        self.events: List[Dict[str, Any]] = []  # shrink/grow log (rides telemetry)
        self.rejoins = 0
        self.rollbacks = 0  # sentinel rollback-to-last-good broadcast rounds
        self.last_seen: Dict[int, float] = {}  # any-frame liveness (heartbeats)
        self.lag_hist: Dict[int, int] = {}  # behavior-policy lag -> rounds seen
        self._lag_by_pid: Dict[int, int] = {}
        # per-player live-metrics summaries (ISSUE 15): players piggyback
        # their compact LivePlane.beat() dict on the data frames they
        # already send; the loop hands it in via note_summary and the
        # fleet view rides stats() to the lead's telemetry + /status
        self.fleet: Dict[int, Dict[str, Any]] = {}
        self._steps_per_frame = env_steps_per_frame or {}
        self._last_data_seq: Dict[int, int] = {}
        self._stash: Dict[int, Frame] = {}  # joiners' early data frames
        self._seen_since_join: set = set()  # joiners that have sent anything yet
        self._t0 = time.monotonic()
        self._frames: Dict[int, int] = {pid: 0 for pid in self.channels}

    def _record_event(self, entry: Dict[str, Any]) -> None:
        """One pool event: the bounded telemetry log AND (when tracing)
        the flight recorder's fleet track share every call site."""
        self.events.append(entry)
        flight.fleet_event(entry["event"], **{k: v for k, v in entry.items() if k != "event"})

    # ------------------------------------------------------------ liveness
    @property
    def live(self) -> List[int]:
        """Full (round-mandatory) members: not dead, not stopped, not
        still joining."""
        return sorted(
            pid
            for pid in self.channels
            if pid not in self.dead and pid not in self.stopped and pid not in self.joining
        )

    def mark_dead(self, pid: int, reason: str) -> None:
        if pid in self.dead or pid in self.stopped:
            return
        # a player that exited CLEANLY finished its work: its final "stop"
        # frame can be destroyed by a TCP reset (unread inbound data at
        # close), so a zero exit code counts as a stop, not a death
        ch = self.channels.get(pid)
        detail = ""
        if ch is not None and ch.detail_fn is not None:
            try:
                detail = ch.detail_fn() or ""
            except Exception:
                detail = ""
        self.joining.pop(pid, None)
        stale = self._stash.pop(pid, None)
        if stale is not None:
            stale.release()
        if "exitcode=0" in detail.replace(" ", ""):
            self.stopped.add(pid)
            return
        self.dead[pid] = reason
        self._record_event(
            {"event": "player_dead", "player": pid, "reason": reason, "live": len(self.live)}
        )

    def begin_join(self, pid: int, channel: Optional[Channel] = None, steps_per_frame: Optional[int] = None) -> None:
        """Admit player ``pid`` to the pool as a JOINER (a restarted dead
        player taking back its slot, or a brand-new pid growing the pool).
        It becomes round-mandatory only once a data frame of its own lands
        on the round being gathered."""
        if channel is not None:
            self.channels[pid] = channel
        self.dead.pop(pid, None)
        self.stopped.discard(pid)
        self._seen_since_join.discard(pid)
        now = time.monotonic()
        self.joining[pid] = now
        self.last_seen[pid] = now
        self._frames.setdefault(pid, 0)
        if steps_per_frame:
            self._steps_per_frame[pid] = steps_per_frame
        self._record_event({"event": "player_join", "player": pid, "live": len(self.live)})

    def note_lag(self, pid: int, lag: int) -> None:
        """Record one round's behavior-policy lag for ``pid`` (the V-trace
        soft-bound telemetry: how stale the weights this shard acted with
        were, in update rounds)."""
        lag = max(0, int(lag))
        self.lag_hist[lag] = self.lag_hist.get(lag, 0) + 1
        self._lag_by_pid[pid] = lag

    def note_summary(self, pid: int, summary: Any) -> None:
        """Record one player's piggybacked live-metrics summary (the
        extra slot after the behavior seq on ``data`` frames; tolerant of
        anything that is not a dict — an old player simply never sends
        one)."""
        if isinstance(summary, dict):
            self.fleet[pid] = summary

    def _require_live(self, who: str = "player") -> None:
        if not self.live and not self.stopped and not self.joining:
            detail = "; ".join(f"player[{p}]: {r}" for p, r in self.dead.items())
            raise PeerDiedError(who, detail)

    # -------------------------------------------------------------- gather
    def _poll_joining(self, data_tag: str, on_control) -> None:
        """Opportunistic drain of joiners' channels: data frames are
        stashed for graduation, control frames flow as usual; a joiner is
        never awaited."""
        for pid in list(self.joining):
            ch = self.channels[pid]
            try:
                frame = ch.recv(timeout=0.01)
            except queue_mod.Empty:
                continue
            except FrameCorruptError as e:
                # unrecoverable corruption (retransmit exhausted): the
                # frame is lost, the channel itself stays usable
                self._record_event(
                    {"event": "frame_corrupt_dropped", "player": pid, "detail": str(e)}
                )
                continue
            except PeerDiedError as e:
                self.mark_dead(pid, f"died while joining: {e}")
                continue
            self.last_seen[pid] = time.monotonic()
            self._seen_since_join.add(pid)
            if frame.tag == "stop":
                self.joining.pop(pid, None)
                self.stopped.add(pid)
                frame.release()
            elif frame.tag == data_tag:
                old = self._stash.pop(pid, None)
                if old is not None:
                    old.release()
                self._stash[pid] = frame
            elif frame.tag == HB_TAG:
                frame.release()
            elif on_control is not None:
                on_control(pid, frame)
            else:
                frame.release()

    def gather(
        self,
        *,
        timeout: float,
        data_tag: str = "data",
        on_control: Optional[Callable[[int, Frame], None]] = None,
    ) -> Tuple[Optional[int], "OrderedDict[int, Frame]"]:
        """Collect the next ``data_tag`` frame from every live player (plus
        any joiner whose stashed frame matches the round).

        Returns ``(seq, frames-by-pid sorted)``; ``(None, {})`` once every
        player has stopped.  Control frames (anything except ``data_tag``,
        ``stop`` and heartbeats) are handed to ``on_control`` as they
        arrive."""
        got: Dict[int, Frame] = {}
        deadline = time.monotonic() + timeout
        while True:
            self._poll_joining(data_tag, on_control)
            pending = [pid for pid in self.live if pid not in got]
            if not pending:
                if got or not self.joining:
                    break
                if self._stash:
                    # every full member is gone but (re)joins are pending:
                    # the round forms from the joiners' stashed frames
                    break
            for pid in pending:
                ch = self.channels[pid]
                try:
                    frame = ch.recv(timeout=0.05)
                except queue_mod.Empty:
                    continue
                except FrameCorruptError as e:
                    self._record_event(
                        {"event": "frame_corrupt_dropped", "player": pid, "detail": str(e)}
                    )
                    continue
                except PeerDiedError as e:
                    self.mark_dead(pid, str(e))
                    continue
                self.last_seen[pid] = time.monotonic()
                if frame.tag == "stop":
                    self.stopped.add(pid)
                    frame.release()
                elif frame.tag == HB_TAG:
                    frame.release()
                elif frame.tag == data_tag:
                    if frame.seq >= 0 and frame.seq <= self._last_data_seq.get(pid, -1):
                        frame.release()  # reconnect replay duplicate
                        continue
                    self._last_data_seq[pid] = frame.seq
                    if data_tag == "data":  # init/control rounds don't count toward sps
                        self._frames[pid] = self._frames.get(pid, 0) + 1
                    got[pid] = frame
                elif on_control is not None:
                    on_control(pid, frame)
                else:
                    frame.release()
            if time.monotonic() > deadline:
                for f in got.values():
                    f.release()
                raise queue_mod.Empty
        self._require_live()
        if not got and not self._stash:
            return None, OrderedDict()
        if got:
            seqs = sorted({f.seq for f in got.values()})
            if len(seqs) != 1:
                raise RuntimeError(f"fan-in round desync: players delivered seqs {seqs}")
            round_seq = seqs[0]
        else:
            round_seq = min(f.seq for f in self._stash.values())
        # graduate joiners whose stashed frame matches this round; release
        # stale stashes (the joiner resyncs its clock off the params
        # broadcasts it keeps receiving and lands on a later round)
        for pid in sorted(list(self._stash)):
            frame = self._stash[pid]
            if frame.seq == round_seq:
                del self._stash[pid]
                self.joining.pop(pid, None)
                self._last_data_seq[pid] = frame.seq
                if data_tag == "data":
                    self._frames[pid] = self._frames.get(pid, 0) + 1
                got[pid] = frame
                self.rejoins += 1
                self._record_event(
                    {"event": "player_rejoin", "player": pid, "round": round_seq, "live": len(self.live)}
                )
            elif frame.seq < round_seq:
                del self._stash[pid]
                frame.release()
        return round_seq, OrderedDict(sorted(got.items()))

    # ----------------------------------------------------------- broadcast
    def broadcast(
        self,
        tag: str,
        arrays,
        seq: int = -1,
        extra_fn: Optional[Callable[[int], Tuple]] = None,
        timeout: float = 600.0,
    ) -> None:
        """Send the same payload to every live AND joining player (a
        joiner needs the params flow to sync its clock before it
        graduates — but only once it has dialed in and sent SOMETHING, or
        a tcp send would stall the round on its boot; per-player extras
        via ``extra_fn`` — e.g. metrics/opt-state for the lead only).  A
        send failure marks that player dead and the broadcast continues."""
        targets = self.live + sorted(p for p in self.joining if p in self._seen_since_join)
        if seq >= 0:
            # the fleet timeline's publish edge: every player's matching
            # broadcast_adopt event (ParamsFollower) subtracts this
            # timestamp (clock-corrected) for the per-seq latency metric
            flight.fleet_event("broadcast_publish", tag=tag, seq=int(seq), n=len(targets))
        # ledger: the trainer's wire time fanning the payload out (credit
        # stalls on a slow player land here, not in compute)
        with flight.span("broadcast", tag=tag, n=len(targets)):
            for pid in targets:
                extra = extra_fn(pid) if extra_fn is not None else ()
                try:
                    self.channels[pid].send(tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)
                except (PeerDiedError, queue_mod.Full, OSError) as e:
                    self.mark_dead(pid, f"broadcast failed: {e}")
        self._require_live()

    def note_rollback(self, round_seq: int) -> None:
        """Record a training-sentinel rollback: the next broadcast of this
        round ships the RESTORED params, and every live player re-adopts
        them through its ParamsFollower — no special protocol round, but
        the event must be visible in the transport telemetry."""
        self.rollbacks += 1
        self._record_event(
            {"event": "rollback", "round": round_seq, "rollbacks": self.rollbacks}
        )

    def send_to(self, pid: int, tag: str, arrays=None, extra=(), seq=-1, timeout: float = 600.0) -> None:
        try:
            self.channels[pid].send(tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)
        except (PeerDiedError, queue_mod.Full, OSError) as e:
            self.mark_dead(pid, f"send failed: {e}")

    # ----------------------------------------------------------- telemetry
    def stats(self, backend: str) -> Dict[str, Any]:
        """One snapshot for the telemetry sink's ``transport`` key."""
        elapsed = max(time.monotonic() - self._t0, 1e-6)
        now = time.monotonic()
        per_player: Dict[str, Any] = {}
        bytes_total = 0
        for pid, ch in self.channels.items():
            bytes_total += ch.bytes_recv + ch.bytes_sent
            entry: Dict[str, Any] = {
                "frames": self._frames.get(pid, 0),
                "bytes_in": ch.bytes_recv,
                "bytes_out": ch.bytes_sent,
                "alive": pid not in self.dead and pid not in self.stopped,
            }
            spf = self._steps_per_frame.get(pid)
            if spf:
                entry["sps"] = round(self._frames.get(pid, 0) * spf / elapsed, 2)
            depth = ch.depth()
            if depth is not None:
                entry["depth"] = depth
            if pid in self.last_seen:
                entry["last_seen_age_s"] = round(now - self.last_seen[pid], 2)
            if pid in self._lag_by_pid:
                entry["lag"] = self._lag_by_pid[pid]
            per_player[str(pid)] = entry
        # per-tag byte/rate breakdown (ISSUE 19): which logical stream —
        # data shards, params broadcasts, heartbeats, live summaries —
        # owns the wire.  Merged across player channels; control tags
        # (``__``-prefixed) are excluded at count time.
        bytes_by_tag: Dict[str, int] = {}
        frames_by_tag: Dict[str, int] = {}
        compress_skipped = 0
        for ch in self.channels.values():
            for tag, n in ch.bytes_by_tag.items():
                bytes_by_tag[tag] = bytes_by_tag.get(tag, 0) + n
            for tag, n in ch.frames_by_tag.items():
                frames_by_tag[tag] = frames_by_tag.get(tag, 0) + n
            compress_skipped += ch.compress_skipped
        out = {
            "backend": backend,
            "players": per_player,
            "num_players": len(self.channels),
            "live": len(self.live),
            "joining": len(self.joining),
            "deaths": len(self.dead),
            "rejoins": self.rejoins,
            "rollbacks": self.rollbacks,
            "lag_hist": {str(k): v for k, v in sorted(self.lag_hist.items())},
            "bytes_per_s": round(bytes_total / elapsed, 1),
            "fan_in_depth": sum(
                ch.depth() or 0 for pid, ch in self.channels.items() if pid not in self.dead
            ),
        }
        if bytes_by_tag:
            out["bytes_by_tag"] = dict(sorted(bytes_by_tag.items()))
            out["frames_per_s_by_tag"] = {
                tag: round(n / elapsed, 2) for tag, n in sorted(frames_by_tag.items())
            }
            out["top_stream"] = max(bytes_by_tag, key=bytes_by_tag.get)
        if compress_skipped:
            out["compress_skipped"] = compress_skipped
        if self.fleet:
            out["fleet"] = {str(pid): dict(s) for pid, s in sorted(self.fleet.items())}
        return out

    def close(self) -> None:
        for ch in self.channels.values():
            ch.close()


# ------------------------------------------------------------ params side
class ParamsFollower:
    """Player-side fixed-lag adoption of the seq-numbered params broadcast.

    Rollout ``k`` acts on EXACTLY the params of update ``k - 1 - lag``
    (during warmup: the initial broadcast) — deterministic and bounded,
    like PR 3's in-process ``_ParamsBus`` but across the transport.  The
    trainer broadcasts every version in order, so waiting for the exact
    target sequence is a drain, not a race."""

    def __init__(
        self,
        channel: Channel,
        *,
        lag: int,
        initial_seq: int,
        timeout: float = 600.0,
        on_stale: Optional[Callable[[Frame], None]] = None,
        digest_slot: Optional[int] = None,
        digest_fn: Optional[Callable] = None,
    ):
        if lag < 0:
            raise ValueError(f"decoupled_params_lag must be >= 0, got {lag}")
        self.lag = int(lag)
        self._chan = channel
        self._initial = int(initial_seq)
        self._timeout = float(timeout)
        self.current_seq = int(initial_seq)
        self.staleness_log: List[Tuple[int, int]] = []  # (round, staleness)
        self._pending: "deque[Frame]" = deque()
        # called (pre-release) for fresh versions drained past without
        # adoption — a checkpoint barrier skipping the lag lets the lead
        # still account their metrics
        self.on_stale = on_stale
        # digest-verified adoption (algo.transport_integrity=digest): the
        # trainer ships a pytree content digest in extra[digest_slot];
        # adoption recomputes it over the received arrays and a mismatch
        # SKIPS that broadcast (treated as never arrived — the next one
        # re-syncs, so the fixed/soft-lag walk is preserved, one round of
        # extra staleness at most)
        self.digest_slot = digest_slot
        # the digest implementation must MATCH the trainer's (host
        # content_digest by default; the batched device digest when
        # algo.params_digest_device routes both ends through it)
        self.digest_fn = digest_fn or content_digest
        self.digest_skips = 0

    def _digest_ok(self, frame: Frame) -> bool:
        slot = self.digest_slot
        if slot is None or not frame.arrays:
            return True
        if len(frame.extra) <= slot or frame.extra[slot] is None:
            return True  # sender did not digest this frame (e.g. crc-only mode)
        st = integrity_stats()
        st.params_digest_checked += 1
        if self.digest_fn(list(frame.arrays.items())) == int(frame.extra[slot]):
            return True
        st.params_digest_mismatch += 1
        self.digest_skips += 1
        flight.fleet_event("params_digest_skip", seq=int(frame.seq))
        return False

    def _next_frame(self, timeout: float) -> Frame:
        if self._pending:
            return self._pending.popleft()
        return self._chan.recv(timeout=timeout)

    def poll_control(self, tag: str) -> Optional[Frame]:
        """Non-blocking sweep for a control frame ``tag`` (e.g. the
        autoscaler's ``retire`` order): checks the stash first, then
        drains whatever is immediately available on the channel, putting
        everything else back on the pending deque IN ORDER so the
        fixed-lag params walk is untouched.  Returns the matching frame
        (caller releases it) or None."""
        for i, frame in enumerate(self._pending):
            if frame.tag == tag:
                del self._pending[i]
                return frame
        stash: List[Frame] = []
        found: Optional[Frame] = None
        while found is None:
            try:
                frame = self._chan.recv(timeout=0.0)
            except (queue_mod.Empty, PeerDiedError):
                break
            if frame.tag == tag:
                found = frame
            else:
                stash.append(frame)
        self._pending.extend(stash)
        return found

    def wait_tag(self, tag: str, timeout: Optional[float] = None) -> Frame:
        """Receive until ``tag`` arrives, stashing params frames for the
        fixed-lag schedule (trainer sends are ordered, but a params
        broadcast may precede the awaited control reply)."""
        deadline = time.monotonic() + (timeout or self._timeout)
        stash: List[Frame] = []
        # ledger: time blocked on the trainer's params/control stream
        with flight.span("params_wait", tag=tag):
            try:
                while True:
                    frame = self._next_frame(max(deadline - time.monotonic(), 0.01))
                    if frame.tag == tag:
                        return frame
                    stash.append(frame)
            finally:
                self._pending.extend(stash)

    def _take_exact(self, target: int, timeout: Optional[float] = None) -> Optional[Frame]:
        """Drain the params stream up to EXACTLY ``target`` (the broadcast
        is ordered, so this is a walk, not a race): reconnect duplicates
        are dropped, fresh intermediate versions go through ``on_stale``.
        Returns None when the target broadcast arrived but failed its
        digest check — the caller keeps its current weights and the next
        round's walk re-syncs (``current_seq`` does not advance)."""
        while True:
            frame = self.wait_tag("params", timeout=timeout)
            if frame.seq <= self.current_seq:
                frame.release()  # reconnect replay duplicate
                continue
            if frame.seq < target:
                if self._digest_ok(frame):
                    self.current_seq = frame.seq
                    if self.on_stale is not None:
                        self.on_stale(frame)
                frame.release()
                continue
            if frame.seq > target:
                raise RuntimeError(
                    f"params broadcast overshot the fixed lag: got seq {frame.seq}, "
                    f"waiting for {target}"
                )
            if not self._digest_ok(frame):
                frame.release()
                return None
            self.current_seq = target
            flight.fleet_event("broadcast_adopt", seq=int(target))
            return frame

    def params_for_round(self, round_k: int) -> Optional[Frame]:
        """The params frame rollout ``round_k`` must act on, or None when
        the fixed-lag target predates the current version (warmup, or a
        checkpoint barrier already jumped ahead: keep the current
        weights).  Caller copies out of the frame and releases it.
        Staleness ``(k-1) - adopted_seq`` is logged either way and is
        bounded by ``lag`` once past warmup."""
        target = round_k - 1 - self.lag
        frame = self._take_exact(target) if target > self.current_seq else None
        self.staleness_log.append((round_k, max(0, (round_k - 1) - self.current_seq)))
        return frame

    def adopt_newest(
        self, round_k: int, max_lag: int, timeout: Optional[float] = None
    ) -> Optional[Frame]:
        """SOFT-bound adoption for the V-trace path: drain every params
        frame that has already arrived and hand back the newest (None when
        nothing fresh arrived — keep acting on the current weights).  The
        call blocks ONLY while acting would exceed ``max_lag`` updates of
        staleness; within the bound a missing broadcast never stalls the
        rollout, because the learner's importance correction absorbs the
        extra lag.  Superseded intermediate versions go through
        ``on_stale`` (the lead still accounts their metrics)."""
        held: List[Frame] = []
        newest: Optional[Frame] = None
        target_min = round_k - 1 - max(0, int(max_lag))
        deadline = time.monotonic() + (timeout or self._timeout)
        # ledger: the soft-lag drain is params-stream waiting (manual
        # enter/exit — the adoption bookkeeping below stays outside)
        wait_span = flight.span("params_wait", tag="params")
        wait_span.__enter__()
        try:
            while True:
                best = newest.seq if newest is not None else self.current_seq
                blocking = best < target_min
                try:
                    frame = self._next_frame(
                        max(deadline - time.monotonic(), 0.01) if blocking else 0.01
                    )
                except queue_mod.Empty:
                    if blocking and time.monotonic() < deadline:
                        continue
                    if blocking:
                        raise RuntimeError(
                            f"params broadcast stalled past the soft lag bound: round "
                            f"{round_k} needs seq >= {target_min}, have {best}"
                        ) from None
                    break
                if frame.tag != "params":
                    held.append(frame)
                    continue
                if frame.seq <= best:
                    frame.release()  # reconnect replay duplicate
                    continue
                if not self._digest_ok(frame):
                    frame.release()  # corrupt broadcast: treated as never arrived
                    continue
                if newest is not None:
                    if self.on_stale is not None:
                        self.on_stale(newest)
                    newest.release()
                newest = frame
        finally:
            self._pending.extend(held)
            wait_span.__exit__(None, None, None)
        if newest is not None:
            self.current_seq = newest.seq
            flight.fleet_event("broadcast_adopt", seq=int(newest.seq))
        self.staleness_log.append((round_k, max(0, (round_k - 1) - self.current_seq)))
        return newest

    def advance_to(self, target_seq: int, timeout: Optional[float] = None) -> Optional[Frame]:
        """Collapse the pipeline to ``target_seq`` (checkpoint barrier:
        the lead player needs the params/opt-state of the update it is
        about to persist; shutdown drain: closing a socket with an UNREAD
        inbound broadcast risks a TCP reset that destroys the in-flight
        frames).  Returns the target frame (None if already adopted)."""
        if target_seq <= self.current_seq:
            return None
        return self._take_exact(target_seq, timeout=timeout)

    def advance_to_at_least(self, target_seq: int, timeout: Optional[float] = None) -> Optional[Frame]:
        """Like :meth:`advance_to` but tolerant of reconnect gaps: a
        params frame LOST to a severed connection is replaced by the
        trainer's replay of its NEWEST broadcast, so the stream may
        legitimately skip past the target — the first frame at or beyond
        it is adopted (the join path's initial weights, where exactness
        would misread a mid-handshake net drop as protocol corruption)."""
        if target_seq <= self.current_seq:
            return None
        while True:
            frame = self.wait_tag("params", timeout=timeout)
            if frame.seq <= self.current_seq:
                frame.release()  # reconnect replay duplicate
                continue
            if not self._digest_ok(frame):
                frame.release()  # corrupt broadcast: wait for the next one
                continue
            if frame.seq < target_seq:
                self.current_seq = frame.seq
                if self.on_stale is not None:
                    self.on_stale(frame)
                frame.release()
                continue
            self.current_seq = frame.seq
            flight.fleet_event("broadcast_adopt", seq=int(frame.seq))
            return frame

    @property
    def max_staleness_seen(self) -> int:
        return max((s for _, s in self.staleness_log), default=0)


class HeartbeatSender:
    """Player-side liveness beacon: a daemon thread sending one array-less
    :data:`HB_TAG` frame every ``interval`` seconds, so the trainer-side
    supervisor can distinguish "slow" from "silent" even for remote (tcp)
    players it has no process handle for.  Send failures are swallowed —
    a dead trainer surfaces through the protocol paths that already
    handle it, not through the heartbeat."""

    def __init__(self, channel: Channel, interval: float = 2.0):
        self._chan = channel
        self._interval = max(0.1, float(interval))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="sheeprl-heartbeat", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._chan.send(HB_TAG, timeout=self._interval)
            except Exception:
                pass

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
