"""Pipelined collect/train: overlap host env stepping with device training.

The coupled on-policy loops (ppo/a2c/ppo_recurrent) serialize the two
halves of every iteration: the host steps the vectorized envs for
``rollout_steps``, then the jitted update consumes the rollout, then the
host steps again.  Under JAX async dispatch the update is ALREADY a
future the moment it is dispatched — the host just never uses that slack.
Podracer-style architectures (Hessel et al., 2021) and EnvPool (Weng et
al., 2022) get their integer-factor speedups from exactly this overlap:
a collector runs iteration t+1's env steps while the device trains on
iteration t.

:class:`PipelinedCollector` implements that overlap as a background
thread with

- **double-buffered rollout storage**: the collector converts + uploads
  (``pack_fn``) its finished rollout into fresh device buffers before the
  next rollout overwrites the host-side ring, and at most ONE packed
  rollout waits in the handoff queue;
- **a params-publish handoff with bounded staleness**: the trainer
  publishes the params produced by iteration t; the collector adopts, at
  each rollout boundary, EXACTLY the params of iteration
  k-1-``max_staleness`` (fixed lag; waits for them if unpublished, keeps
  the initial weights during warmup).  Default ``max_staleness=1`` — a
  rollout acts on weights exactly one update behind the fully-serial
  schedule.  A "newest published wins" adoption would honor the same
  bound but make the adopted version a thread-timing race; the fixed lag
  keeps overlapped runs reproducible given their seed;
- **a sync fallback** (``overlap=False``, config
  ``algo.overlap_collect=false``): the same collect/pack/train code runs
  inline on the caller's thread in the exact pre-pipeline order, so
  runs stay bit-exact with the serial loop for determinism checks.

RNG: the serial path draws per-step policy keys from ``runtime.next_key``
(bit-exact with the pre-pipeline loops).  The overlapped path draws them
from an independent, deterministically-seeded stream
(:class:`KeyStream`): thread interleaving cannot change which keys the
collector sees, and the fixed-lag params handoff (below) pins WHICH
weights each rollout acts on.  Exact float reproducibility across
overlapped runs additionally depends on the backend (concurrent host
uploads/saves on a shared CPU client can reorder allocator/runtime work);
``algo.overlap_collect=false`` is the documented bit-exactness switch.

Thread rules: the collector thread may touch the envs, the player and
the rollout buffer (it is their only user while active); the aggregator,
logger, timer registry and checkpoint manager stay on the caller's
thread — per-step episode events are deferred through the payload and
applied by the caller (:meth:`RolloutPayload.apply_events`).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.obs import flight

__all__ = [
    "KeyStream",
    "PipelinedCollector",
    "RolloutPayload",
    "credit_timer",
    "detach_copy",
    "resolve_overlap_setting",
]


def resolve_overlap_setting(cfg) -> bool:
    """Resolve ``algo.overlap_collect`` (``true``/``false``/``auto``).

    ``auto`` enables the pipeline only where it can win: the collector
    thread needs a host core of its own, and on a single-core host the
    overlap degenerates to time-slicing plus handoff overhead (measured
    0.67-0.81x in BENCH_r05) — those hosts stay on the bit-exact serial
    path.

    With ``algo.env_backend=jax`` the overlap resolves to OFF regardless:
    the fused collect IS the device program — there is no host env work
    left to overlap, and the pipeline thread would only add handoff
    latency.  A one-line notice is emitted when the setting would
    otherwise have enabled it."""
    import os
    import sys

    val = cfg.algo.get("overlap_collect", False)
    is_auto = isinstance(val, str) and val.strip().lower() == "auto"
    resolved = (os.cpu_count() or 1) > 1 if is_auto else bool(val)
    if str(cfg.algo.get("env_backend", "host") or "host").lower() == "jax":
        if resolved:
            print(
                "overlap_collect resolved to off: env_backend=jax runs the fused "
                "device collect — no host env stepping left to overlap.",
                file=sys.stderr,
            )
        return False
    return resolved


class KeyStream:
    """Independent PRNG-key stream for the collector thread.

    Mirrors ``MeshRuntime.next_key`` (raw uint32[2] key data from a host
    PCG64) but over its own generator, so the collector and trainer can
    draw keys concurrently without racing the runtime's shared stream —
    and an overlapped run draws the same keys every time given its seed.
    """

    def __init__(self, seed: int, tag: int = 0xC011EC7):
        self._rng = np.random.Generator(np.random.PCG64([int(seed) & 0xFFFFFFFF, int(tag)]))
        self._live = None

    def __call__(self, num: int = 1):
        data = self._rng.integers(0, 2**32, size=(num, 2), dtype=np.uint32)
        # retain the buffer until the NEXT draw: the key is usually passed
        # as a call-expression temporary, and CPU device_put may zero-copy
        # alias it — freeing it before the async consumer runs lets the
        # allocator recycle the memory mid-computation.  By the next draw
        # the previous step's computation has been forced by its caller.
        self._live = data
        return data[0] if num == 1 else [row for row in data]


def credit_timer(name: str, seconds: float, metric_cls=None, **metric_kwargs: Any) -> None:
    """Account ``seconds`` to a named timer without entering its context.

    The overlapped collector cannot use ``with timer(...)`` — the caller
    thread's ``timer.reset()`` at a log boundary races the collector's
    ``__exit__`` — so it accumulates wall-clock into the payload and the
    caller credits it here, on the thread that owns the timer registry.
    """
    from sheeprl_tpu.utils.metric import SumMetric
    from sheeprl_tpu.utils.timer import timer

    if timer.disabled:
        return
    timer(name, metric_cls or SumMetric, **metric_kwargs)  # registers if missing
    timer.timers[name].update(seconds)
    buf = timer.samples.get(name)
    if buf is None:
        from collections import deque

        buf = timer.samples[name] = deque(maxlen=timer.max_samples)
    buf.append(seconds)


class RolloutPayload:
    """One collected iteration, as handed from the collector to the trainer.

    ``data``/``next_obs`` are whatever ``pack_fn`` produced (device-placed
    arrays on both the sync and overlapped paths).  ``events`` holds
    deferred per-step episode records ``(policy_step, env_idx, reward,
    length)`` on the overlapped path (empty on the sync path, where the
    collector applies them inline exactly like the pre-pipeline loops).
    """

    __slots__ = (
        "iter_num",
        "data",
        "next_obs",
        "extras",
        "events",
        "env_seconds",
        "policy_step_end",
        "params_version",
        "host_refs",
    )

    def __init__(self, iter_num: int, data: Any = None, next_obs: Any = None):
        self.iter_num = iter_num
        self.data = data
        self.next_obs = next_obs
        self.extras: Dict[str, Any] = {}
        self.events: List[Tuple[int, int, float, float]] = []
        self.env_seconds: float = 0.0
        self.policy_step_end: int = 0
        self.params_version: int = -1
        # pack_fn parks its host-side upload sources here: CPU device_put
        # zero-copy aliases aligned numpy buffers WITHOUT keeping them
        # alive, so the arrays must outlive the update that reads them —
        # the payload does (see :meth:`PipelinedCollector.publish`)
        self.host_refs: List[Any] = []

    def apply_events(self, aggregator, runtime, log_level: int) -> None:
        """Apply deferred episode events on the caller's thread (overlap
        path); the sync path recorded nothing here."""
        if not self.events:
            return
        for policy_step, env_idx, ep_rew, ep_len in self.events:
            if log_level > 0:
                if aggregator and "Rewards/rew_avg" in aggregator:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                if aggregator and "Game/ep_len_avg" in aggregator:
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{env_idx}={ep_rew}")
        if self.env_seconds > 0.0:
            from sheeprl_tpu.utils.metric import SumMetric

            credit_timer("Time/env_interaction_time", self.env_seconds, SumMetric, sync_on_compute=False)
            self.env_seconds = 0.0


class _ParamsBus:
    """Versioned params mailbox between the trainer and the collector.

    Keeps the last few published versions so the collector can adopt an
    EXACT version (the overlap path's fixed lag — see
    :meth:`PipelinedCollector._worker`): adopting "whatever is newest"
    would make which-params-collected-rollout-k a thread-timing race and
    overlapped runs irreproducible.
    """

    def __init__(self, initial_version: int, keep: int = 3):
        self._cond = threading.Condition()
        self._version = initial_version
        self._keep = int(keep)
        self._store: Dict[int, Any] = {}

    def publish(self, version: int, params: Any) -> None:
        with self._cond:
            if version > self._version:
                self._version = version
                self._store[version] = params
                for v in [v for v in self._store if v <= version - self._keep]:
                    del self._store[v]
                self._cond.notify_all()

    def latest(self) -> Tuple[int, Any]:
        with self._cond:
            return self._version, self._store.get(self._version)

    def take_exact(self, version: int, stop: threading.Event, poll_s: float = 0.05) -> Tuple[bool, Any]:
        """Block until ``version`` is published, return ``(True, params)``
        and prune strictly older versions; ``(False, None)`` on ``stop``
        or when ``version`` predates every publish (warmup: the player
        keeps its initial weights)."""
        with self._cond:
            while version not in self._store:
                if self._version >= version or stop.is_set():
                    # warmup (nothing that old was ever stored) or shutdown
                    return False, None
                self._cond.wait(timeout=poll_s)
            params = self._store[version]
            for v in [v for v in self._store if v < version]:
                del self._store[v]
            return True, params


def detach_copy(tree: Any) -> Any:
    """Fresh, materialized (blocked-on) copies of every leaf.

    Use to break buffer aliasing with a tree that is about to enter the
    donated update chain: the coupled loops hand the player a detached
    copy of the INITIAL params before the collector thread starts —
    ``PPOPlayer.__init__``'s ``device_put`` is a no-op on a same-device
    tree, so without the copy the player's warmup rollouts read the very
    buffers update 1 donates, and a fast trainer overwrites them
    mid-rollout at a timing-dependent step."""
    import jax
    import jax.numpy as jnp

    return jax.block_until_ready(jax.tree_util.tree_map(jnp.copy, tree))


def _copy_tree_for_publish(params: Any) -> Any:
    """Fresh, MATERIALIZED device buffers for the published params.

    The train steps donate their params/opt-state inputs
    (``donate_argnums``), so the arrays the trainer publishes for
    iteration t become donated inputs when iteration t+1's update
    dispatches.  An async ``jnp.copy`` is not enough: the copy and the
    donating update are both runnable once update t finishes, and the XLA
    client may execute them concurrently — the copy then reads buffers
    the donated update is overwriting (observed as run-to-run weight
    divergence on the CPU backend).  ``block_until_ready`` pins the copy
    before ``publish`` returns; the wait equals update t's completion,
    which the serial loop paid anyway — env collection still overlaps on
    the collector thread.
    """
    return detach_copy(params)


class PipelinedCollector:
    """Iterator of (iter_num, :class:`RolloutPayload`) over training iterations.

    Parameters
    ----------
    collect_fn:
        ``collect_fn(iter_num, inline, key_fn) -> RolloutPayload`` — steps
        the envs for one iteration and returns the HOST-side rollout
        (``payload.data``/``next_obs`` as produced by the rollout buffer).
        ``inline`` is True on the sync path (apply episode events / timers
        directly, exactly like the pre-pipeline loops); ``key_fn`` is the
        per-step policy key source to use.
    pack_fn:
        ``pack_fn(payload) -> None`` — converts ``payload.data`` /
        ``payload.next_obs`` (and any extras) to device-placed arrays.
        Runs inline on the sync path and on the collector thread on the
        overlapped path, where the host->device upload of rollout t+1
        overlaps the training dispatch of rollout t.
    adopt_params_fn:
        Called by the collector (rollout boundaries only) with the newest
        published params; typically ``player.params = p``.
    overlap:
        False = sync fallback: everything runs inline on the caller's
        thread in the exact serial order (bit-exact with the pre-pipeline
        loops).  True = background collector thread.
    max_staleness:
        Fixed lag (in updates behind the serial schedule) of the params a
        rollout acts on; >= 1.  Also the staleness upper bound — the
        collector waits for the lagged version rather than racing ahead.
    """

    def __init__(
        self,
        runtime,
        collect_fn: Callable[[int, bool, Callable], RolloutPayload],
        pack_fn: Callable[[RolloutPayload], None],
        *,
        start_iter: int,
        total_iters: int,
        overlap: bool,
        seed: int = 0,
        adopt_params_fn: Optional[Callable[[Any], None]] = None,
        max_staleness: int = 1,
    ):
        if max_staleness < 1:
            raise ValueError(f"max_staleness must be >= 1, got {max_staleness}")
        self._runtime = runtime
        self._collect_fn = collect_fn
        self._pack_fn = pack_fn
        self._start_iter = int(start_iter)
        self._total_iters = int(total_iters)
        self.overlap = bool(overlap)
        self._adopt = adopt_params_fn
        self._max_staleness = int(max_staleness)
        self._bus = _ParamsBus(initial_version=self._start_iter - 1, keep=self._max_staleness + 2)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._queue: "queue.Queue[RolloutPayload]" = queue.Queue(maxsize=1)
        self._keys = KeyStream(seed)
        self._iter = self._start_iter
        self.staleness_log: List[Tuple[int, int]] = []  # (iter_num, staleness)
        self._thread: Optional[threading.Thread] = None
        if self.overlap and self._total_iters >= self._start_iter:
            self._thread = threading.Thread(
                target=self._worker, name="sheeprl-collector", daemon=True
            )
            from sheeprl_tpu.analysis.sanitizers import leak_registry

            self._leak_token = leak_registry.register(
                "thread", "sheeprl-collector", self._thread, where="PipelinedCollector"
            )
            self._thread.start()

    # ------------------------------------------------------------- worker
    def _worker(self) -> None:
        try:
            for k in range(self._start_iter, self._total_iters + 1):
                # fixed-lag adoption: rollout k acts on EXACTLY the params
                # of iteration k - 1 - max_staleness (warmup: the initial
                # weights).  A "newest published" adoption would satisfy
                # the staleness bound too, but which version wins would be
                # a thread-timing race — fixed lag keeps overlapped runs
                # reproducible given their seed.
                target = k - 1 - self._max_staleness
                ok, params = self._bus.take_exact(target, self._stop)
                if self._stop.is_set():
                    return
                version = target if ok else self._start_iter - 1
                if ok and self._adopt is not None:
                    self._adopt(params)
                self.staleness_log.append((k, max(0, (k - 1) - version)))
                with flight.span("collect", round=k):
                    payload = self._collect_fn(k, False, self._keys)
                payload.params_version = version
                self._pack_fn(payload)
                while not self._stop.is_set():
                    try:
                        self._queue.put(payload, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # surfaced on the caller's next __next__
            self._error = e
            self._stop.set()

    # ------------------------------------------------------------ iterator
    def __iter__(self):
        return self

    def __next__(self) -> Tuple[int, RolloutPayload]:
        if self._iter > self._total_iters:
            raise StopIteration
        k = self._iter
        if not self.overlap:
            version, params = self._bus.latest()
            if params is not None and self._adopt is not None:
                self._adopt(params)
            self.staleness_log.append((k, max(0, (k - 1) - version)))
            with flight.span("collect", round=k):
                payload = self._collect_fn(k, True, self._runtime.next_key)
            payload.params_version = version
            self._pack_fn(payload)
            self._iter += 1
            return k, payload
        while True:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            try:
                payload = self._queue.get(timeout=0.5)
                break
            except queue.Empty:
                if self._stop.is_set():
                    raise StopIteration from None
                continue
        assert payload.iter_num == k, f"pipeline out of order: got {payload.iter_num}, expected {k}"
        self._iter += 1
        return k, payload

    # ------------------------------------------------------------- trainer
    def publish(self, version: int, params: Any) -> None:
        """Publish iteration ``version``'s freshly-trained params for the
        collector to adopt at its next rollout boundary.  On the sync path
        this feeds the same adopt-at-boundary handoff (keeping the serial
        order: adopt happens at the top of the next __next__).

        INVARIANT: publish returns only after update ``version`` has
        COMPLETED on device (the overlap path blocks on the params copy,
        the sync path blocks on the params themselves).  The algo loops'
        ``pack_fn``s rely on this: host buffers that CPU ``device_put``
        zero-copy aliased (``payload.host_refs``) may be released once the
        payload that published ``version`` is dropped — without the
        barrier, freeing them mid-update lets the allocator hand their
        memory to the next rollout's pack, scribbling the tensors the
        in-flight update is reading."""
        if self.overlap:
            params = _copy_tree_for_publish(params)
        else:
            import jax

            jax.block_until_ready(params)
        self._bus.publish(version, params)

    # ------------------------------------------------------------- teardown
    def close(self, timeout: float = 30.0) -> None:
        """Stop and join the collector thread (no-op on the sync path).
        Call before closing the envs — the thread may be mid-``env.step``."""
        self._stop.set()
        if self._thread is not None:
            # unblock a collector stuck on a full handoff queue
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():  # pragma: no cover - pathological env hang
                import warnings

                warnings.warn("PipelinedCollector: collector thread did not join within timeout")
            self._thread = None
            from sheeprl_tpu.analysis.sanitizers import leak_registry

            leak_registry.unregister(getattr(self, "_leak_token", None))
            self._leak_token = None

    @property
    def closed(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def __enter__(self) -> "PipelinedCollector":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class OnPolicyCollector:
    """Shared PPO/A2C rollout stepper (the bodies were copy-identical).

    Owns the carried env state (``next_obs``) and writes into ``rb``; one
    ``collect`` call steps ``cfg.algo.rollout_steps`` env steps and
    returns the host-side rollout payload.  On the sync path
    (``inline=True``) episode metrics/prints and the env-interaction timer
    run inline — the exact pre-pipeline behavior; on the overlapped path
    they are deferred through the payload (see module docstring).
    """

    def __init__(
        self,
        *,
        envs,
        player,
        rb,
        cfg,
        runtime,
        obs_keys,
        total_envs: int,
        world_size: int,
        aggregator=None,
        clip_rewards_fn: Optional[Callable] = None,
        policy_step: int = 0,
    ):
        self.envs = envs
        self.player = player
        self.rb = rb
        self.cfg = cfg
        self.runtime = runtime
        self.obs_keys = list(obs_keys)
        self.total_envs = int(total_envs)
        self.world_size = int(world_size)
        self.aggregator = aggregator
        self.clip_rewards_fn = clip_rewards_fn or (lambda r: r)
        self.policy_step = int(policy_step)
        self.next_obs = envs.reset(seed=cfg.seed)[0]
        self._step_data: Dict[str, np.ndarray] = {}

    def collect(self, iter_num: int, inline: bool, key_fn) -> RolloutPayload:
        from sheeprl_tpu.utils.metric import SumMetric
        from sheeprl_tpu.utils.timer import timer
        from sheeprl_tpu.utils.utils import start_async_host_copy

        cfg = self.cfg
        payload = RolloutPayload(iter_num)
        step_data = self._step_data
        next_obs_np = self.next_obs
        for _ in range(cfg.algo.rollout_steps):
            self.policy_step += cfg.env.num_envs * self.world_size
            t0 = None
            cm = (
                timer("Time/env_interaction_time", SumMetric, sync_on_compute=False)
                if inline
                else None
            )
            if cm is not None:
                cm.__enter__()
            else:
                t0 = time.perf_counter()
            try:
                flat_actions, real_actions, logprobs, values = self.player.get_actions(
                    next_obs_np, key_fn()
                )
                # overlap the three host fetches the buffer write needs with
                # the env step: only the action array is awaited here
                start_async_host_copy(flat_actions, logprobs, values)
                real_actions_np = np.asarray(real_actions)
                obs, rewards, terminated, truncated, info = self.envs.step(
                    real_actions_np.reshape(self.envs.action_space.shape)
                )
                truncated_envs = np.nonzero(truncated)[0]
                if len(truncated_envs) > 0:
                    # fixed-shape bootstrap: substitute final obs rows, value
                    # the full env batch, pick the truncated entries
                    real_next_obs = {k: np.array(v) for k, v in obs.items()}
                    for env_idx in truncated_envs:
                        final = info["final_obs"][env_idx]
                        for k in self.obs_keys:
                            real_next_obs[k][env_idx] = final[k]
                    vals = np.asarray(self.player.get_values(real_next_obs))
                    rewards[truncated_envs] += cfg.algo.gamma * vals[truncated_envs].reshape(
                        rewards[truncated_envs].shape
                    )
                dones = (
                    np.logical_or(terminated, truncated)
                    .reshape(self.total_envs, 1)
                    .astype(np.uint8)
                )
                rewards = self.clip_rewards_fn(rewards).reshape(self.total_envs, 1).astype(np.float32)
            finally:
                if cm is not None:
                    cm.__exit__(None, None, None)
                else:
                    payload.env_seconds += time.perf_counter() - t0

            for k in self.obs_keys:
                step_data[k] = next_obs_np[k][np.newaxis]
            step_data["dones"] = dones[np.newaxis]
            step_data["values"] = np.asarray(values)[np.newaxis]
            step_data["actions"] = np.asarray(flat_actions)[np.newaxis]
            step_data["logprobs"] = np.asarray(logprobs)[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis]
            self.rb.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs_np = obs

            if cfg.metric.log_level > 0 and "final_info" in info:
                ep = info["final_info"].get("episode")
                if ep is not None:
                    mask = info["final_info"]["_episode"]
                    for i in np.nonzero(mask)[0]:
                        ep_rew = float(ep["r"][i])
                        ep_len = float(ep["l"][i])
                        if inline:
                            if self.aggregator and "Rewards/rew_avg" in self.aggregator:
                                self.aggregator.update("Rewards/rew_avg", ep_rew)
                            if self.aggregator and "Game/ep_len_avg" in self.aggregator:
                                self.aggregator.update("Game/ep_len_avg", ep_len)
                            self.runtime.print(
                                f"Rank-0: policy_step={self.policy_step}, reward_env_{i}={ep_rew}"
                            )
                        else:
                            payload.events.append((self.policy_step, int(i), ep_rew, ep_len))

        self.next_obs = next_obs_np
        payload.data = self.rb.to_arrays()
        payload.next_obs = next_obs_np
        payload.policy_step_end = self.policy_step
        return payload
