"""Deterministic dummy environments — the fake backend for the test suite
(reference sheeprl/envs/dummy.py:8 + utils/env.py:234).

Observations count steps; images are NHWC (H, W, C) uint8 — the TPU build's
canonical image layout."""

from __future__ import annotations

from typing import Dict, List, Tuple

import gymnasium as gym
import numpy as np


class BaseDummyEnv(gym.Env):
    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}
    render_mode = "rgb_array"

    def __init__(
        self,
        image_size: Tuple[int, int, int] = (64, 64, 3),
        n_steps: int = 128,
        vector_shape: Tuple[int] = (10,),
        dict_obs_space: bool = True,
    ):
        self._dict_obs_space = dict_obs_space
        if self._dict_obs_space:
            self.observation_space = gym.spaces.Dict(
                {
                    "rgb": gym.spaces.Box(0, 255, shape=image_size, dtype=np.uint8),
                    "state": gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32),
                }
            )
        else:
            self.observation_space = gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32)
        self.reward_range = (-np.inf, np.inf)
        self._current_step = 0
        self._n_steps = n_steps

    def get_obs(self):
        if self._dict_obs_space:
            return {
                "rgb": np.full(
                    self.observation_space["rgb"].shape, self._current_step % 256, dtype=np.uint8
                ),
                "state": np.full(self.observation_space["state"].shape, self._current_step, dtype=np.float32),
            }
        return np.full(self.observation_space.shape, self._current_step, dtype=np.float32)

    def step(self, action):
        done = self._current_step == self._n_steps
        self._current_step += 1
        return self.get_obs(), 0.0, done, False, {}

    def reset(self, seed=None, options=None):
        super().reset(seed=seed)
        self._current_step = 0
        return self.get_obs(), {}

    def render(self):
        return np.zeros((64, 64, 3), dtype=np.uint8)

    def close(self):
        pass


class ContinuousDummyEnv(BaseDummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (64, 64, 3),
        n_steps: int = 128,
        vector_shape: Tuple[int] = (10,),
        action_dim: int = 2,
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.Box(-1.0, 1.0, shape=(action_dim,))
        super().__init__(image_size, n_steps, vector_shape, dict_obs_space)


class DiscreteDummyEnv(BaseDummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (64, 64, 3),
        n_steps: int = 4,
        vector_shape: Tuple[int] = (10,),
        action_dim: int = 2,
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.Discrete(action_dim)
        super().__init__(image_size, n_steps, vector_shape, dict_obs_space)


class MultiDiscreteDummyEnv(BaseDummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (64, 64, 3),
        n_steps: int = 128,
        vector_shape: Tuple[int] = (10,),
        action_dims: List[int] = [2, 2],
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.MultiDiscrete(action_dims)
        super().__init__(image_size, n_steps, vector_shape, dict_obs_space)


def make_dummy_env(id: str, **kwargs) -> gym.Env:
    """Factory resolving a dummy env id (reference utils/env.py:234)."""
    if "continuous" in id:
        return ContinuousDummyEnv(**kwargs)
    if "multidiscrete" in id:
        return MultiDiscreteDummyEnv(**kwargs)
    if "discrete" in id:
        return DiscreteDummyEnv(**kwargs)
    raise ValueError(f"Unrecognized dummy environment: {id}")
