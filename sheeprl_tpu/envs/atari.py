"""Atari environment factory (gated on ale_py availability).

The reference relies on gymnasium's atari extras
(configs/env/atari.yaml: gym.make of *NoFrameskip-v4). Frame preprocessing
(resize/grayscale) happens in make_env's transform chain, so here we only
need the raw env with rgb rendering."""

from __future__ import annotations

import gymnasium as gym

from sheeprl_tpu.utils.imports import _IS_ATARI_AVAILABLE


def make_atari_env(id: str, screen_size: int = 64, **kwargs) -> gym.Env:
    if not _IS_ATARI_AVAILABLE:
        raise ModuleNotFoundError(
            "ale_py is not installed in this environment; Atari environments are unavailable. "
            "Install gymnasium[atari] to use them."
        )
    import ale_py  # noqa: F401

    gym.register_envs(ale_py)
    return gym.make(id, render_mode="rgb_array")
