"""Generic gymnasium wrappers.

Counterpart of reference sheeprl/envs/wrappers.py (MaskVelocityWrapper:13,
ActionRepeat:48, RestartOnException:74, FrameStack:126,
RewardAsObservationWrapper:185, GrayscaleRenderWrapper:244,
ActionsAsObservationWrapper:258), written against gymnasium>=1.0.

TPU-first difference: FrameStack concatenates frames on the **channel
(last) axis** of NHWC images — (H, W, C*num_stack) — instead of adding a
leading stack axis, so stacked frames feed XLA convolutions directly with
no reshape."""

from __future__ import annotations

import copy
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, SupportsFloat, Tuple, Union

import gymnasium as gym
import numpy as np


class MaskVelocityWrapper(gym.ObservationWrapper):
    """Mask velocity terms of classic-control observations to make the MDP
    partially observable."""

    velocity_indices = {
        "CartPole-v0": np.array([1, 3]),
        "CartPole-v1": np.array([1, 3]),
        "MountainCar-v0": np.array([1]),
        "MountainCarContinuous-v0": np.array([1]),
        "Pendulum-v1": np.array([2]),
        "LunarLander-v2": np.array([2, 3, 5]),
        "LunarLanderContinuous-v2": np.array([2, 3, 5]),
    }

    def __init__(self, env: gym.Env):
        super().__init__(env)
        assert env.unwrapped.spec is not None
        env_id: str = env.unwrapped.spec.id
        self.mask = np.ones_like(env.observation_space.sample())
        try:
            self.mask[self.velocity_indices[env_id]] = 0.0
        except KeyError as e:
            raise NotImplementedError(f"Velocity masking not implemented for {env_id}") from e

    def observation(self, observation: np.ndarray) -> np.ndarray:
        return observation * self.mask


class ActionRepeat(gym.Wrapper):
    """Repeat an action `amount` times, accumulating rewards, stopping early
    on termination."""

    def __init__(self, env: gym.Env, amount: int = 1):
        super().__init__(env)
        if amount <= 0:
            raise ValueError("`amount` should be a positive integer")
        self._amount = amount

    @property
    def action_repeat(self) -> int:
        return self._amount

    def step(self, action):
        terminated = truncated = False
        total_reward = 0.0
        obs, info = None, {}
        for _ in range(self._amount):
            obs, reward, terminated, truncated, info = self.env.step(action)
            total_reward += float(reward)
            if terminated or truncated:
                break
        return obs, total_reward, terminated, truncated, info


class RestartOnException(gym.Wrapper):
    """Fault tolerance: re-instantiate a crashed env, within a sliding-window
    fail budget; flags the restart via ``info["restart_on_exception"]``.

    Algorithms react by truncating the last stored step and restarting the
    episode (see reference dreamer_v3.py:595-608)."""

    def __init__(
        self,
        env_fn: Callable[..., gym.Env],
        exceptions: Union[type, Tuple[type, ...]] = (Exception,),
        window: float = 300,
        maxfails: int = 2,
        wait: float = 20,
    ):
        if not isinstance(exceptions, (tuple, list)):
            exceptions = (exceptions,)
        self._env_fn = env_fn
        self._exceptions = tuple(exceptions)
        self._window = window
        self._maxfails = maxfails
        self._wait = wait
        self._last = time.time()
        self._fails = 0
        super().__init__(self._env_fn())

    def _register_failure(self, e: BaseException, where: str) -> None:
        if time.time() > self._last + self._window:
            self._last = time.time()
            self._fails = 1
        else:
            self._fails += 1
        if self._fails > self._maxfails:
            raise RuntimeError(f"The env crashed too many times: {self._fails}") from e
        gym.logger.warn(f"{where} - Restarting env after crash with {type(e).__name__}: {e}")
        time.sleep(self._wait)

    def step(self, action) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        try:
            return self.env.step(action)
        except self._exceptions as e:
            self._register_failure(e, "STEP")
            self.env = self._env_fn()
            new_obs, info = self.env.reset()
            info.update({"restart_on_exception": True})
            return new_obs, 0.0, False, False, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        try:
            return self.env.reset(seed=seed, options=options)
        except self._exceptions as e:
            self._register_failure(e, "RESET")
            self.env = self._env_fn()
            new_obs, info = self.env.reset(seed=seed, options=options)
            info.update({"restart_on_exception": True})
            return new_obs, info


class EnvStepGuard(gym.Wrapper):
    """Robust ``step``: a crashed/raising env is rebuilt ONCE with backoff
    and the interrupted episode is marked **truncated**; a second fault
    before the restarted env completes a step re-raises with the env index
    and the last action in the message.

    Differences from :class:`RestartOnException` (kept for reference
    parity on the Dreamer-V3/minerl paths): the interrupted episode ends as
    a normal truncation — the vector env's SAME_STEP autoreset then resets
    the rebuilt env and the algorithms' truncation bootstrapping handles
    the value target, so no algorithm-side special-casing is needed — and
    an unrecoverable env surfaces a diagnosable error instead of a fail
    counter. Applied per-env inside the thunk (``make_env``) so it guards
    Sync and Async vector envs alike. The ``env_step_raise`` fault site
    (resilience/faults.py) raises from inside the guard, making the
    recovery path testable without a crashy env."""

    def __init__(
        self,
        env: gym.Env,
        env_fn: Callable[[], gym.Env],
        env_idx: int = 0,
        backoff_s: float = 1.0,
    ):
        super().__init__(env)
        self._env_fn = env_fn
        self._env_idx = env_idx
        self._backoff_s = backoff_s
        self._last_obs: Any = None
        self._last_action: Any = None
        # True from a restart until the rebuilt env survives one step: a
        # fault in that window is a double fault (the rebuild didn't help)
        self._just_restarted = False

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, info = self.env.reset(seed=seed, options=options)
        self._last_obs = obs
        return obs, info

    def step(self, action) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        self._last_action = action
        try:
            from sheeprl_tpu.resilience.faults import fault_point

            if fault_point("env_step_raise"):
                raise RuntimeError("injected fault: env_step_raise")
            obs, reward, terminated, truncated, info = self.env.step(action)
        except Exception as e:
            if self._just_restarted:
                raise RuntimeError(
                    f"env {self._env_idx} crashed again right after a restart "
                    f"(double fault, giving up); last action: {self._last_action!r}"
                ) from e
            gym.logger.warn(
                f"env {self._env_idx} crashed in step ({type(e).__name__}: {e}); "
                f"restarting once after {self._backoff_s}s and truncating the episode"
            )
            try:
                self.env.close()
            except Exception:
                pass
            time.sleep(self._backoff_s)
            self.env = self._env_fn()
            self.env.reset()
            self._just_restarted = True
            # end the interrupted episode as a truncation at the last good
            # observation; SAME_STEP autoreset resets the fresh env next
            return (
                self._last_obs,
                0.0,
                False,
                True,
                {"env_restarted": True, "env_restart_error": f"{type(e).__name__}: {e}"},
            )
        self._just_restarted = False
        self._last_obs = obs
        return obs, reward, terminated, truncated, info


class FrameStack(gym.Wrapper):
    """Stack the last ``num_stack`` frames of dict image observations on the
    channel axis: (H, W, C) -> (H, W, C*num_stack), with optional dilation."""

    def __init__(self, env: gym.Env, num_stack: int, cnn_keys: Sequence[str], dilation: int = 1) -> None:
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"Invalid value for num_stack, expected a value greater than zero, got {num_stack}")
        if dilation <= 0:
            raise ValueError(f"Invalid value for dilation, expected a value greater than zero, got {dilation}")
        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise RuntimeError(
                f"Expected an observation space of type gym.spaces.Dict, got: {type(env.observation_space)}"
            )
        self._num_stack = num_stack
        self._dilation = dilation
        self._cnn_keys = []
        self.observation_space = copy.deepcopy(self.env.observation_space)
        for k, v in self.env.observation_space.spaces.items():
            if cnn_keys and k in cnn_keys and len(v.shape) == 3:
                self._cnn_keys.append(k)
                h, w, c = v.shape
                self.observation_space[k] = gym.spaces.Box(
                    np.concatenate([v.low] * num_stack, axis=-1),
                    np.concatenate([v.high] * num_stack, axis=-1),
                    (h, w, c * num_stack),
                    v.dtype,
                )
        if len(self._cnn_keys) == 0:
            raise RuntimeError("Specify at least one valid cnn key to be stacked")
        self._frames = {k: deque(maxlen=num_stack * dilation) for k in self._cnn_keys}

    def _get_obs(self, key: str) -> np.ndarray:
        subset = list(self._frames[key])[self._dilation - 1 :: self._dilation]
        assert len(subset) == self._num_stack
        return np.concatenate(subset, axis=-1)

    def step(self, action):
        obs, reward, terminated, truncated, infos = self.env.step(action)
        for k in self._cnn_keys:
            self._frames[k].append(obs[k])
            obs[k] = self._get_obs(k)
        return obs, reward, terminated, truncated, infos

    def reset(self, *, seed=None, options=None, **kwargs):
        obs, infos = self.env.reset(seed=seed, **kwargs)
        for k in self._cnn_keys:
            self._frames[k].clear()
            for _ in range(self._num_stack * self._dilation):
                self._frames[k].append(obs[k])
            obs[k] = self._get_obs(k)
        return obs, infos


class RewardAsObservationWrapper(gym.Wrapper):
    """Expose the previous reward as a (1,) Box observation under the
    ``reward`` key (``obs`` key wraps non-dict observations)."""

    def __init__(self, env: gym.Env) -> None:
        super().__init__(env)
        reward_range = getattr(self.env, "reward_range", None) or (-np.inf, np.inf)
        reward_space = gym.spaces.Box(*reward_range, (1,), np.float32)
        if isinstance(self.env.observation_space, gym.spaces.Dict):
            self.observation_space = gym.spaces.Dict(
                {"reward": reward_space, **dict(self.env.observation_space.items())}
            )
        else:
            self.observation_space = gym.spaces.Dict(
                {"obs": self.env.observation_space, "reward": reward_space}
            )

    def _convert_obs(self, obs: Any, reward: Union[float, np.ndarray]) -> Dict[str, Any]:
        reward_obs = np.asarray(reward, dtype=np.float32).reshape(-1)
        if isinstance(obs, dict):
            obs["reward"] = reward_obs
        else:
            obs = {"obs": obs, "reward": reward_obs}
        return obs

    def step(self, action):
        obs, reward, terminated, truncated, infos = self.env.step(action)
        return self._convert_obs(obs, copy.deepcopy(reward)), reward, terminated, truncated, infos

    def reset(self, *, seed=None, options=None):
        obs, infos = self.env.reset(seed=seed, options=options)
        return self._convert_obs(obs, 0), infos


class GrayscaleRenderWrapper(gym.Wrapper):
    """Promote 2D/1-channel render frames to 3-channel for video encoders."""

    def render(self):
        frame = super().render()
        if isinstance(frame, np.ndarray):
            if len(frame.shape) == 2:
                frame = frame[..., np.newaxis]
            if len(frame.shape) == 3 and frame.shape[-1] == 1:
                frame = frame.repeat(3, axis=-1)
        return frame


class ActionsAsObservationWrapper(gym.Wrapper):
    """Expose the last ``num_stack`` executed actions (one-hot for discrete
    spaces) as the ``action_stack`` observation, noop-filled on reset."""

    def __init__(self, env: gym.Env, num_stack: int, noop: Union[float, int, List[int]], dilation: int = 1):
        super().__init__(env)
        if num_stack < 1:
            raise ValueError(
                f"The number of actions to stack must be greater or equal than 1, got: {num_stack}"
            )
        if dilation < 1:
            raise ValueError(f"The actions stack dilation argument must be greater than zero, got: {dilation}")
        if not isinstance(noop, (int, float, list)):
            raise ValueError(f"The noop action must be an integer or float or list, got: {noop} ({type(noop)})")
        self._num_stack = num_stack
        self._dilation = dilation
        self._actions = deque(maxlen=num_stack * dilation)
        self._is_continuous = isinstance(self.env.action_space, gym.spaces.Box)
        self._is_multidiscrete = isinstance(self.env.action_space, gym.spaces.MultiDiscrete)
        self.observation_space = copy.deepcopy(self.env.observation_space)
        if self._is_continuous:
            if isinstance(noop, list):
                raise ValueError(f"The noop actions must be a float for continuous action spaces, got: {noop}")
            self._action_shape = self.env.action_space.shape[0]
            low = np.resize(self.env.action_space.low, self._action_shape * num_stack)
            high = np.resize(self.env.action_space.high, self._action_shape * num_stack)
            self.noop = np.full((self._action_shape,), noop, dtype=np.float32)
        elif self._is_multidiscrete:
            if not isinstance(noop, list):
                raise ValueError(f"The noop actions must be a list for multi-discrete action spaces, got: {noop}")
            nvec = self.env.action_space.nvec
            if len(nvec) != len(noop):
                raise RuntimeError(
                    "The number of noop actions must equal the number of env actions: "
                    f"nvec={nvec}, noop={noop}"
                )
            low, high = 0, 1
            self._action_shape = int(sum(nvec))
            noops = []
            for idx, n in zip(noop, nvec):
                oh = np.zeros((n,), dtype=np.float32)
                oh[idx] = 1.0
                noops.append(oh)
            self.noop = np.concatenate(noops, axis=-1)
        else:
            if isinstance(noop, (list, float)):
                raise ValueError(f"The noop actions must be an integer for discrete action spaces, got: {noop}")
            low, high = 0, 1
            self._action_shape = int(self.env.action_space.n)
            self.noop = np.zeros((self._action_shape,), dtype=np.float32)
            self.noop[noop] = 1.0
        self.observation_space["action_stack"] = gym.spaces.Box(
            low=low, high=high, shape=(self._action_shape * num_stack,), dtype=np.float32
        )

    def _encode(self, action: Any) -> np.ndarray:
        if self._is_continuous:
            return np.asarray(action, dtype=np.float32).reshape(-1)
        if self._is_multidiscrete:
            parts = []
            for idx, n in zip(np.asarray(action).reshape(-1), self.env.action_space.nvec):
                oh = np.zeros((n,), dtype=np.float32)
                oh[int(idx)] = 1.0
                parts.append(oh)
            return np.concatenate(parts, axis=-1)
        oh = np.zeros((self._action_shape,), dtype=np.float32)
        oh[int(np.asarray(action).reshape(-1)[0])] = 1.0
        return oh

    def step(self, action):
        self._actions.append(self._encode(action))
        obs, reward, terminated, truncated, info = super().step(action)
        obs["action_stack"] = self._get_actions_stack()
        return obs, reward, terminated, truncated, info

    def reset(self, *, seed=None, options=None):
        obs, info = super().reset(seed=seed, options=options)
        self._actions.clear()
        for _ in range(self._num_stack * self._dilation):
            self._actions.append(self.noop)
        obs["action_stack"] = self._get_actions_stack()
        return obs, info

    def _get_actions_stack(self) -> np.ndarray:
        stack = list(self._actions)[self._dilation - 1 :: self._dilation]
        return np.concatenate(stack, axis=-1).astype(np.float32)
