"""Custom MineRL Navigate task (gated on ``minerl``).

Behavioral counterpart of reference sheeprl/envs/minerl_envs/navigate.py
(CustomNavigate:18): reach a diamond block ~64m away guided by a compass;
+100 sparse reward on touch (optionally dense distance shaping); the
in-engine time limit is disabled so the gymnasium TimeLimit wrapper can
distinguish truncation from termination."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError(
        "minerl is not installed; MineRL environments are unavailable. "
        "Install minerl==0.4.4 to use them."
    )

from typing import List

import minerl.herobraine.hero.handlers as handlers
from minerl.herobraine.hero.handler import Handler

from sheeprl_tpu.envs.minerl_envs.backend import CustomSimpleEmbodimentEnvSpec

NAVIGATE_STEPS = 6000


class CustomNavigate(CustomSimpleEmbodimentEnvSpec):
    def __init__(self, dense, extreme, *args, **kwargs):
        suffix = ("Extreme" if extreme else "") + ("Dense" if dense else "")
        self.dense, self.extreme = dense, extreme
        # time limit handled by the gymnasium TimeLimit wrapper (MineRL
        # cannot distinguish terminated from truncated)
        kwargs.pop("max_episode_steps", None)
        super().__init__(f"CustomMineRLNavigate{suffix}-v0", *args, max_episode_steps=None, **kwargs)

    def is_from_folder(self, folder: str) -> bool:
        return folder == ("navigateextreme" if self.extreme else "navigate")

    def create_observables(self) -> List[Handler]:
        return super().create_observables() + [
            handlers.CompassObservation(angle=True, distance=False),
            handlers.FlatInventoryObservation(["dirt"]),
        ]

    def create_actionables(self) -> List[Handler]:
        return super().create_actionables() + [
            handlers.PlaceBlock(["none", "dirt"], _other="none", _default="none")
        ]

    def create_rewardables(self) -> List[Handler]:
        return [
            handlers.RewardForTouchingBlockType(
                [{"type": "diamond_block", "behaviour": "onceOnly", "reward": 100.0}]
            )
        ] + ([handlers.RewardForDistanceTraveledToCompassTarget(reward_per_block=1.0)] if self.dense else [])

    def create_agent_start(self) -> List[Handler]:
        return super().create_agent_start() + [
            handlers.SimpleInventoryAgentStart([dict(type="compass", quantity="1")])
        ]

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromTouchingBlockType(["diamond_block"])]

    def create_server_world_generators(self) -> List[Handler]:
        if self.extreme:
            return [handlers.BiomeGenerator(biome=3, force_reset=True)]
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[Handler]:
        return [handlers.ServerQuitWhenAnyAgentFinishes()]

    def create_server_decorators(self) -> List[Handler]:
        return [
            handlers.NavigationDecorator(
                max_randomized_radius=64,
                min_randomized_radius=64,
                block="diamond_block",
                placement="surface",
                max_radius=8,
                min_radius=0,
                max_randomized_distance=8,
                min_randomized_distance=0,
                randomize_compass_location=True,
            )
        ]

    def create_server_initial_conditions(self) -> List[Handler]:
        return [
            handlers.TimeInitialCondition(allow_passage_of_time=False, start_time=6000),
            handlers.WeatherInitialCondition("clear"),
            handlers.SpawningInitialCondition("false"),
        ]

    def get_docstring(self) -> str:
        biome = "an extreme hills biome" if self.extreme else "a random survival map"
        shaping = "dense distance-based shaping" if self.dense else "a sparse +100 on reaching the goal"
        return (
            "Reach a diamond block ~64m from spawn guided by a compass observation; "
            f"the agent spawns in {biome} and receives {shaping}."
        )

    def determine_success_from_rewards(self, rewards: list) -> bool:
        reward_threshold = 100.0 + (60 if self.dense else 0)
        return sum(rewards) >= reward_threshold
