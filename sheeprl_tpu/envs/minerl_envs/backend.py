"""Shared base spec for the custom MineRL tasks (gated on ``minerl``).

Behavioral counterpart of reference sheeprl/envs/minerl_envs/backend.py
(CustomSimpleEmbodimentEnvSpec:19), itself derived from the public
minerllabs/minerl simple-embodiment spec plus danijar/diamond_env's
break-speed handler: POV/location/life-stats observables, the simple
keyboard + camera actionables, and a configurable block-break speed
multiplier injected into the mission XML."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError(
        "minerl is not installed; MineRL environments are unavailable. "
        "Install minerl==0.4.4 to use them."
    )

from abc import ABC
from typing import List

from minerl.herobraine.env_spec import EnvSpec
from minerl.herobraine.hero import handler, handlers
from minerl.herobraine.hero.handlers.translation import TranslationHandler
from minerl.herobraine.hero.mc import INVERSE_KEYMAP

SIMPLE_KEYBOARD_ACTION = ["forward", "back", "left", "right", "jump", "sneak", "sprint", "attack"]


class BreakSpeedMultiplier(handler.Handler):
    """Mission-XML handler scaling block-breaking speed."""

    def __init__(self, multiplier: float = 1.0):
        self.multiplier = multiplier

    def to_string(self) -> str:
        return f"break_speed({self.multiplier})"

    def xml_template(self) -> str:
        return "<BreakSpeedMultiplier>{{multiplier}}</BreakSpeedMultiplier>"


class CustomSimpleEmbodimentEnvSpec(EnvSpec, ABC):
    """Base spec all custom sheeprl_tpu MineRL tasks inherit from."""

    def __init__(self, name, *args, resolution=(64, 64), break_speed: int = 100, **kwargs):
        self.resolution = resolution
        self.break_speed = break_speed
        super().__init__(name, *args, **kwargs)

    def create_agent_start(self) -> List[handler.Handler]:
        return [BreakSpeedMultiplier(self.break_speed)]

    def create_observables(self) -> List[TranslationHandler]:
        return [
            handlers.POVObservation(self.resolution),
            handlers.ObservationFromCurrentLocation(),
            handlers.ObservationFromLifeStats(),
        ]

    def create_actionables(self) -> List[TranslationHandler]:
        return [
            handlers.KeybasedCommandAction(k, v)
            for k, v in INVERSE_KEYMAP.items()
            if k in SIMPLE_KEYBOARD_ACTION
        ] + [handlers.CameraAction()]

    def create_monitors(self) -> List[TranslationHandler]:
        return []
