"""Custom MineRL Obtain tasks (gated on ``minerl``).

Behavioral counterpart of reference sheeprl/envs/minerl_envs/obtain.py
(CustomObtain:23, CustomObtainDiamond:172, CustomObtainIronPickaxe:251):
the classic obtain-item hierarchy with GUI-free craft/smelt/equip/place
actionables, milestone reward schedules, and agent-quit handlers on the
target item; in-engine time limits disabled (TimeLimit wrapper instead)."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError(
        "minerl is not installed; MineRL environments are unavailable. "
        "Install minerl==0.4.4 to use them."
    )

from typing import Dict, List, Union

from minerl.herobraine.hero import handlers
from minerl.herobraine.hero.handler import Handler

from sheeprl_tpu.envs.minerl_envs.backend import CustomSimpleEmbodimentEnvSpec

NONE = "none"
OTHER = "other"

# milestone schedule shared by the diamond/iron-pickaxe tasks (diamond adds
# the final 1024 entry)
_IRON_SCHEDULE = [
    dict(type="log", amount=1, reward=1),
    dict(type="planks", amount=1, reward=2),
    dict(type="stick", amount=1, reward=4),
    dict(type="crafting_table", amount=1, reward=4),
    dict(type="wooden_pickaxe", amount=1, reward=8),
    dict(type="cobblestone", amount=1, reward=16),
    dict(type="furnace", amount=1, reward=32),
    dict(type="stone_pickaxe", amount=1, reward=32),
    dict(type="iron_ore", amount=1, reward=64),
    dict(type="iron_ingot", amount=1, reward=128),
    dict(type="iron_pickaxe", amount=1, reward=256),
]


def snake_to_camel(word: str) -> str:
    return "".join(x.capitalize() or "_" for x in word.split("_"))


class CustomObtain(CustomSimpleEmbodimentEnvSpec):
    def __init__(
        self,
        target_item: str,
        dense: bool,
        reward_schedule: List[Dict[str, Union[str, int, float]]],
        *args,
        max_episode_steps=None,
        **kwargs,
    ):
        self.target_item = target_item
        self.dense = dense
        self.reward_schedule = reward_schedule
        suffix = snake_to_camel(target_item) + ("Dense" if dense else "")
        self.reward_text = (
            "every time it obtains an item" if dense else "only once per item the first time it obtains that item"
        )
        super().__init__(
            *args,
            name=f"CustomMineRLObtain{suffix}-v0",
            max_episode_steps=max_episode_steps,
            **kwargs,
        )

    def create_observables(self) -> List[Handler]:
        return super().create_observables() + [
            handlers.FlatInventoryObservation(
                [
                    "dirt", "coal", "torch", "log", "planks", "stick", "crafting_table",
                    "wooden_axe", "wooden_pickaxe", "stone", "cobblestone", "furnace",
                    "stone_axe", "stone_pickaxe", "iron_ore", "iron_ingot", "iron_axe",
                    "iron_pickaxe",
                ]
            ),
            handlers.EquippedItemObservation(
                items=[
                    "air", "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe",
                    "iron_axe", "iron_pickaxe", OTHER,
                ],
                _default="air",
                _other=OTHER,
            ),
        ]

    def create_actionables(self) -> List[Handler]:
        return super().create_actionables() + [
            handlers.PlaceBlock(
                [NONE, "dirt", "stone", "cobblestone", "crafting_table", "furnace", "torch"],
                _other=NONE,
                _default=NONE,
            ),
            handlers.EquipAction(
                [NONE, "air", "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe",
                 "iron_axe", "iron_pickaxe"],
                _other=NONE,
                _default=NONE,
            ),
            handlers.CraftAction(
                [NONE, "torch", "stick", "planks", "crafting_table"], _other=NONE, _default=NONE
            ),
            handlers.CraftNearbyAction(
                [NONE, "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe",
                 "iron_axe", "iron_pickaxe", "furnace"],
                _other=NONE,
                _default=NONE,
            ),
            handlers.SmeltItemNearby([NONE, "iron_ingot", "coal"], _other=NONE, _default=NONE),
        ]

    def create_rewardables(self) -> List[Handler]:
        reward_handler = handlers.RewardForCollectingItems if self.dense else handlers.RewardForCollectingItemsOnce
        return [reward_handler(self.reward_schedule if self.reward_schedule else {self.target_item: 1})]

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromPossessingItem([dict(type="diamond", amount=1)])]

    def create_server_world_generators(self) -> List[Handler]:
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[Handler]:
        return [handlers.ServerQuitWhenAnyAgentFinishes()]

    def create_server_decorators(self) -> List[Handler]:
        return []

    def create_server_initial_conditions(self) -> List[Handler]:
        return [
            handlers.TimeInitialCondition(start_time=6000, allow_passage_of_time=True),
            handlers.SpawningInitialCondition(allow_spawning=True),
        ]

    def is_from_folder(self, folder: str) -> bool:
        return folder == f"o_{self.target_item}"

    def get_docstring(self) -> str:
        return (
            f"Obtain a {self.target_item} starting from nothing on a random survival map; "
            f"the agent is rewarded {self.reward_text} along the item hierarchy."
        )

    def determine_success_from_rewards(self, rewards: list) -> bool:
        rewards = set(rewards)
        max_missing = round(len(self.reward_schedule) * 0.1)
        reward_values = [s["reward"] for s in self.reward_schedule]
        return len(rewards.intersection(reward_values)) >= len(reward_values) - max_missing


class CustomObtainDiamond(CustomObtain):
    def __init__(self, dense, *args, **kwargs):
        kwargs.pop("max_episode_steps", None)
        super().__init__(
            *args,
            target_item="diamond",
            dense=dense,
            reward_schedule=_IRON_SCHEDULE + [dict(type="diamond", amount=1, reward=1024)],
            max_episode_steps=None,
            **kwargs,
        )

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_dia"


class CustomObtainIronPickaxe(CustomObtain):
    def __init__(self, dense, *args, **kwargs):
        kwargs.pop("max_episode_steps", None)
        super().__init__(
            *args,
            target_item="iron_pickaxe",
            dense=dense,
            reward_schedule=list(_IRON_SCHEDULE),
            max_episode_steps=None,
            **kwargs,
        )

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromCraftingItem([dict(type="iron_pickaxe", amount=1)])]

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_iron"
