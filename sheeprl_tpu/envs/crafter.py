"""Crafter adapter (gated on ``crafter``).

Behavioral counterpart of reference sheeprl/envs/crafter.py
(CrafterWrapper:17): old-gym crafter.Env becomes a gymnasium env with a
``{"rgb": ...}`` dict observation; the terminal ``discount`` distinguishes
termination (discount == 0) from truncation."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_CRAFTER_AVAILABLE

if not _IS_CRAFTER_AVAILABLE:
    raise ModuleNotFoundError(
        "crafter is not installed; Crafter environments are unavailable. "
        "Install crafter to use them."
    )

from typing import Any, Dict, Optional, Sequence, Union

import crafter
import gymnasium as gym
import numpy as np
from gymnasium import spaces


class CrafterWrapper(gym.Env):
    def __init__(self, id: str, screen_size: Union[Sequence[int], int], seed: Optional[int] = None):
        if id not in {"crafter_reward", "crafter_nonreward"}:
            raise AssertionError(f"Unknown crafter task: {id}")
        if isinstance(screen_size, int):
            screen_size = (screen_size,) * 2

        env = crafter.Env(size=tuple(screen_size), seed=seed, reward=(id == "crafter_reward"))
        self.env = env
        self.observation_space = spaces.Dict(
            {
                "rgb": spaces.Box(
                    env.observation_space.low,
                    env.observation_space.high,
                    env.observation_space.shape,
                    env.observation_space.dtype,
                )
            }
        )
        self.action_space = spaces.Discrete(env.action_space.n)
        self.reward_range = env.reward_range or (-np.inf, np.inf)
        self.observation_space.seed(seed)
        self.action_space.seed(seed)
        self._render_mode = "rgb_array"
        self._metadata = {"render_fps": 30}

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def step(self, action: Any):
        obs, reward, done, info = self.env.step(action)
        terminated = done and info["discount"] == 0
        truncated = done and info["discount"] != 0
        return {"rgb": obs}, reward, terminated, truncated, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        # seed=None must not clobber the constructor-provided seed
        if seed is not None:
            self.env._seed = seed
        obs = self.env.reset()
        return {"rgb": obs}, {}

    def render(self):
        return self.env.render()

    def close(self) -> None:
        return
