"""MineDojo adapter (gated on ``minedojo``).

Behavioral counterpart of reference sheeprl/envs/minedojo.py
(MineDojoWrapper:56): flattens MineDojo's 8-slot functional action space to
a 3-head MultiDiscrete (action-type, craft-item, inventory-slot), converts
the raw observations to fixed-size vectors over the full Minecraft item
vocabulary, emits per-head ACTION MASKS consumed by the Dreamer Minedojo
actors, enforces pitch limits, and implements sticky attack/jump.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINEDOJO_AVAILABLE

if not _IS_MINEDOJO_AVAILABLE:
    raise ModuleNotFoundError(
        "minedojo is not installed; MineDojo environments are unavailable. "
        "Install minedojo to use them."
    )

import copy
from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import minedojo
import minedojo.tasks
import numpy as np
from minedojo.sim import ALL_CRAFT_SMELT_ITEMS, ALL_ITEMS

N_ALL_ITEMS = len(ALL_ITEMS)

# 19 composite agent actions -> MineDojo's 8-slot action vector
# (slot meanings: move, strafe, jump/sneak/sprint, pitch, yaw, functional,
# craft-arg, inventory-arg; 12 is the no-op camera bucket)
ACTION_MAP = {
    0: np.array([0, 0, 0, 12, 12, 0, 0, 0]),  # no-op
    1: np.array([1, 0, 0, 12, 12, 0, 0, 0]),  # forward
    2: np.array([2, 0, 0, 12, 12, 0, 0, 0]),  # back
    3: np.array([0, 1, 0, 12, 12, 0, 0, 0]),  # left
    4: np.array([0, 2, 0, 12, 12, 0, 0, 0]),  # right
    5: np.array([1, 0, 1, 12, 12, 0, 0, 0]),  # jump + forward
    6: np.array([1, 0, 2, 12, 12, 0, 0, 0]),  # sneak + forward
    7: np.array([1, 0, 3, 12, 12, 0, 0, 0]),  # sprint + forward
    8: np.array([0, 0, 0, 11, 12, 0, 0, 0]),  # pitch down (-15)
    9: np.array([0, 0, 0, 13, 12, 0, 0, 0]),  # pitch up (+15)
    10: np.array([0, 0, 0, 12, 11, 0, 0, 0]),  # yaw down (-15)
    11: np.array([0, 0, 0, 12, 13, 0, 0, 0]),  # yaw up (+15)
    12: np.array([0, 0, 0, 12, 12, 1, 0, 0]),  # use
    13: np.array([0, 0, 0, 12, 12, 2, 0, 0]),  # drop
    14: np.array([0, 0, 0, 12, 12, 3, 0, 0]),  # attack
    15: np.array([0, 0, 0, 12, 12, 4, 0, 0]),  # craft
    16: np.array([0, 0, 0, 12, 12, 5, 0, 0]),  # equip
    17: np.array([0, 0, 0, 12, 12, 6, 0, 0]),  # place
    18: np.array([0, 0, 0, 12, 12, 7, 0, 0]),  # destroy
}
ITEM_ID_TO_NAME = dict(enumerate(ALL_ITEMS))
ITEM_NAME_TO_ID = dict(zip(ALL_ITEMS, range(N_ALL_ITEMS)))
# minedojo.make mutates the global task-spec table; keep a pristine copy so
# repeated construction stays deterministic
ALL_TASKS_SPECS = copy.deepcopy(minedojo.tasks.ALL_TASKS_SPECS)


def _norm(name: str) -> str:
    return "_".join(name.split(" "))


class MineDojoWrapper(gym.Env):
    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        **kwargs: Optional[Dict[Any, Any]],
    ):
        self._height = height
        self._width = width
        self._pitch_limits = pitch_limits
        self._pos = kwargs.get("start_position", None)
        self._break_speed_multiplier = kwargs.pop("break_speed_multiplier", 100)
        self._start_pos = copy.deepcopy(self._pos)
        # a high break-speed multiplier replaces the sticky attack
        self._sticky_attack = 0 if self._break_speed_multiplier > 1 else sticky_attack
        self._sticky_jump = sticky_jump
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0

        if self._pos is not None and not (self._pitch_limits[0] <= self._pos["pitch"] <= self._pitch_limits[1]):
            raise ValueError(
                f"The initial position must respect the pitch limits {self._pitch_limits}, "
                f"given {self._pos['pitch']}"
            )

        env = minedojo.make(
            task_id=id,
            image_size=(height, width),
            world_seed=seed,
            fast_reset=True,
            break_speed_multiplier=self._break_speed_multiplier,
            **kwargs,
        )
        self.env = env
        self._inventory: Dict[str, list] = {}
        self._inventory_names: Optional[np.ndarray] = None
        self._inventory_max = np.zeros(N_ALL_ITEMS)
        self.action_space = gym.spaces.MultiDiscrete(
            np.array([len(ACTION_MAP), len(ALL_CRAFT_SMELT_ITEMS), N_ALL_ITEMS])
        )
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, self.env.observation_space["rgb"].shape, np.uint8),
                "inventory": gym.spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_max": gym.spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_delta": gym.spaces.Box(-np.inf, np.inf, (N_ALL_ITEMS,), np.float32),
                "equipment": gym.spaces.Box(0.0, 1.0, (N_ALL_ITEMS,), np.int32),
                "life_stats": gym.spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
                "mask_action_type": gym.spaces.Box(0, 1, (len(ACTION_MAP),), bool),
                "mask_equip_place": gym.spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_destroy": gym.spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_craft_smelt": gym.spaces.Box(0, 1, (len(ALL_CRAFT_SMELT_ITEMS),), bool),
            }
        )
        self._render_mode = "rgb_array"
        self.seed(seed=seed)
        minedojo.tasks.ALL_TASKS_SPECS = copy.deepcopy(ALL_TASKS_SPECS)

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def __getattr__(self, name):
        if name.startswith("_") or name == "env":
            raise AttributeError(name)
        return getattr(self.env, name)

    def _convert_inventory(self, inventory: Dict[str, Any]) -> np.ndarray:
        """Slot list -> per-item count vector; tracks slot positions and the
        running max count per item."""
        converted = np.zeros(N_ALL_ITEMS)
        self._inventory = {}
        self._inventory_names = np.array([_norm(item) for item in inventory["name"].copy().tolist()])
        for i, (item, quantity) in enumerate(zip(inventory["name"], inventory["quantity"])):
            item = _norm(item)
            self._inventory.setdefault(item, []).append(i)
            # air stacks are counted as one slot each
            converted[ITEM_NAME_TO_ID[item]] += 1 if item == "air" else quantity
        self._inventory_max = np.maximum(converted, self._inventory_max)
        return converted

    def _convert_inventory_delta(self, inventory_delta: Dict[str, Any]) -> np.ndarray:
        converted = np.zeros(N_ALL_ITEMS)
        for sign, names_key, qty_key in (
            (+1, "inc_name_by_craft", "inc_quantity_by_craft"),
            (-1, "dec_name_by_craft", "dec_quantity_by_craft"),
            (+1, "inc_name_by_other", "inc_quantity_by_other"),
            (-1, "dec_name_by_other", "dec_quantity_by_other"),
        ):
            for item, quantity in zip(inventory_delta[names_key], inventory_delta[qty_key]):
                converted[ITEM_NAME_TO_ID[_norm(item)]] += sign * quantity
        return converted

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        equip = np.zeros(N_ALL_ITEMS, dtype=np.int32)
        equip[ITEM_NAME_TO_ID[_norm(equipment["name"][0])]] = 1
        return equip

    def _convert_masks(self, masks: Dict[str, Any]) -> Dict[str, np.ndarray]:
        equip_mask = np.zeros(N_ALL_ITEMS, dtype=bool)
        destroy_mask = np.zeros(N_ALL_ITEMS, dtype=bool)
        for item, eqp, dst in zip(self._inventory_names, masks["equip"], masks["destroy"]):
            idx = ITEM_NAME_TO_ID[item]
            equip_mask[idx] = eqp
            destroy_mask[idx] = dst
        # functional actions equip(5)/place(6) need an equippable item,
        # destroy(7) a destroyable one
        masks["action_type"][5:7] *= np.any(equip_mask).item()
        masks["action_type"][7] *= np.any(destroy_mask).item()
        return {
            # the 12 movement/camera actions are always valid
            "mask_action_type": np.concatenate((np.array([True] * 12), masks["action_type"][1:])),
            "mask_equip_place": equip_mask,
            "mask_destroy": destroy_mask,
            "mask_craft_smelt": masks["craft_smelt"],
        }

    def _convert_action(self, action: np.ndarray) -> np.ndarray:
        converted = ACTION_MAP[int(action[0])].copy()
        if self._sticky_attack:
            if converted[5] == 3:  # attack selected: arm the counter
                self._sticky_attack_counter = self._sticky_attack - 1
            if self._sticky_attack_counter > 0 and converted[5] == 0:
                converted[5] = 3
                self._sticky_attack_counter -= 1
            elif converted[5] != 3:
                self._sticky_attack_counter = 0
        if self._sticky_jump:
            if converted[2] == 1:  # jump selected: arm the counter
                self._sticky_jump_counter = self._sticky_jump - 1
            if self._sticky_jump_counter > 0 and converted[0] == 0:
                converted[2] = 1
                # keep moving forward while the sticky jump plays out unless
                # another movement action was chosen
                if converted[0] == converted[1] == 0:
                    converted[0] = 1
                self._sticky_jump_counter -= 1
            elif converted[2] != 1:
                self._sticky_jump_counter = 0
        # craft (functional action 4) consumes the craft-item head
        converted[6] = int(action[1]) if converted[5] == 4 else 0
        # equip/place/destroy (5/6/7) consume the inventory-slot head
        if converted[5] in {5, 6, 7}:
            converted[7] = self._inventory[ITEM_ID_TO_NAME[int(action[2])]][0]
        else:
            converted[7] = 0
        return converted

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            "rgb": obs["rgb"].copy(),
            "inventory": self._convert_inventory(obs["inventory"]),
            "inventory_max": self._inventory_max,
            "inventory_delta": self._convert_inventory_delta(obs["delta_inv"]),
            "equipment": self._convert_equipment(obs["equipment"]),
            "life_stats": np.concatenate(
                (obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["oxygen"])
            ),
            **self._convert_masks(obs["masks"]),
        }

    def _read_position(self, obs: Dict[str, Any]) -> Dict[str, float]:
        return {
            "x": float(obs["location_stats"]["pos"][0]),
            "y": float(obs["location_stats"]["pos"][1]),
            "z": float(obs["location_stats"]["pos"][2]),
            "pitch": float(obs["location_stats"]["pitch"].item()),
            "yaw": float(obs["location_stats"]["yaw"].item()),
        }

    def seed(self, seed: Optional[int] = None) -> None:
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    def step(self, action: np.ndarray):
        raw_action = action
        action = self._convert_action(action)
        # clamp the pitch by cancelling camera moves that would exceed it
        next_pitch = self._pos["pitch"] + (action[3] - 12) * 15
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            action[3] = 12

        obs, reward, done, info = self.env.step(action)
        is_timelimit = info.get("TimeLimit.truncated", False)
        self._pos = self._read_position(obs)
        info.update(
            {
                "life_stats": {
                    "life": float(obs["life_stats"]["life"].item()),
                    "oxygen": float(obs["life_stats"]["oxygen"].item()),
                    "food": float(obs["life_stats"]["food"].item()),
                },
                "location_stats": copy.deepcopy(self._pos),
                "action": raw_action.tolist(),
                "biomeid": float(obs["location_stats"]["biome_id"].item()),
            }
        )
        return self._convert_obs(obs), reward, done and not is_timelimit, done and is_timelimit, info

    def reset(self, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs = self.env.reset()
        self._pos = self._read_position(obs)
        self._sticky_jump_counter = 0
        self._sticky_attack_counter = 0
        self._inventory_max = np.zeros(N_ALL_ITEMS)
        return self._convert_obs(obs), {
            "life_stats": {
                "life": float(obs["life_stats"]["life"].item()),
                "oxygen": float(obs["life_stats"]["oxygen"].item()),
                "food": float(obs["life_stats"]["food"].item()),
            },
            "location_stats": copy.deepcopy(self._pos),
            "biomeid": float(obs["location_stats"]["biome_id"].item()),
        }

    def render(self):
        if self.render_mode == "human":
            return super().render()
        if self.render_mode == "rgb_array":
            prev = self.env.unwrapped._prev_obs
            return None if prev is None else prev["rgb"]
        return None
