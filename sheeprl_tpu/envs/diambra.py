"""DIAMBRA Arena adapter (gated on ``diambra`` + ``diambra.arena``).

Behavioral counterpart of reference sheeprl/envs/diambra.py
(DiambraWrapper:22): arena settings assembly (role/action-space
validation, sticky-action step_ratio guard, frame-shape placement by
``increase_performance``), Discrete/MultiDiscrete observation entries
normalized to int32 Boxes, and ``env_domain`` info tagging."""

from __future__ import annotations

import warnings

from sheeprl_tpu.utils.imports import _IS_DIAMBRA_ARENA_AVAILABLE, _IS_DIAMBRA_AVAILABLE

if not _IS_DIAMBRA_AVAILABLE:
    raise ModuleNotFoundError(
        "diambra is not installed; DIAMBRA environments are unavailable. Install diambra to use them."
    )
if not _IS_DIAMBRA_ARENA_AVAILABLE:
    raise ModuleNotFoundError(
        "diambra.arena is not installed; DIAMBRA environments are unavailable. "
        "Install diambra-arena to use them."
    )

from typing import Any, Dict, Optional, Tuple, Union

import diambra
import diambra.arena
import gymnasium as gym
import numpy as np
from diambra.arena import EnvironmentSettings, WrappersSettings


class DiambraWrapper(gym.Wrapper):
    def __init__(
        self,
        id: str,
        action_space: str = "DISCRETE",
        screen_size: Union[int, Tuple[int, int]] = 64,
        grayscale: bool = False,
        repeat_action: int = 1,
        rank: int = 0,
        diambra_settings: Optional[Dict[str, Any]] = None,
        diambra_wrappers: Optional[Dict[str, Any]] = None,
        render_mode: str = "rgb_array",
        log_level: int = 0,
        increase_performance: bool = True,
    ) -> None:
        if isinstance(screen_size, int):
            screen_size = (screen_size,) * 2
        diambra_settings = dict(diambra_settings or {})
        diambra_wrappers = dict(diambra_wrappers or {})

        for disabled in ("frame_shape", "n_players"):
            if diambra_settings.pop(disabled, None) is not None:
                warnings.warn(f"The DIAMBRA {disabled} setting is disabled")

        role = diambra_settings.pop("role", None)
        if action_space not in {"DISCRETE", "MULTI_DISCRETE"}:
            raise ValueError(
                "The valid values for the `action_space` attribute are "
                f"'DISCRETE' or 'MULTI_DISCRETE', got {action_space}"
            )
        if role is not None and role not in {"P1", "P2"}:
            raise ValueError(f"The valid values for the `role` attribute are 'P1' or 'P2' or None, got {role}")
        self._action_type = action_space.lower()
        if repeat_action > 1:
            # sticky actions need the engine stepping one frame at a time
            if diambra_settings.get("step_ratio", 6) > 1:
                warnings.warn(
                    f"step_ratio parameter modified to 1 because the sticky action is active ({repeat_action})"
                )
            diambra_settings["step_ratio"] = 1
        settings = EnvironmentSettings(
            **{
                **diambra_settings,
                "game_id": id,
                "action_space": getattr(diambra.arena.SpaceTypes, action_space, diambra.arena.SpaceTypes.DISCRETE),
                "n_players": 1,
                "role": getattr(diambra.arena.Roles, role, diambra.arena.Roles.P1) if role is not None else None,
                "render_mode": render_mode,
            }
        )
        for disabled in ("frame_shape", "stack_frames", "dilation", "flatten"):
            if diambra_wrappers.pop(disabled, None) is not None:
                warnings.warn(f"The DIAMBRA {disabled} wrapper is disabled")
        wrappers = WrappersSettings(
            **{
                **diambra_wrappers,
                "flatten": True,
                "repeat_action": repeat_action,
            }
        )
        # resizing in the engine (settings) is faster than in the wrapper
        if increase_performance:
            settings.frame_shape = tuple(screen_size) + (int(grayscale),)
        else:
            wrappers.frame_shape = tuple(screen_size) + (int(grayscale),)
        env = diambra.arena.make(id, settings, wrappers, rank=rank, render_mode=render_mode, log_level=log_level)
        super().__init__(env)

        self.action_space = self.env.action_space
        obs = {}
        for k, space in self.env.observation_space.spaces.items():
            if isinstance(space, gym.spaces.Box):
                obs[k] = space
                continue
            if isinstance(space, gym.spaces.Discrete):
                low, high, shape = 0, space.n - 1, (1,)
            elif isinstance(space, gym.spaces.MultiDiscrete):
                low, high, shape = np.zeros_like(space.nvec), space.nvec - 1, (len(space.nvec),)
            else:
                raise RuntimeError(f"Invalid observation space, got: {type(space)}")
            obs[k] = gym.spaces.Box(low, high, shape, np.int32)
        self.observation_space = gym.spaces.Dict(obs)
        self._render_mode = render_mode

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def __getattr__(self, name):
        return getattr(self.env, name)

    def _convert_obs(self, obs: Dict[str, Union[int, np.ndarray]]) -> Dict[str, np.ndarray]:
        return {
            k: np.asarray(v).reshape(self.observation_space[k].shape) for k, v in obs.items()
        }

    def step(self, action: Any):
        if self._action_type == "discrete" and isinstance(action, np.ndarray):
            action = action.squeeze().item()
        obs, reward, terminated, truncated, infos = self.env.step(action)
        infos["env_domain"] = "DIAMBRA"
        return self._convert_obs(obs), reward, terminated or infos.get("env_done", False), truncated, infos

    def render(self, mode: str = "rgb_array", **kwargs):
        return self.env.render()

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, infos = self.env.reset(seed=seed, options=options)
        infos["env_domain"] = "DIAMBRA"
        return self._convert_obs(obs), infos
