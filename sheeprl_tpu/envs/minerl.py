"""MineRL adapter (gated on ``minerl``).

Behavioral counterpart of reference sheeprl/envs/minerl.py
(MineRLWrapper:48): builds the custom Navigate/ObtainDiamond/
ObtainIronPickaxe tasks (sheeprl_tpu.envs.minerl_envs), flattens the
MineRL dict action space to one Discrete space via an auto-derived
ACTIONS_MAP (enums expand to one action per value, camera to 4 fixed
15-degree moves, jump/sneak/sprint imply forward), converts observations
to fixed-size vectors (optionally multi-hot over the full Minecraft item
vocabulary), enforces pitch limits, and implements sticky attack/jump.

TPU-native divergence: the ``rgb`` observation stays channels-LAST (HWC)
to match the NHWC sheeprl_tpu pipeline (the reference transposes to CHW
for torch)."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError(
        "minerl is not installed; MineRL environments are unavailable. "
        "Install minerl==0.4.4 to use them."
    )

import copy
from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import minerl
import numpy as np
from minerl.herobraine.hero import mc

from sheeprl_tpu.envs.minerl_envs.navigate import CustomNavigate
from sheeprl_tpu.envs.minerl_envs.obtain import CustomObtainDiamond, CustomObtainIronPickaxe

CUSTOM_ENVS = {
    "custom_navigate": CustomNavigate,
    "custom_obtain_diamond": CustomObtainDiamond,
    "custom_obtain_iron_pickaxe": CustomObtainIronPickaxe,
}

N_ALL_ITEMS = len(mc.ALL_ITEMS)
NOOP = {
    "camera": (0, 0),
    "forward": 0,
    "back": 0,
    "left": 0,
    "right": 0,
    "attack": 0,
    "sprint": 0,
    "jump": 0,
    "sneak": 0,
    "craft": "none",
    "nearbyCraft": "none",
    "nearbySmelt": "none",
    "place": "none",
    "equip": "none",
}
ITEM_ID_TO_NAME = dict(enumerate(mc.ALL_ITEMS))
ITEM_NAME_TO_ID = dict(zip(mc.ALL_ITEMS, range(N_ALL_ITEMS)))


class MineRLWrapper(gym.Env):
    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        break_speed_multiplier: Optional[int] = 100,
        multihot_inventory: bool = True,
        **kwargs: Optional[Dict[Any, Any]],
    ):
        self._height = height
        self._width = width
        self._pitch_limits = pitch_limits
        # a high break-speed multiplier replaces the sticky attack
        self._sticky_attack = 0 if break_speed_multiplier > 1 else sticky_attack
        self._sticky_jump = sticky_jump
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._break_speed_multiplier = break_speed_multiplier
        self._multihot_inventory = multihot_inventory
        if "navigate" not in id.lower():
            kwargs.pop("extreme", None)

        env = CUSTOM_ENVS[id.lower()](break_speed=break_speed_multiplier, **kwargs).make()
        self.env = env

        # flatten the dict action space to one Discrete space: index 0 is
        # the no-op; enum actions expand to one index per value, camera to 4
        # fixed 15-degree moves, binary actions to one index
        self.ACTIONS_MAP: Dict[int, Dict[str, Any]] = {0: {}}
        act_idx = 1
        for act in self.env.action_space:
            if isinstance(self.env.action_space[act], minerl.herobraine.hero.spaces.Enum):
                # sorted so action indices are stable across processes
                # (spawned env workers have different hash seeds)
                act_val = sorted(set(self.env.action_space[act].values.tolist()) - {"none"})
                act_len = len(act_val)
            elif act != "camera":
                act_len = 1
                act_val = [1]
            else:
                act_len = 4
                act_val = [
                    np.array([-15, 0]),
                    np.array([15, 0]),
                    np.array([0, -15]),
                    np.array([0, 15]),
                ]
            action = dict(zip((np.arange(act_len) + act_idx).tolist(), [{act: v} for v in act_val]))
            # jumping/sneaking/sprinting in place is useless: pair with forward
            if act in {"jump", "sneak", "sprint"}:
                action[act_idx]["forward"] = 1
            self.ACTIONS_MAP.update(action)
            act_idx += act_len

        self.action_space = gym.spaces.Discrete(len(self.ACTIONS_MAP))

        if multihot_inventory:
            self.inventory_size = N_ALL_ITEMS
            self.inventory_item_to_id = ITEM_NAME_TO_ID
        else:
            self.inventory_size = len(self.env.observation_space["inventory"])
            self.inventory_item_to_id = dict(
                zip(self.env.observation_space["inventory"], range(self.inventory_size))
            )
        obs_space = {
            "rgb": gym.spaces.Box(0, 255, (height, width, 3), np.uint8),
            "life_stats": gym.spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
            "inventory": gym.spaces.Box(0.0, np.inf, (self.inventory_size,), np.float32),
            "max_inventory": gym.spaces.Box(0.0, np.inf, (self.inventory_size,), np.float32),
        }
        if "compass" in self.env.observation_space.spaces:
            obs_space["compass"] = gym.spaces.Box(-180, 180, (1,), np.float32)
        if "equipped_items" in self.env.observation_space.spaces:
            if multihot_inventory:
                self.equip_size = N_ALL_ITEMS
                self.equip_item_to_id = ITEM_NAME_TO_ID
            else:
                equip_values = self.env.observation_space["equipped_items"]["mainhand"]["type"].values.tolist()
                self.equip_size = len(equip_values)
                self.equip_item_to_id = dict(zip(equip_values, range(self.equip_size)))
            obs_space["equipment"] = gym.spaces.Box(0.0, 1.0, (self.equip_size,), np.int32)
        self.observation_space = gym.spaces.Dict(obs_space)

        self._pos = {"pitch": 0.0, "yaw": 0.0}
        self._max_inventory = np.zeros(self.inventory_size)
        self._render_mode = "rgb_array"
        self.seed(seed=seed)

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def __getattr__(self, name):
        if name.startswith("_") or name == "env":
            raise AttributeError(name)
        return getattr(self.env, name)

    def _convert_actions(self, action: np.ndarray) -> Dict[str, Any]:
        converted = copy.deepcopy(NOOP)
        converted.update(self.ACTIONS_MAP[action.item()])
        if self._sticky_attack:
            if converted["attack"]:
                self._sticky_attack_counter = self._sticky_attack
            if self._sticky_attack_counter > 0:
                converted["attack"] = 1
                converted["jump"] = 0
                self._sticky_attack_counter -= 1
        if self._sticky_jump:
            if converted["jump"]:
                self._sticky_jump_counter = self._sticky_jump
            if self._sticky_jump_counter > 0:
                converted["jump"] = 1
                converted["forward"] = 1
                self._sticky_jump_counter -= 1
        return converted

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        equip = np.zeros(self.equip_size, dtype=np.int32)
        try:
            equip[self.equip_item_to_id[equipment["mainhand"]["type"]]] = 1
        except KeyError:
            equip[self.equip_item_to_id["air"]] = 1
        return equip

    def _convert_inventory(self, inventory: Dict[str, Any]) -> Dict[str, np.ndarray]:
        converted = {"inventory": np.zeros(self.inventory_size)}
        for item, quantity in inventory.items():
            # air stacks count one per slot
            converted["inventory"][self.inventory_item_to_id[item]] += 1 if item == "air" else quantity
        converted["max_inventory"] = np.maximum(converted["inventory"], self._max_inventory)
        self._max_inventory = converted["max_inventory"].copy()
        return converted

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        converted = {
            "rgb": obs["pov"].copy(),
            "life_stats": np.array(
                [obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["air"]],
                dtype=np.float32,
            ),
            **self._convert_inventory(obs["inventory"]),
        }
        if "equipment" in self.observation_space.spaces:
            converted["equipment"] = self._convert_equipment(obs["equipped_items"])
        if "compass" in self.observation_space.spaces:
            converted["compass"] = obs["compass"]["angle"].reshape(-1)
        return converted

    def seed(self, seed: Optional[int] = None) -> None:
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    def step(self, actions: np.ndarray):
        converted_actions = self._convert_actions(actions)
        # clamp pitch by cancelling the vertical camera move
        next_pitch = self._pos["pitch"] + converted_actions["camera"][0]
        next_yaw = ((self._pos["yaw"] + converted_actions["camera"][1]) + 180) % 360 - 180
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            converted_actions["camera"] = np.array([0, converted_actions["camera"][1]])
            next_pitch = self._pos["pitch"]

        obs, reward, done, info = self.env.step(converted_actions)
        self._pos = {"pitch": next_pitch, "yaw": next_yaw}
        return self._convert_obs(obs), reward, done, False, info

    def reset(self, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs = self.env.reset()
        self._max_inventory = np.zeros(self.inventory_size)
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._pos = {"pitch": 0.0, "yaw": 0.0}
        return self._convert_obs(obs), {}

    def render(self, mode: Optional[str] = "rgb_array"):
        return self.env.render(self.render_mode)
