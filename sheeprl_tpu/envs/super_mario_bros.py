"""Super Mario Bros adapter (gated on ``gym_super_mario_bros``).

Behavioral counterpart of reference sheeprl/envs/super_mario_bros.py
(SuperMarioBrosWrapper:26): nes-py env behind a JoypadSpace with a
seedable reset, ``{"rgb": ...}`` dict observation, and time-limit-aware
terminated/truncated split."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_SUPER_MARIO_BROS_AVAILABLE

if not _IS_SUPER_MARIO_BROS_AVAILABLE:
    raise ModuleNotFoundError(
        "gym_super_mario_bros is not installed; Super Mario Bros environments "
        "are unavailable. Install gym_super_mario_bros to use them."
    )

from typing import Any, Dict, Optional, Union

import gym_super_mario_bros as gsmb
import gymnasium as gym
import numpy as np
from gym_super_mario_bros.actions import COMPLEX_MOVEMENT, RIGHT_ONLY, SIMPLE_MOVEMENT
from nes_py.wrappers import JoypadSpace

ACTIONS_SPACE_MAP = {"simple": SIMPLE_MOVEMENT, "right_only": RIGHT_ONLY, "complex": COMPLEX_MOVEMENT}


class JoypadSpaceCustomReset(JoypadSpace):
    """JoypadSpace whose reset forwards gymnasium's seed/options kwargs."""

    def reset(self, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        return self.env.reset(seed=seed, options=options)


class SuperMarioBrosWrapper(gym.Env):
    def __init__(self, id: str, action_space: str = "simple", render_mode: str = "rgb_array"):
        env = JoypadSpaceCustomReset(gsmb.make(id), ACTIONS_SPACE_MAP[action_space])
        self.env = env
        self._render_mode = render_mode
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(
                    env.observation_space.low,
                    env.observation_space.high,
                    env.observation_space.shape,
                    env.observation_space.dtype,
                )
            }
        )
        self.action_space = gym.spaces.Discrete(env.action_space.n)

    @property
    def render_mode(self) -> str:
        return self._render_mode

    @render_mode.setter
    def render_mode(self, render_mode: str) -> None:
        self._render_mode = render_mode

    def step(self, action: Union[np.ndarray, int]):
        if isinstance(action, np.ndarray):
            action = action.squeeze().item()
        obs, reward, done, info = self.env.step(action)
        # info["time"] is the in-game countdown clock: an episode ending with
        # time left is a real death (terminated), the clock hitting zero is a
        # timeout (truncated). The reference inverts this (its `is_timelimit
        # = info.get("time", False)` is truthy on deaths); fixed here.
        is_timeout = info.get("time", 1) == 0
        return {"rgb": obs.copy()}, reward, done and not is_timeout, done and is_timeout, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs = self.env.reset(seed=seed, options=options)
        return {"rgb": obs.copy()}, {}

    def render(self):
        frame = self.env.render(mode=self.render_mode)
        if self.render_mode == "rgb_array" and frame is not None:
            return frame.copy()
        return None
