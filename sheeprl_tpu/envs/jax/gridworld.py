"""Procedurally-generated gridworld/maze as a pure-JAX env.

The layout IS the random draw: every ``reset(key)`` samples a fresh wall
pattern, start cell and goal cell from the key, so domain randomization
costs nothing beyond the key axis — ``vmap`` over 4096 reset keys steps
4096 DIFFERENT mazes in one XLA program, and a curriculum/PBT sweep is
just a different key schedule (ROADMAP items 2 and 5).

Everything is fixed-shape jit-safe machinery:

- walls: ``(size, size)`` bernoulli(density) bool grid; the start and
  goal cells are force-cleared after sampling;
- start/goal cells: categorical draws over the FREE-cell mask (masked
  logits — no rejection loops);
- movement: 4 discrete actions; hitting a wall or the border is a no-op
  step (the agent stays put);
- observation (``"state"``): the egocentric ``view x view`` wall window
  (dynamic_slice over a wall-padded grid) ++ normalized position ++
  normalized goal offset — a flat f32 vector, MLP-encoder ready.

Reward: ``+1`` on reaching the goal (terminates), small per-step cost
otherwise; episodes truncate at ``max_episode_steps`` (random layouts
are not guaranteed solvable — truncation, not reachability analysis, is
the contract, exactly like procgen-style task distributions).
"""

from __future__ import annotations

from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv

# action index -> (drow, dcol)
_MOVES = np.array([[-1, 0], [1, 0], [0, -1], [0, 1]], np.int32)


class GridWorldJax(JaxEnv):
    """Procedural maze: layout drawn from the reset key.

    State pytree: ``{"walls": (S, S) bool, "pos": (2,) i32, "goal": (2,) i32}``.
    """

    def __init__(
        self,
        size: int = 9,
        view: int = 5,
        wall_density: float = 0.25,
        step_cost: float = 0.01,
        max_episode_steps: int = 128,
    ):
        if view % 2 != 1:
            raise ValueError(f"view must be odd, got {view}")
        self.size = int(size)
        self.view = int(view)
        self.wall_density = float(wall_density)
        self.step_cost = float(step_cost)
        self.max_episode_steps = int(max_episode_steps)
        self._conf = (self.size, self.view, self.wall_density, self.step_cost, self.max_episode_steps)
        obs_dim = self.view * self.view + 4
        self.observation_space = gym.spaces.Dict(
            {"state": gym.spaces.Box(-np.inf, np.inf, shape=(obs_dim,), dtype=np.float32)}
        )
        self.action_space = gym.spaces.Discrete(4)

    # ------------------------------------------------------------- helpers
    def _sample_free_cell(self, key: jax.Array, walls: jax.Array, exclude: jax.Array = None) -> jax.Array:
        """Random cell index (2,) over free (non-wall) cells; ``exclude``
        optionally removes one cell (the start, when drawing the goal)."""
        free = ~walls.reshape(-1)
        if exclude is not None:
            flat_ex = exclude[0] * self.size + exclude[1]
            free = free & (jnp.arange(self.size * self.size) != flat_ex)
        # masked categorical: every free cell equally likely, no loops.
        # degenerate draws (all walls) cannot happen: reset clears start/goal
        logits = jnp.where(free, 0.0, -jnp.inf)
        flat = jax.random.categorical(key, logits)
        return jnp.stack([flat // self.size, flat % self.size]).astype(jnp.int32)

    def _obs(self, state) -> Dict[str, jax.Array]:
        pad = self.view // 2
        # border reads as wall: pad the grid with True then slice the
        # egocentric window around pos (dynamic_slice is jit/vmap native)
        padded = jnp.pad(state["walls"], pad, constant_values=True)
        window = jax.lax.dynamic_slice(
            padded.astype(jnp.float32), (state["pos"][0], state["pos"][1]), (self.view, self.view)
        )
        denom = jnp.float32(max(self.size - 1, 1))
        pos = state["pos"].astype(jnp.float32) / denom
        offset = (state["goal"] - state["pos"]).astype(jnp.float32) / denom
        return {"state": jnp.concatenate([window.reshape(-1), pos, offset]).astype(jnp.float32)}

    # ------------------------------------------------------------- protocol
    def reset(self, key: jax.Array):
        k_walls, k_start, k_goal = jax.random.split(key, 3)
        walls = jax.random.bernoulli(k_walls, self.wall_density, (self.size, self.size))
        start = self._sample_free_cell(k_start, walls)
        goal = self._sample_free_cell(k_goal, walls, exclude=start)
        # force-clear both cells (the masked draws already avoid walls, but
        # an all-wall row/grid degenerate draw must still land on a free cell)
        walls = walls.at[start[0], start[1]].set(False)
        walls = walls.at[goal[0], goal[1]].set(False)
        state = {"walls": walls, "pos": start, "goal": goal}
        return state, self._obs(state)

    def step(self, state, action, key):
        del key  # deterministic dynamics; the LAYOUT is the random axis
        delta = jnp.asarray(_MOVES)[action.astype(jnp.int32)]
        proposed = jnp.clip(state["pos"] + delta, 0, self.size - 1)
        blocked = state["walls"][proposed[0], proposed[1]]
        pos = jnp.where(blocked, state["pos"], proposed)
        reached = jnp.all(pos == state["goal"])
        reward = jnp.where(reached, 1.0, -self.step_cost).astype(jnp.float32)
        new_state = {"walls": state["walls"], "pos": pos, "goal": state["goal"]}
        return new_state, self._obs(new_state), reward, reached, {}
