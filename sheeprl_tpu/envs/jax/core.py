"""Device-resident environment core: the ``JaxEnv`` protocol + the
vectorized auto-reset machinery (ROADMAP item 2).

Every bench round since PR 3 measured the same wall: the host env step.
``SyncVectorEnv`` bounds the decoupled ratio, the overlap pipeline has
nothing to overlap *with* (0.67-0.81x on 1-core hosts), and the N-player
fan-in stays Python-bound.  A :class:`JaxEnv` removes the wall instead of
hiding it: dynamics are pure jax functions over pytree state, so
thousands of parallel envs ride ONE ``vmap``, auto-reset folds into the
step via ``lax.select`` (no host round trip at episode boundaries), and
the whole policy-step + env-step + buffer-append loop compiles into a
single XLA program (``envs/jax/collect.py``).

Design rules (every env family must hold them):

- ``reset``/``step`` are PURE: state in, state out, all pytrees of
  fixed-shape arrays — jit/vmap/scan-safe by construction;
- ALL randomness flows through explicit PRNG keys.  Domain randomization
  is therefore just an extra key axis: an env that draws its layout /
  physics params at ``reset`` sweeps a *distribution* of scenarios under
  one ``vmap`` over reset keys, one compiled program;
- episode-boundary bookkeeping (auto-reset, time-limit truncation,
  episode return/length) lives HERE, not in the env families — one
  implementation, shared semantics, matching the gymnasium SAME_STEP
  autoreset mode the host path uses (``utils/env.py``).

Key discipline (pinned by the autoreset-parity golden test): every key
consumed by env ``i`` derives from the run ``base`` key as

- initial reset:      ``fold_in(fold_in(fold_in(base, 0), i), 0)``
- step ``t`` (global): ``split(fold_in(fold_in(fold_in(base, 1), t), i))``
  -> ``(k_step, k_reset)`` — ``k_reset`` seeds the auto-reset episode.

The host-side :class:`~sheeprl_tpu.envs.jax.gym_adapter.JaxToGymEnv`
mirrors the same chains, so a ``JaxVectorEnv`` rollout and a gymnasium
``SyncVectorEnv`` over the adapter produce bit-identical trajectories —
the parity test that keeps the two stacks honest.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

# fold_in tags separating the initial-reset chain from the step chain
RESET_TAG = 0
STEP_TAG = 1


class JaxEnv:
    """Protocol/base class for device-resident environments.

    Subclasses implement single-env (unbatched) semantics; batching is
    the caller's ``vmap``.  ``info`` dicts must have a FIXED key set and
    fixed-shape array values (scan/vmap requirement); return ``{}`` when
    there is nothing to report.
    """

    #: gymnasium spaces describing ONE env (host-side metadata only —
    #: never consumed inside jit)
    observation_space: Any = None
    action_space: Any = None
    #: steps after which an episode truncates (None = never); consumed by
    #: the vector wrapper, NOT by the env's own ``step``
    max_episode_steps: Optional[int] = None
    #: hashable config tuple set by subclasses — envs are passed as STATIC
    #: jit arguments (``gym_adapter``), so two instances with the same
    #: config must share one compiled executable instead of recompiling
    #: per vector slot
    _conf: Tuple = ()

    def __hash__(self) -> int:
        return hash((type(self), self._conf))

    def __eq__(self, other: Any) -> bool:
        return type(other) is type(self) and other._conf == self._conf

    def reset(self, key: jax.Array) -> Tuple[Any, Dict[str, jax.Array]]:
        """``key -> (state, obs)``; draws initial state (and any
        domain-randomized params) from ``key``."""
        raise NotImplementedError

    def step(
        self, state: Any, action: jax.Array, key: jax.Array
    ) -> Tuple[Any, Dict[str, jax.Array], jax.Array, jax.Array, Dict[str, jax.Array]]:
        """``(state, action, key) -> (state, obs, reward, terminated, info)``.

        ``terminated`` is the MDP-terminal signal only; time-limit
        truncation is the vector wrapper's job (the env never sees it).
        """
        raise NotImplementedError


def tree_select(pred: jax.Array, on_true: Any, on_false: Any) -> Any:
    """Per-env ``jnp.where`` over matching pytrees.

    ``pred`` is a ``(N,)`` bool vector; leaves are ``(N, ...)`` — the
    predicate broadcasts over each leaf's trailing dims.  This is the
    auto-reset fold: done envs take the freshly-reset leaf, live envs
    keep the stepped one, no host involvement.
    """

    def _sel(a, b):
        shaped = pred.reshape(pred.shape + (1,) * (a.ndim - pred.ndim))
        return jnp.where(shaped, a, b)

    return jax.tree_util.tree_map(_sel, on_true, on_false)


def initial_reset_key(base: jax.Array, env_index) -> jax.Array:
    """Reset key of env ``env_index``'s FIRST episode (see key discipline
    in the module docstring)."""
    return jax.random.fold_in(jax.random.fold_in(jax.random.fold_in(base, RESET_TAG), env_index), 0)


def step_keys(base: jax.Array, gstep, env_index) -> Tuple[jax.Array, jax.Array]:
    """``(k_step, k_reset)`` for env ``env_index`` at global step
    ``gstep``: ``k_step`` drives the dynamics, ``k_reset`` seeds the
    auto-reset episode if this step ends one."""
    k = jax.random.fold_in(jax.random.fold_in(jax.random.fold_in(base, STEP_TAG), gstep), env_index)
    ks = jax.random.split(k)
    return ks[0], ks[1]


def vector_reset(env: JaxEnv, base: jax.Array, num_envs: int) -> Dict[str, Any]:
    """Reset ``num_envs`` parallel envs; returns the vector state pytree.

    The vector state carries, besides the batched env state and current
    obs, the per-env episode accounting (steps since reset, running
    return/length) and the GLOBAL step counter feeding the key chain.
    """
    keys = jax.vmap(lambda i: initial_reset_key(base, i))(jnp.arange(num_envs))
    state, obs = jax.vmap(env.reset)(keys)
    zf = jnp.zeros((num_envs,), jnp.float32)
    zi = jnp.zeros((num_envs,), jnp.int32)
    return {
        "env": state,
        "obs": obs,
        "t": zi,  # per-env steps since reset (time-limit clock)
        "ep_return": zf,
        "ep_length": zi,
        "gstep": jnp.zeros((), jnp.int32),  # global step (key chain)
    }


def vector_step(
    env: JaxEnv,
    vstate: Dict[str, Any],
    actions: jax.Array,
    base: jax.Array,
    max_episode_steps: Optional[int] = None,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """One auto-resetting step of every parallel env (SAME_STEP semantics).

    Returns ``(new_vstate, out)`` where ``out`` is a dict of batched
    arrays::

        obs        post-autoreset observation (what the policy acts on
                   next; reset obs where the episode ended — exactly the
                   gymnasium SAME_STEP contract)
        reward, terminated, truncated, done
        final_obs  the PRE-reset terminal observation (valid where done;
                   the truncation bootstrap and final_obs info use it)
        ep_return / ep_length
                   the episode totals INCLUDING this step (valid where
                   done — the RecordEpisodeStatistics ``r``/``l`` fields)

    Everything is fixed-shape; "valid where done" fields are dense with a
    mask, never ragged — the scan/telemetry consumers slice them.
    """
    num_envs = vstate["t"].shape[0]
    idx = jnp.arange(num_envs)
    k_step, k_reset = jax.vmap(lambda i: step_keys(base, vstate["gstep"], i))(idx)

    new_env, obs, reward, terminated, _info = jax.vmap(env.step)(vstate["env"], actions, k_step)
    reward = reward.astype(jnp.float32).reshape(num_envs)
    terminated = terminated.reshape(num_envs).astype(bool)

    t = vstate["t"] + 1
    limit = max_episode_steps if max_episode_steps is not None else env.max_episode_steps
    if limit:
        truncated = (t >= jnp.int32(limit)) & ~terminated
    else:
        truncated = jnp.zeros_like(terminated)
    done = terminated | truncated

    reset_env, reset_obs = jax.vmap(env.reset)(k_reset)
    next_env = tree_select(done, reset_env, new_env)
    next_obs = tree_select(done, reset_obs, obs)

    ep_return = vstate["ep_return"] + reward
    ep_length = vstate["ep_length"] + 1

    out = {
        "obs": next_obs,
        "reward": reward,
        "terminated": terminated,
        "truncated": truncated,
        "done": done,
        "final_obs": obs,
        "ep_return": ep_return,
        "ep_length": ep_length,
    }
    new_vstate = {
        "env": next_env,
        "obs": next_obs,
        "t": jnp.where(done, 0, t),
        "ep_return": jnp.where(done, 0.0, ep_return),
        "ep_length": jnp.where(done, 0, ep_length),
        "gstep": vstate["gstep"] + 1,
    }
    return new_vstate, out
