"""``JaxVectorEnv`` — device-resident envs behind the gymnasium vector API.

The drop-in tier of ROADMAP item 2: every existing loop (PPO/A2C/SAC/
recurrent, decoupled players, the serve workers) steps a vector env
through ``reset``/``step`` and reads SAME_STEP autoreset infos
(``final_obs`` / ``final_info`` with episode statistics).  This class
reproduces that exact contract while the N envs live on the accelerator:

- one jitted program per ``step`` call steps ALL envs (vmap) with
  auto-reset folded in (``core.vector_step``) — no per-env Python loop,
  no episode-boundary host round trip;
- outputs come back as numpy (this adapter IS the host boundary; the
  fused collector in ``collect.py`` is the zero-round-trip tier);
- info structure mirrors gymnasium's SAME_STEP vector envs wrapped in
  ``RecordEpisodeStatistics`` — pinned by the autoreset-parity golden
  test against a real gymnasium ``SyncVectorEnv`` over the
  ``JaxToGymEnv`` adapter.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv, vector_reset, vector_step


class JaxVectorEnv(gym.vector.VectorEnv):
    """Vectorized auto-resetting view of one :class:`JaxEnv` family.

    All ``num_envs`` instances share the dynamics family; per-env variety
    (procedural layouts, randomized physics) comes from each env's reset
    key — domain randomization as a key axis.
    """

    metadata = {"autoreset_mode": gym.vector.AutoresetMode.SAME_STEP}

    def __init__(
        self,
        env: JaxEnv,
        num_envs: int,
        seed: int = 0,
        max_episode_steps: Optional[int] = None,
    ):
        self.env = env
        self.num_envs = int(num_envs)
        self._seed = int(seed)
        self._max_steps = max_episode_steps if max_episode_steps is not None else env.max_episode_steps
        self.single_observation_space = env.observation_space
        self.single_action_space = env.action_space
        self.observation_space = gym.vector.utils.batch_space(env.observation_space, self.num_envs)
        self.action_space = gym.vector.utils.batch_space(env.action_space, self.num_envs)
        self._discrete = isinstance(env.action_space, gym.spaces.Discrete)
        # one trace each; fixed shapes, so the compile counter stays flat
        self._jreset = jax.jit(lambda base: vector_reset(env, base, self.num_envs))
        self._jstep = jax.jit(
            lambda vstate, actions, base: vector_step(env, vstate, actions, base, self._max_steps)
        )
        self._vstate = None
        self._episode_start_ts = 0.0

    # ------------------------------------------------------------------ api
    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None):
        if seed is not None:
            self._seed = int(seed)
        self._base = jax.random.PRNGKey(self._seed)
        self._vstate = self._jreset(self._base)
        # seeded spaces: envs.action_space.sample() (SAC warmup) is
        # deterministic given the run seed, like the host path's per-env
        # space seeding in make_env
        self.action_space.seed(self._seed)
        self.single_action_space.seed(self._seed)
        self._episode_start_ts = time.perf_counter()
        obs = {k: np.asarray(v) for k, v in self._vstate["obs"].items()}
        return obs, {}

    def step(self, actions):
        if self._vstate is None:
            raise RuntimeError("JaxVectorEnv.step called before reset()")
        acts = np.asarray(actions)
        if self._discrete:
            acts = acts.reshape(self.num_envs).astype(np.int32)
        else:
            acts = acts.reshape(self.num_envs, *self.single_action_space.shape).astype(np.float32)
        self._vstate, out = self._jstep(self._vstate, acts, self._base)

        obs = {k: np.asarray(v) for k, v in out["obs"].items()}
        reward = np.asarray(out["reward"], dtype=np.float64).reshape(self.num_envs)
        terminated = np.asarray(out["terminated"]).reshape(self.num_envs)
        truncated = np.asarray(out["truncated"]).reshape(self.num_envs)
        done = terminated | truncated

        infos: Dict[str, Any] = {}
        if done.any():
            final_obs_np = {k: np.asarray(v) for k, v in out["final_obs"].items()}
            final_obs = np.full(self.num_envs, None, dtype=object)
            for i in np.nonzero(done)[0]:
                final_obs[i] = {k: v[i] for k, v in final_obs_np.items()}
            ep_r = np.where(done, np.asarray(out["ep_return"], dtype=np.float64), 0.0)
            ep_l = np.where(done, np.asarray(out["ep_length"]), 0)
            ep_t = np.where(done, round(time.perf_counter() - self._episode_start_ts, 6), 0.0)
            infos["final_obs"] = final_obs
            infos["_final_obs"] = done.copy()
            infos["final_info"] = {
                "episode": {
                    "r": ep_r,
                    "_r": done.copy(),
                    "l": ep_l,
                    "_l": done.copy(),
                    "t": ep_t,
                    "_t": done.copy(),
                },
                "_episode": done.copy(),
            }
            infos["_final_info"] = done.copy()
        return obs, reward, terminated, truncated, infos

    def close_extras(self, **kwargs):
        self._vstate = None

    def __repr__(self) -> str:
        return f"JaxVectorEnv({type(self.env).__name__}, num_envs={self.num_envs})"
