"""Fused collect: policy-step + env-step + buffer-append as ONE XLA program.

The real prize of device-resident envs (``algo.env_backend=jax``).  The
host collectors (``parallel/pipeline.py``) pay, per env step: a jitted
policy dispatch, an action fetch, a Python vector-env loop, and a numpy
buffer write — then one host->device upload per rollout.  Here the whole
rollout is a single ``lax.scan`` over ``algo.rollout_steps``:

- the policy samples actions from the CURRENT obs (same agent module the
  update trains — no separate player network, no weight transfer);
- ``core.vector_step`` advances all N envs with auto-reset folded in;
- truncation bootstrapping (reward += gamma * V(final_obs), exactly the
  host collectors' fixed-shape substitute-rows scheme) runs on device;
- the per-step records stack into the (T, N, ...) rollout layout the
  update functions already consume — the "buffer append" is the scan's
  output stacking, there is no buffer.

One dispatch per rollout, zero host round trips, one trace (fixed
shapes — the post-warmup compile counter stays flat, asserted in tests
and the bench ladder).

Episode returns/lengths accumulate on device inside the scan; the host
fetches them at the existing ``metric.fetch_every`` cadence (same
SUBSAMPLING semantics as the losses fetch: skipped rollouts' episode
events are dropped, not deferred — ``configs/metric/default.yaml``).

The collectors below expose the exact ``collect(iter_num, inline,
key_fn)`` contract of ``OnPolicyCollector`` / ``RecurrentCollector``, so
the loops drive them through the same ``PipelinedCollector`` scaffold
(always on its serial path: ``resolve_overlap_setting`` forces the
overlap OFF for this backend — there is no host work left to overlap).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import tree_select, vector_reset, vector_step
from sheeprl_tpu.envs.jax.vector import JaxVectorEnv
from sheeprl_tpu.parallel.pipeline import RolloutPayload
from sheeprl_tpu.utils.utils import MetricFetchGate

__all__ = ["FusedOnPolicyCollector", "FusedRecurrentCollector"]


class _FusedCollectorBase:
    """Shared scaffolding: params adoption, episode-event fetch cadence,
    policy-step accounting, telemetry counters."""

    def __init__(
        self,
        *,
        envs: JaxVectorEnv,
        module: Any,
        params: Any,
        cfg: Any,
        runtime: Any,
        obs_keys: Sequence[str],
        total_envs: int,
        world_size: int,
        aggregator: Any = None,
        policy_step: int = 0,
    ):
        self.envs = envs
        self.jax_env = envs.env
        self.module = module
        self.params = params
        self.cfg = cfg
        self.runtime = runtime
        self.obs_keys = list(obs_keys)
        self.total_envs = int(total_envs)
        self.world_size = int(world_size)
        self.aggregator = aggregator
        self.policy_step = int(policy_step)
        self.max_episode_steps = envs._max_steps
        self.rollout_steps = int(cfg.algo.rollout_steps)
        # device env state: seeded from the run seed, SAME key discipline
        # as JaxVectorEnv/JaxToGymEnv (core.py module docstring)
        self._env_base = jax.random.PRNGKey(int(cfg.seed))
        self._jinit = jax.jit(lambda base: self._initial_carry(base))
        # commit the initial carry to the mesh-replicated layout: rollout
        # outputs inherit the params' NamedSharding, so an uncommitted
        # first carry would make collect #2 a different arg-sharding
        # signature — one extra compile, breaking the flat-counter contract
        self._carry = jax.device_put(self._jinit(self._env_base), runtime.replicated)
        self._rollout = jax.jit(self._rollout_fn)
        # device->host episode-event fetch cadence (metric.fetch_every)
        self._event_gate = MetricFetchGate(cfg.metric.get("fetch_every", 1))
        self._log_events = int(cfg.metric.get("log_level", 1)) > 0
        # telemetry counters (obs/__init__.py "jaxenv" record section)
        self._n_rollouts = 0
        self._n_episodes = 0
        self._n_event_fetches = 0

    # subclasses implement
    def _initial_carry(self, base):
        raise NotImplementedError

    def _rollout_fn(self, params, carry, key, env_base):
        raise NotImplementedError

    def adopt(self, params: Any) -> None:
        """Params handoff target for ``PipelinedCollector``'s adopt hook —
        the fused program acts on whatever was last adopted (serial path:
        exactly the previous iteration's update, the host loops' order)."""
        self.params = params

    def _apply_events(self, events: Dict[str, Any], step_start: int) -> None:
        """Fetch + emit on-device episode events at the fetch cadence."""
        if not self._log_events or self.aggregator is None:
            return
        if not self._event_gate():
            return
        self._n_event_fetches += 1
        done = np.asarray(events["done"])  # (T, N)
        if not done.any():
            return
        ep_ret = np.asarray(events["ep_return"])
        ep_len = np.asarray(events["ep_length"])
        per_step = self.total_envs  # policy steps per scan step (global)
        for t, i in zip(*np.nonzero(done)):
            self._n_episodes += 1
            ep_rew = float(ep_ret[t, i])
            if self.aggregator and "Rewards/rew_avg" in self.aggregator:
                self.aggregator.update("Rewards/rew_avg", ep_rew)
            if self.aggregator and "Game/ep_len_avg" in self.aggregator:
                self.aggregator.update("Game/ep_len_avg", float(ep_len[t, i]))
            self.runtime.print(
                f"Rank-0: policy_step={step_start + (int(t) + 1) * per_step}, "
                f"reward_env_{int(i)}={ep_rew}"
            )

    def stats(self) -> Dict[str, Any]:
        """Telemetry provider (``jaxenv`` key in telemetry.jsonl)."""
        return {
            "backend": "jax",
            "fused": True,
            "env": type(self.jax_env).__name__,
            "num_envs": self.total_envs,
            "rollout_steps": self.rollout_steps,
            "rollouts": self._n_rollouts,
            "env_steps": self._n_rollouts * self.rollout_steps * self.total_envs,
            "episodes_reported": self._n_episodes,
            "event_fetches": self._n_event_fetches,
        }


class FusedOnPolicyCollector(_FusedCollectorBase):
    """Fused drop-in for the PPO/A2C ``OnPolicyCollector.collect``."""

    def _initial_carry(self, base):
        return vector_reset(self.jax_env, base, self.total_envs)

    def _rollout_fn(self, params, carry, key, env_base):
        from sheeprl_tpu.algos.ppo.agent import get_values, sample_actions
        from sheeprl_tpu.algos.ppo.utils import normalize_obs

        cfg = self.cfg
        env = self.jax_env
        obs_keys = tuple(self.obs_keys)
        cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
        gamma = float(cfg.algo.gamma)
        clip_rewards = bool(cfg.env.clip_rewards)
        max_steps = self.max_episode_steps
        discrete = not self.module.is_continuous

        def norm(obs):
            return normalize_obs({k: obs[k].astype(jnp.float32) for k in obs_keys}, cnn_keys, obs_keys)

        def step_fn(vstate, k_pol):
            obs = vstate["obs"]
            flat, real, logprobs, values = sample_actions(self.module, params, norm(obs), k_pol)
            act = real[..., 0] if discrete else flat
            new_vstate, out = vector_step(env, vstate, act, env_base, max_steps)
            rewards = out["reward"][:, None]
            if max_steps:
                # truncation bootstrap — the host collectors' fixed-shape
                # scheme: value the full env batch with terminal rows
                # substituted, add gamma * V only on truncated rows.  The
                # critic forward rides a lax.cond so the (common) steps
                # with no truncation skip it at runtime — the host path
                # likewise only values on actual truncations
                def _bootstrap():
                    real_next = tree_select(out["truncated"], out["final_obs"], out["obs"])
                    return get_values(self.module, params, norm(real_next))

                vals = jax.lax.cond(
                    out["truncated"].any(),
                    _bootstrap,
                    lambda: jnp.zeros((out["reward"].shape[0], 1), jnp.float32),
                )
                rewards = rewards + gamma * vals * out["truncated"][:, None].astype(jnp.float32)
            if clip_rewards:
                rewards = jnp.tanh(rewards)
            rec = {k: obs[k].astype(jnp.float32) for k in obs_keys}
            rec.update(
                dones=out["done"][:, None].astype(jnp.float32),
                values=values.astype(jnp.float32),
                actions=flat.astype(jnp.float32),
                logprobs=logprobs.astype(jnp.float32),
                rewards=rewards.astype(jnp.float32),
            )
            ev = {"done": out["done"], "ep_return": out["ep_return"], "ep_length": out["ep_length"]}
            return new_vstate, (rec, ev)

        keys = jax.random.split(jnp.asarray(key), self.rollout_steps)
        carry, (data, events) = jax.lax.scan(step_fn, carry, keys)
        return carry, data, events

    def collect(self, iter_num: int, inline: bool, key_fn) -> RolloutPayload:
        from sheeprl_tpu.utils.metric import SumMetric
        from sheeprl_tpu.utils.timer import timer

        payload = RolloutPayload(iter_num)
        step_start = self.policy_step
        with timer("Time/env_interaction_time", SumMetric, sync_on_compute=False):
            self._carry, data, events = self._rollout(self.params, self._carry, key_fn(), self._env_base)
        self._n_rollouts += 1
        self.policy_step += self.rollout_steps * self.total_envs
        self._apply_events(events, step_start)
        payload.data = data
        payload.next_obs = {k: self._carry["obs"][k] for k in self.obs_keys}
        payload.policy_step_end = self.policy_step
        return payload


class FusedRecurrentCollector(_FusedCollectorBase):
    """Fused drop-in for ``RecurrentCollector.collect`` (recurrent PPO):
    the scan carry additionally threads (hx, cx, prev_actions), captures
    the PRE-action recurrent state per step (what the update conditions
    on) and zeroes it on done (``algo.reset_recurrent_state_on_done``),
    and the payload carries the bootstrap ``next_values`` extra."""

    def _initial_carry(self, base):
        h = self.module.rnn_hidden_size
        a = sum(self.module.actions_dim)
        return {
            "vstate": vector_reset(self.jax_env, base, self.total_envs),
            "hx": jnp.zeros((self.total_envs, h), jnp.float32),
            "cx": jnp.zeros((self.total_envs, h), jnp.float32),
            "prev_actions": jnp.zeros((1, self.total_envs, a), jnp.float32),
        }

    def _rollout_fn(self, params, carry, key, env_base):
        from sheeprl_tpu.algos.ppo.utils import normalize_obs
        from sheeprl_tpu.algos.ppo_recurrent.agent import get_values, sample_actions

        cfg = self.cfg
        env = self.jax_env
        obs_keys = tuple(self.obs_keys)
        cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
        gamma = float(cfg.algo.gamma)
        clip_rewards = bool(cfg.env.clip_rewards)
        reset_on_done = bool(cfg.algo.reset_recurrent_state_on_done)
        max_steps = self.max_episode_steps
        discrete = not self.module.is_continuous
        n = self.total_envs

        def norm(obs):
            # (T=1, B, ...) layout — what the recurrent module consumes
            # (host parity: ppo_recurrent.utils.prepare_obs)
            return normalize_obs(
                {k: obs[k][None].astype(jnp.float32) for k in obs_keys}, cnn_keys, obs_keys
            )

        def step_fn(c, k_pol):
            vstate = c["vstate"]
            obs = vstate["obs"]
            prev_hx, prev_cx, prev_actions = c["hx"], c["cx"], c["prev_actions"]
            flat, real, logprobs, values, (hx, cx) = sample_actions(
                self.module, params, norm(obs), prev_actions, prev_hx, prev_cx, k_pol
            )
            act = real.reshape(n, -1)[..., 0] if discrete else flat.reshape(n, -1)
            new_vstate, out = vector_step(env, vstate, act, env_base, max_steps)
            rewards = out["reward"][:, None]
            if max_steps:
                # host parity: the bootstrap values use the POST-action
                # recurrent state and the just-taken actions; the forward
                # rides a lax.cond — no-truncation steps skip it at runtime
                def _bootstrap():
                    real_next = tree_select(out["truncated"], out["final_obs"], out["obs"])
                    return get_values(self.module, params, norm(real_next), flat, hx, cx).reshape(n, -1)[
                        :, :1
                    ]

                vals = jax.lax.cond(
                    out["truncated"].any(),
                    _bootstrap,
                    lambda: jnp.zeros((n, 1), jnp.float32),
                )
                rewards = rewards + gamma * vals * out["truncated"][:, None].astype(jnp.float32)
            if clip_rewards:
                rewards = jnp.tanh(rewards)
            new_prev_actions = flat if flat.ndim == 3 else flat[None]
            if reset_on_done:
                keep = (1.0 - out["done"].astype(jnp.float32))[:, None]
                hx = hx * keep
                cx = cx * keep
                new_prev_actions = new_prev_actions * keep[None]
            rec = {k: obs[k].astype(jnp.float32) for k in obs_keys}
            rec.update(
                dones=out["done"][:, None].astype(jnp.float32),
                values=values.reshape(n, -1).astype(jnp.float32),
                actions=flat.reshape(n, -1).astype(jnp.float32),
                logprobs=logprobs.reshape(n, -1).astype(jnp.float32),
                rewards=rewards.astype(jnp.float32),
                prev_hx=prev_hx.astype(jnp.float32),
                prev_cx=prev_cx.astype(jnp.float32),
                prev_actions=prev_actions.reshape(n, -1).astype(jnp.float32),
            )
            ev = {"done": out["done"], "ep_return": out["ep_return"], "ep_length": out["ep_length"]}
            new_c = {"vstate": new_vstate, "hx": hx, "cx": cx, "prev_actions": new_prev_actions}
            return new_c, (rec, ev)

        keys = jax.random.split(jnp.asarray(key), self.rollout_steps)
        carry, (data, events) = jax.lax.scan(step_fn, carry, keys)
        next_values = get_values(
            self.module,
            params,
            norm(carry["vstate"]["obs"]),
            carry["prev_actions"],
            carry["hx"],
            carry["cx"],
        ).reshape(n, -1)
        return carry, data, events, next_values

    def collect(self, iter_num: int, inline: bool, key_fn) -> RolloutPayload:
        from sheeprl_tpu.utils.metric import SumMetric
        from sheeprl_tpu.utils.timer import timer

        payload = RolloutPayload(iter_num)
        step_start = self.policy_step
        with timer("Time/env_interaction_time", SumMetric, sync_on_compute=False):
            self._carry, data, events, next_values = self._rollout(
                self.params, self._carry, key_fn(), self._env_base
            )
        self._n_rollouts += 1
        self.policy_step += self.rollout_steps * self.total_envs
        self._apply_events(events, step_start)
        payload.data = data
        payload.next_obs = {k: self._carry["vstate"]["obs"][k] for k in self.obs_keys}
        payload.extras["next_values"] = next_values
        payload.policy_step_end = self.policy_step
        return payload
