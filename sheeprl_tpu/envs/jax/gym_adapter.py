"""Host-side gymnasium adapter over a :class:`JaxEnv`.

Two jobs:

1. ``env_backend=host`` compatibility: the jax env families are ordinary
   gym envs through this adapter, so ``make_env``'s wrapper chain, the
   test-episode rollout, video capture and the Sync/Async vector envs all
   work unchanged (``configs/env/jax_*.yaml`` point their ``wrapper``
   target at :func:`make_gym_env`);
2. the autoreset-parity oracle: the adapter consumes EXACTLY the key
   chains of ``core.py`` (initial-reset / per-step / auto-reset keys), so
   a gymnasium ``SyncVectorEnv`` over pinned adapters and a
   ``JaxVectorEnv`` produce bit-identical trajectories — the golden test
   that keeps the device-resident fast path semantically honest.

The single-env step/reset functions are jitted with the env as a STATIC
argument; :class:`JaxEnv` instances hash by (type, config), so N adapter
instances over the same env family share ONE compiled executable instead
of recompiling per vector slot.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.envs.jax.core import RESET_TAG, JaxEnv, initial_reset_key, step_keys


@partial(jax.jit, static_argnums=0)
def _jit_reset(env: JaxEnv, key):
    return env.reset(key)


@partial(jax.jit, static_argnums=0)
def _jit_step(env: JaxEnv, state, action, key):
    return env.step(state, action, key)


class JaxToGymEnv(gym.Env):
    """One :class:`JaxEnv` behind the standard ``gym.Env`` API.

    ``seed`` / ``env_index`` pin the adapter to the shared key
    discipline: ``base = PRNGKey(seed)``; with ``pin_keys=True`` the
    chain additionally ignores ``reset(seed=...)`` overrides so a
    lockstep gymnasium vector run replays the exact ``JaxVectorEnv``
    trajectory (the parity test's configuration).  The default
    (``pin_keys=False``) honors ``reset(seed=...)`` like any gym env —
    what ``make_env``'s per-env seeding expects.
    """

    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}
    render_mode = "rgb_array"

    def __init__(self, env: JaxEnv, seed: int = 0, env_index: int = 0, pin_keys: bool = False):
        self.jax_env = env
        self.observation_space = env.observation_space
        self.action_space = env.action_space
        self._env_index = int(env_index)
        self._pin_keys = bool(pin_keys)
        self._base = jax.random.PRNGKey(int(seed))
        self._gstep = 0  # global step ordinal (the vector env's gstep)
        self._reset_count = 0
        self._t = 0  # steps since reset (time-limit clock)
        self._state = None
        self._pending_reset_key = None  # autoreset key stashed at done

    # ------------------------------------------------------------------ api
    def reset(self, seed: Optional[int] = None, options: Optional[dict] = None):
        super().reset(seed=seed)
        if seed is not None and not self._pin_keys:
            self._base = jax.random.PRNGKey(int(seed))
            self._reset_count = 0
            self._pending_reset_key = None
        if self._pending_reset_key is not None:
            # gymnasium's SAME_STEP machinery resetting us right after the
            # terminal step: consume the SAME k_reset the fused/vector path
            # derives from that step's key — episodes line up bit-exactly
            key = self._pending_reset_key
            self._pending_reset_key = None
        elif self._reset_count == 0:
            key = initial_reset_key(self._base, self._env_index)
        else:
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(self._base, RESET_TAG), self._env_index),
                self._reset_count,
            )
        self._reset_count += 1
        self._t = 0
        self._state, obs = _jit_reset(self.jax_env, key)
        return {k: np.asarray(v) for k, v in obs.items()}, {}

    def step(self, action):
        k_step, k_reset = step_keys(self._base, self._gstep, self._env_index)
        self._gstep += 1
        act = np.asarray(action)
        self._state, obs, reward, terminated, _info = _jit_step(self.jax_env, self._state, act, k_step)
        self._t += 1
        terminated = bool(terminated)
        limit = self.jax_env.max_episode_steps
        truncated = bool(limit) and self._t >= int(limit) and not terminated
        if terminated or truncated:
            self._pending_reset_key = k_reset
        return (
            {k: np.asarray(v) for k, v in obs.items()},
            float(reward),
            terminated,
            truncated,
            {},
        )

    def render(self):
        return np.zeros((64, 64, 3), dtype=np.uint8)

    def close(self):
        pass


def make_gym_env(id: str, seed: int = 0, **kwargs: Any) -> gym.Env:
    """``env.wrapper`` factory for the jax env families on the HOST path
    (``configs/env/jax_*.yaml``): resolves ``id`` through the jax env
    registry and wraps it for gymnasium."""
    from sheeprl_tpu.envs.jax import make_jax_env

    return JaxToGymEnv(make_jax_env(id, **kwargs), seed=seed)
