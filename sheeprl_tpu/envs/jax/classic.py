"""Classic-control dynamics as pure-JAX envs (CartPole / Pendulum class).

Same physics as the gymnasium references (cartpole.py / pendulum.py),
re-derived as pure functions so ``vmap`` batches thousands of instances
and the fused collector scans them inside one XLA program.

Domain randomization rides the PRNG: with ``randomize=True`` each reset
draws per-episode physics scale factors from its key, so a ``vmap`` over
reset keys is a parameter SWEEP — every parallel env integrates a
slightly different plant, one compiled program covering the whole
distribution (the scenario-diversity play of ROADMAP item 2).

Observations are dict pytrees keyed ``"state"`` — the same shape/key
contract the host envs expose after ``make_env``'s dict-ification, so
``algo.mlp_keys.encoder=[state]`` works unchanged on either backend.
"""

from __future__ import annotations

from typing import Dict, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv


class CartPoleJax(JaxEnv):
    """CartPole-v1 dynamics (Barto-Sutton-Anderson, Euler integration).

    State pytree: ``{"x": (4,) f32, "params": (2,) f32}`` — ``params``
    holds the per-episode (pole_length_scale, pole_mass_scale) factors
    (both exactly 1.0 when ``randomize=False``, so the deterministic
    variant pays nothing for the randomization axis).
    """

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    X_THRESHOLD = 2.4
    THETA_THRESHOLD = 12 * 2 * np.pi / 360

    def __init__(self, randomize: bool = False, randomize_scale: float = 0.3, max_episode_steps: int = 500):
        self.randomize = bool(randomize)
        self.randomize_scale = float(randomize_scale)
        self.max_episode_steps = int(max_episode_steps)
        self._conf = (self.randomize, self.randomize_scale, self.max_episode_steps)
        self.observation_space = gym.spaces.Dict(
            {
                "state": gym.spaces.Box(-np.inf, np.inf, shape=(4,), dtype=np.float32),
            }
        )
        self.action_space = gym.spaces.Discrete(2)

    def _obs(self, x: jax.Array) -> Dict[str, jax.Array]:
        return {"state": x}

    def reset(self, key: jax.Array):
        k_state, k_params = jax.random.split(key)
        x = jax.random.uniform(k_state, (4,), jnp.float32, -0.05, 0.05)
        if self.randomize:
            s = self.randomize_scale
            params = jax.random.uniform(k_params, (2,), jnp.float32, 1.0 - s, 1.0 + s)
        else:
            params = jnp.ones((2,), jnp.float32)
        state = {"x": x, "params": params}
        return state, self._obs(x)

    def step(self, state, action, key):
        del key  # deterministic dynamics; randomness enters at reset
        x, x_dot, theta, theta_dot = state["x"]
        length = self.LENGTH * state["params"][0]
        masspole = self.MASSPOLE * state["params"][1]
        total_mass = self.MASSCART + masspole
        polemass_length = masspole * length

        force = jnp.where(action.astype(jnp.int32) == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        new_x = jnp.stack([x, x_dot, theta, theta_dot]).astype(jnp.float32)

        terminated = (
            (jnp.abs(x) > self.X_THRESHOLD) | (jnp.abs(theta) > self.THETA_THRESHOLD)
        )
        reward = jnp.float32(1.0)
        new_state = {"x": new_x, "params": state["params"]}
        return new_state, self._obs(new_x), reward, terminated, {}


class PendulumJax(JaxEnv):
    """Pendulum-v1 dynamics (torque-limited swing-up, never terminates).

    State pytree: ``{"th": (), "thdot": (), "params": (2,)}`` with
    ``params = (g_scale, l_scale)`` per-episode randomization factors.
    Obs is the standard ``(cos th, sin th, thdot)`` triple under
    ``"state"``; episodes end only by truncation (default 200 steps).
    """

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    def __init__(self, randomize: bool = False, randomize_scale: float = 0.3, max_episode_steps: int = 200):
        self.randomize = bool(randomize)
        self.randomize_scale = float(randomize_scale)
        self.max_episode_steps = int(max_episode_steps)
        self._conf = (self.randomize, self.randomize_scale, self.max_episode_steps)
        self.observation_space = gym.spaces.Dict(
            {
                "state": gym.spaces.Box(
                    np.array([-1.0, -1.0, -self.MAX_SPEED], np.float32),
                    np.array([1.0, 1.0, self.MAX_SPEED], np.float32),
                    dtype=np.float32,
                ),
            }
        )
        self.action_space = gym.spaces.Box(-self.MAX_TORQUE, self.MAX_TORQUE, shape=(1,), dtype=np.float32)

    def _obs(self, th: jax.Array, thdot: jax.Array) -> Dict[str, jax.Array]:
        return {"state": jnp.stack([jnp.cos(th), jnp.sin(th), thdot]).astype(jnp.float32)}

    def reset(self, key: jax.Array):
        k_state, k_params = jax.random.split(key)
        high = jnp.array([jnp.pi, 1.0], jnp.float32)
        init = jax.random.uniform(k_state, (2,), jnp.float32, -1.0, 1.0) * high
        if self.randomize:
            s = self.randomize_scale
            params = jax.random.uniform(k_params, (2,), jnp.float32, 1.0 - s, 1.0 + s)
        else:
            params = jnp.ones((2,), jnp.float32)
        state = {"th": init[0], "thdot": init[1], "params": params}
        return state, self._obs(state["th"], state["thdot"])

    def step(self, state, action, key):
        del key  # deterministic dynamics; randomness enters at reset
        th, thdot = state["th"], state["thdot"]
        g = self.G * state["params"][0]
        length = self.L * state["params"][1]
        u = jnp.clip(action.reshape(()), -self.MAX_TORQUE, self.MAX_TORQUE)

        norm_th = jnp.mod(th + jnp.pi, 2 * jnp.pi) - jnp.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2

        newthdot = thdot + (3.0 * g / (2.0 * length) * jnp.sin(th) + 3.0 / (self.M * length**2) * u) * self.DT
        newthdot = jnp.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        newth = th + newthdot * self.DT

        new_state = {"th": newth, "thdot": newthdot, "params": state["params"]}
        reward = (-cost).astype(jnp.float32)
        terminated = jnp.zeros((), bool)
        return new_state, self._obs(newth, newthdot), reward, terminated, {}
