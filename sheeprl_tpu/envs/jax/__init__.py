"""sheeprl_tpu.envs.jax — device-resident environments (ROADMAP item 2).

Three tiers, fastest last:

1. :func:`make_gym_env` / :class:`JaxToGymEnv` — the jax env families as
   ordinary host gym envs (``env_backend=host``): wrapper chain, video,
   Sync/Async vector envs all unchanged;
2. :class:`JaxVectorEnv` — all N envs stepped by ONE jitted program per
   ``step`` call behind the gymnasium vector API (``final_obs`` /
   ``final_info`` SAME_STEP semantics preserved);
3. the fused collect path (:mod:`sheeprl_tpu.envs.jax.collect`,
   ``algo.env_backend=jax``) — policy-step + env-step + buffer-append as
   one ``lax.scan`` per rollout, zero host round trips.

``howto/jax-envs.md`` documents the protocol, the auto-reset semantics
and when host envs are still required.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from sheeprl_tpu.envs.jax.classic import CartPoleJax, PendulumJax
from sheeprl_tpu.envs.jax.core import (
    JaxEnv,
    initial_reset_key,
    step_keys,
    tree_select,
    vector_reset,
    vector_step,
)
from sheeprl_tpu.envs.jax.gridworld import GridWorldJax
from sheeprl_tpu.envs.jax.gym_adapter import JaxToGymEnv, make_gym_env
from sheeprl_tpu.envs.jax.vector import JaxVectorEnv

__all__ = [
    "JAX_ENV_REGISTRY",
    "CartPoleJax",
    "GridWorldJax",
    "JaxEnv",
    "JaxToGymEnv",
    "JaxVectorEnv",
    "PendulumJax",
    "initial_reset_key",
    "is_jax_env_id",
    "make_gym_env",
    "make_jax_env",
    "step_keys",
    "tree_select",
    "vector_reset",
    "vector_step",
]

#: id -> constructor; ids are the ``env.id`` values of the
#: ``configs/env/jax_*.yaml`` group entries
JAX_ENV_REGISTRY: Dict[str, Callable[..., JaxEnv]] = {
    "jax_cartpole": CartPoleJax,
    "jax_pendulum": PendulumJax,
    "jax_gridworld": GridWorldJax,
}


def is_jax_env_id(env_id: Any) -> bool:
    return str(env_id) in JAX_ENV_REGISTRY


def make_jax_env(id: str, **kwargs: Any) -> JaxEnv:
    """Resolve a registered jax env id to a constructed :class:`JaxEnv`.

    ``kwargs`` pass through to the family constructor (``randomize``,
    ``size``, ``max_episode_steps``, ...), so env configs parameterize
    the families the same way host wrappers take factory kwargs.
    """
    if id not in JAX_ENV_REGISTRY:
        raise ValueError(
            f"Unknown jax env id {id!r}; registered: {', '.join(sorted(JAX_ENV_REGISTRY))}"
        )
    return JAX_ENV_REGISTRY[id](**kwargs)
