"""DeepMind Control Suite adapter (gated on ``dm_control``).

Behavioral counterpart of reference sheeprl/envs/dmc.py (DMCWrapper:49),
itself derived from the public dmc2gym wrapper: dm_env specs become
gymnasium Boxes, actions are normalized to [-1, 1], and the observation is
a dict with optional ``rgb`` (rendered pixels) and ``state`` (flattened
proprioception) keys.

TPU-native divergence: images default to channels-LAST (NHWC) because the
whole sheeprl_tpu preprocessing/encoder pipeline is NHWC (XLA's preferred
conv layout), where the reference defaults to channels-first for torch.
"""

from __future__ import annotations

import os

# MuJoCo's GL backend must be chosen before dm_control loads its rendering
# stack.  Unset, it tries GLFW, which aborts (SIGABRT) on headless hosts
# with no display; EGL drives a GPU-less software context fine.  Only a
# default — export MUJOCO_GL to override.
os.environ.setdefault("MUJOCO_GL", "egl")

from sheeprl_tpu.utils.imports import _IS_DMC_AVAILABLE

if not _IS_DMC_AVAILABLE:
    raise ModuleNotFoundError(
        "dm_control is not installed; DMC environments are unavailable. "
        "Install dm_control to use them."
    )

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np
from dm_control import suite
from dm_env import specs
from gymnasium import spaces


def _spec_to_box(spec, dtype) -> spaces.Box:
    """Concatenate a collection of dm_env specs into one flat Box."""
    mins, maxs = [], []
    for s in spec:
        dim = int(np.prod(s.shape))
        if type(s) is specs.Array:
            bound = np.inf * np.ones(dim, dtype=np.float32)
            mins.append(-bound)
            maxs.append(bound)
        elif type(s) is specs.BoundedArray:
            zeros = np.zeros(dim, dtype=np.float32)
            mins.append(s.minimum + zeros)
            maxs.append(s.maximum + zeros)
        else:
            raise ValueError(f"Unrecognized spec: {type(s)}")
    low = np.concatenate(mins, axis=0).astype(dtype)
    high = np.concatenate(maxs, axis=0).astype(dtype)
    return spaces.Box(low, high, dtype=dtype)


def _flatten_obs(obs: Dict[Any, Any]) -> np.ndarray:
    pieces = [np.array([v]) if np.isscalar(v) else np.asarray(v).ravel() for v in obs.values()]
    return np.concatenate(pieces, axis=0)


class DMCWrapper(gym.Env):
    """dm_control suite task as a gymnasium env with dict observations.

    A ``gym.Env`` (not ``gym.Wrapper``) because the wrapped object is a
    dm_env ``Environment``, which newer gymnasium Wrappers reject."""

    def __init__(
        self,
        domain_name: str,
        task_name: str,
        from_pixels: bool = False,
        from_vectors: bool = True,
        height: int = 84,
        width: int = 84,
        camera_id: int = 0,
        task_kwargs: Optional[Dict[Any, Any]] = None,
        environment_kwargs: Optional[Dict[Any, Any]] = None,
        channels_first: bool = False,
        visualize_reward: bool = False,
        seed: Optional[int] = None,
        fast_render: bool = False,
    ):
        if not (from_vectors or from_pixels):
            raise ValueError(
                "'from_vectors' and 'from_pixels' must not be both False: "
                f"got {from_vectors} and {from_pixels} respectively."
            )
        self._from_pixels = from_pixels
        self._from_vectors = from_vectors
        self._height = height
        self._width = width
        self._camera_id = camera_id
        self._channels_first = channels_first

        # the wrapper owns task seeding through reset()
        task_kwargs = dict(task_kwargs or {})
        task_kwargs.pop("random", None)
        env = suite.load(
            domain_name=domain_name,
            task_name=task_name,
            task_kwargs=task_kwargs,
            visualize_reward=visualize_reward,
            environment_kwargs=environment_kwargs,
        )
        self.env = env
        if from_pixels and fast_render:
            # Headless hosts render through software GL, where the shadow /
            # reflection / MSAA passes dominate (measured 52 -> 26 ms per
            # 64x64 frame on one CPU core). Scene content is unchanged —
            # only lighting decoration — so policies keep learning.
            # Default False (pixel-exact MuJoCo defaults): checkpoints
            # whose saved config predates this knob must keep their frame
            # distribution on resume; configs/env/dmc.yaml opts new runs in.
            m = env.physics.model
            m.vis.quality.shadowsize = 0
            m.vis.quality.offsamples = 0
            m.mat_reflectance[:] = 0.0

        self._true_action_space = _spec_to_box([env.action_spec()], np.float32)
        self._norm_action_space = spaces.Box(
            low=-1.0, high=1.0, shape=self._true_action_space.shape, dtype=np.float32
        )
        reward_space = _spec_to_box([env.reward_spec()], np.float32)
        self._reward_range = (reward_space.low.item(), reward_space.high.item())

        obs_space = {}
        if from_pixels:
            shape = (3, height, width) if channels_first else (height, width, 3)
            obs_space["rgb"] = spaces.Box(low=0, high=255, shape=shape, dtype=np.uint8)
        if from_vectors:
            obs_space["state"] = _spec_to_box(env.observation_spec().values(), np.float64)
        self._observation_space = spaces.Dict(obs_space)
        self._state_space = _spec_to_box(env.observation_spec().values(), np.float64)
        self.current_state = None
        self._render_mode = "rgb_array"
        self._metadata = {}
        self.seed(seed=seed)

    def __getattr__(self, name):
        if name.startswith("_") or name == "env":
            raise AttributeError(name)
        return getattr(self.env, name)

    @property
    def observation_space(self) -> spaces.Dict:
        return self._observation_space

    @observation_space.setter
    def observation_space(self, space) -> None:
        self._observation_space = space

    @property
    def state_space(self) -> spaces.Box:
        return self._state_space

    @property
    def action_space(self) -> spaces.Box:
        return self._norm_action_space

    @action_space.setter
    def action_space(self, space) -> None:
        self._norm_action_space = space

    @property
    def reward_range(self) -> Tuple[float, float]:
        return self._reward_range

    @property
    def render_mode(self) -> str:
        return self._render_mode

    def seed(self, seed: Optional[int] = None) -> None:
        self._true_action_space.seed(seed)
        self._norm_action_space.seed(seed)
        self._observation_space.seed(seed)

    def _get_obs(self, time_step) -> Dict[str, np.ndarray]:
        obs = {}
        if self._from_pixels:
            rgb = self.render(camera_id=self._camera_id)
            if self._channels_first:
                rgb = rgb.transpose(2, 0, 1).copy()
            obs["rgb"] = rgb
        if self._from_vectors:
            obs["state"] = _flatten_obs(time_step.observation)
        return obs

    def _convert_action(self, action) -> np.ndarray:
        """[-1, 1] -> the task's true action bounds."""
        action = np.asarray(action, dtype=np.float64)
        true_delta = self._true_action_space.high - self._true_action_space.low
        norm_delta = self._norm_action_space.high - self._norm_action_space.low
        action = (action - self._norm_action_space.low) / norm_delta
        return (action * true_delta + self._true_action_space.low).astype(np.float32)

    def step(self, action):
        time_step = self.env.step(self._convert_action(action))
        obs = self._get_obs(time_step)
        self.current_state = _flatten_obs(time_step.observation)
        info = {
            "discount": time_step.discount,
            "internal_state": self.env.physics.get_state().copy(),
        }
        # dm_env signals episode end via discount: 1.0 at the horizon
        # (time limit), 0.0 on true termination
        truncated = time_step.last() and time_step.discount == 1
        terminated = False if time_step.first() else time_step.last() and time_step.discount == 0
        return obs, time_step.reward or 0.0, terminated, truncated, info

    def reset(self, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        # gymnasium semantics: seed=None keeps the existing (seeded) stream
        if isinstance(seed, np.random.RandomState):
            self.env.task._random = seed
        elif seed is not None:
            self.env.task._random = np.random.RandomState(seed)
        time_step = self.env.reset()
        self.current_state = _flatten_obs(time_step.observation)
        return self._get_obs(time_step), {}

    def render(self, camera_id: Optional[int] = None) -> np.ndarray:
        return self.env.physics.render(
            height=self._height, width=self._width, camera_id=camera_id or self._camera_id
        )
