"""sheeprl_tpu — TPU-native (jax/XLA/pjit/pallas) distributed deep-RL
framework with the capabilities of SheepRL.

Importing the package registers every algorithm via decorator side-effect
(reference sheeprl/__init__.py:18-51)."""

import os

# Quiet TPU init logs in CLI usage
os.environ.setdefault("TPU_STDERR_LOG_LEVEL", "3")

from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry  # noqa: E402

from sheeprl_tpu.algos import (  # noqa: E402, F401
    a2c,
    dreamer_v1,
    dreamer_v2,
    dreamer_v3,
    droq,
    p2e_dv1,
    p2e_dv2,
    p2e_dv3,
    ppo,
    ppo_recurrent,
    sac,
    sac_ae,
)

__version__ = "0.1.0"
