"""CLI: run / evaluation / registration entrypoints.

Counterpart of reference sheeprl/cli.py (run:358, run_algorithm:60,
eval_algorithm:202, check_configs:271, resume_from_checkpoint:23,
evaluation:369, registration:408), driven by the in-house hydra-style
composer (no hydra dependency). Overrides are passed exactly like the
reference: ``python sheeprl.py exp=ppo env.num_envs=8 fabric.devices=4``.

There is no ``fabric.launch`` process boundary: under single-controller
SPMD one process per host drives all local devices through the mesh.
"""

from __future__ import annotations

import importlib
import os
import sys
import warnings
from typing import Any, Dict, List, Optional, Sequence

from sheeprl_tpu.config import compose, dotdict
from sheeprl_tpu.config.compose import deep_merge, yaml_load
from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry, find_algorithm, find_evaluation


def _app_config(name: str) -> dict:
    """Defaults of an app-level entry config (eval_config.yaml /
    model_manager_config.yaml, reference sheeprl/configs/*.yaml) — the
    reference mounts these via @hydra.main; here they are plain yaml files
    in the package config dir."""
    path = os.path.join(os.path.dirname(__file__), "configs", f"{name}.yaml")
    try:
        with open(path) as f:
            return yaml_load(f.read()) or {}
    except OSError:
        return {}


def _resolve_interp(value, ctx: dict):
    """Resolve the tiny interpolation set the app-level entry configs use
    (``${now:FMT}``, ``${oc.env:VAR}``, ``${key}`` from ``ctx``) — the
    stand-in for the omegaconf resolvers the reference's @hydra.main
    mounting provides.  Unresolvable values (missing env var / ``???``)
    become None so callers fall back to their defaults."""
    if not isinstance(value, str) or value == "???":
        return None if value == "???" else value

    import re
    from datetime import datetime

    unresolved = False

    def sub(m) -> str:
        nonlocal unresolved
        expr = m.group(1)
        if expr.startswith("now:"):
            return datetime.now().strftime(expr[4:])
        if expr.startswith("oc.env:"):
            env = os.getenv(expr[7:])
            if env is None:
                unresolved = True
                return ""
            return env
        if expr in ctx and ctx[expr] is not None:
            return str(ctx[expr])
        unresolved = True
        return ""

    out = re.sub(r"\$\{([^}]+)\}", sub, value)
    return None if unresolved else out


def resume_from_checkpoint(cfg: dotdict) -> dotdict:
    """Merge the checkpoint's config with the current one, keeping the new
    total_steps / learning_starts-style knobs (reference cli.py:23-57)."""
    import yaml

    ckpt_path = cfg.checkpoint.resume_from
    ckpt_dir = os.path.dirname(os.path.dirname(ckpt_path))
    old_cfg_path = os.path.join(ckpt_dir, "config.yaml")
    if not os.path.exists(old_cfg_path):
        old_cfg_path = os.path.join(os.path.dirname(ckpt_path), "config.yaml")
    if not os.path.exists(old_cfg_path):
        raise RuntimeError(f"Cannot find the config file of the checkpoint: {old_cfg_path}")
    with open(old_cfg_path) as f:
        old_cfg = yaml_load(f.read())
    if old_cfg["env"]["id"] != cfg.env.id:
        raise RuntimeError(
            f"This experiment is run with a different environment from the checkpoint: "
            f"{old_cfg['env']['id']} vs {cfg.env.id}"
        )
    if old_cfg["algo"]["name"] != cfg.algo.name:
        raise RuntimeError(
            f"This experiment is run with a different algorithm from the checkpoint: "
            f"{old_cfg['algo']['name']} vs {cfg.algo.name}"
        )
    kept = {
        "total_steps": cfg.algo.total_steps,
        "resume_from": ckpt_path,
        "run_name": cfg.run_name,
        "exp_name": cfg.exp_name,
        "seed": cfg.seed,
    }
    learning_starts = cfg.algo.get("learning_starts")
    merged = dict(old_cfg)
    # checkpoint cadence and metric knobs are OPERATIONAL, not training
    # semantics: they follow the resuming invocation, so a resume chain can
    # e.g. checkpoint more often or fetch metrics less often (amortizing
    # the per-dispatch device sync on high-latency links) than the original
    # run did (deviation from the reference, whose resume pins the old
    # cadence — cli.py:49-57)
    deep_merge(
        merged,
        {
            "checkpoint": {
                "resume_from": ckpt_path,
                "every": cfg.checkpoint.every,
                "keep_last": cfg.checkpoint.keep_last,
                "save_last": cfg.checkpoint.save_last,
                "async_save": cfg.checkpoint.get("async_save", True),
                "sharded": cfg.checkpoint.get("sharded", False),
                "device_digests": cfg.checkpoint.get("device_digests", False),
            },
            # the mesh is a RESTART-TIME choice: sharded checkpoints restore
            # with resharding (resilience/sharded_ckpt.py), so the resuming
            # invocation's fabric section (devices/strategy/mesh_shape) wins
            # over the saved one — a 4x2 run resumes onto 2x4, 8x1 or a
            # single device without the old mesh pinning it
            "fabric": {k: v for k, v in cfg.fabric.items()},
            "metric": {
                "log_every": cfg.metric.log_every,
                "log_level": cfg.metric.log_level,
                "fetch_every": cfg.metric.get("fetch_every", 1),
                "disable_timer": cfg.metric.get("disable_timer", False),
            },
        },
    )
    merged["algo"]["total_steps"] = kept["total_steps"]
    if learning_starts is not None:
        merged["algo"]["learning_starts"] = learning_starts
    merged["run_name"] = kept["run_name"]
    merged["exp_name"] = kept["exp_name"]
    merged["seed"] = kept["seed"]
    return dotdict(merged)


def check_configs(cfg: dotdict) -> None:
    """Config validation (reference cli.py:271-345): strategy whitelist and
    per-algo constraints."""
    from sheeprl_tpu.parallel.mesh import _STRATEGIES

    strategy = str(cfg.fabric.get("strategy", "auto"))
    if strategy not in _STRATEGIES:
        raise ValueError(
            f"Unknown fabric strategy '{strategy}'. The TPU runtime supports: "
            + ", ".join(_STRATEGIES)
        )
    decoupled = False
    try:
        _, _, decoupled = find_algorithm(cfg.algo.name)
    except RuntimeError:
        pass
    if decoupled:
        # reference cli.py:289-332: decoupled algos only run under DDP; here
        # the learner runs on the mesh, so only dp-style layouts qualify
        if strategy == "fsdp":
            raise ValueError(
                f"The '{strategy}' strategy is currently not supported for decoupled "
                "algorithms. Please launch the script with a data-parallel strategy "
                "('python sheeprl.py fabric.strategy=ddp')"
            )
        if cfg.fabric.get("accelerator") == "cpu" and int(cfg.env.num_envs) < 1:
            raise ValueError("Decoupled algorithms need at least one environment")


def _build_runtime(cfg: dotdict):
    from sheeprl_tpu.config import instantiate

    fabric_cfg = dict(cfg.fabric)
    if fabric_cfg.get("accelerator") == "cpu":
        # force the host platform even when the machine env pins
        # JAX_PLATFORMS to an accelerator (works while no backend is
        # initialized yet, same trick as tests/conftest.py)
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    runtime = instantiate(fabric_cfg)
    runtime.launch()
    return runtime


def run_algorithm(cfg: dotdict) -> None:
    """Registry lookup + algorithm dispatch (reference cli.py:60-199)."""
    module, entrypoint, decoupled = find_algorithm(cfg.algo.name)
    algo_module = importlib.import_module(f"{module}.{cfg.algo.name}")
    utils_module = importlib.import_module(f"{module}.utils")

    # filter metric aggregator by the algo's known keys (reference cli.py:151-165)
    keys = getattr(utils_module, "AGGREGATOR_KEYS", set())
    if "aggregator" in cfg.metric and "metrics" in cfg.metric.aggregator:
        cfg.metric.aggregator.metrics = dotdict(
            {k: v for k, v in cfg.metric.aggregator.metrics.items() if k in keys}
        )

    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    # set both ways: these are class-level flags, and a previous in-process
    # run (tests, notebooks) may have disabled them
    MetricAggregator.disabled = cfg.metric.log_level == 0
    timer.disabled = cfg.metric.log_level == 0 or bool(cfg.metric.get("disable_timer", False))

    runtime = _build_runtime(cfg)
    entry_fn = getattr(algo_module, entrypoint)

    if cfg.metric.get("profile", False) and runtime.is_global_zero:
        # jax.profiler trace of the whole run (rank 0): the TPU analogue of
        # the reference's missing torch-profiler hook (SURVEY §5.1). Meant
        # for short profiling runs — traces grow with wall-clock. View with
        # tensorboard --logdir <root_dir>/profile.
        import jax

        trace_dir = os.path.join(
            str(cfg.get("root_dir", ".")), str(cfg.get("run_name", "run")), "profile"
        )
        os.makedirs(trace_dir, exist_ok=True)
        with jax.profiler.trace(trace_dir):
            entry_fn(runtime, cfg)
    else:
        entry_fn(runtime, cfg)


def install_stack_dumper(suffix: str = "") -> None:
    """Observability for long headless runs: dump every thread's stack to
    ``SHEEPRL_STACK_DUMP_FILE``(+suffix) every ``SHEEPRL_STACK_DUMP_S``
    seconds, so a slow/stuck loop shows WHERE it sits without gdb/py-spy.
    Decoupled player subprocesses call this too (with a suffix), since the
    parent's dumper cannot see their threads."""
    try:
        stack_dump_s = float(os.environ.get("SHEEPRL_STACK_DUMP_S", 0))
    except ValueError:
        stack_dump_s = 0.0
    if stack_dump_s <= 0:
        return
    # idempotent per-process: repeated run() calls in one interpreter (the
    # bench harness) must neither truncate earlier legs' stack history nor
    # leak the previously registered dump file
    if getattr(install_stack_dumper, "_installed", None) == suffix:
        return
    import faulthandler

    path = os.environ.get("SHEEPRL_STACK_DUMP_FILE", "/tmp/sheeprl_stacks.log") + suffix
    try:
        dump_file = open(path, "a", buffering=1)
    except OSError as e:  # diagnostics must never kill the run
        warnings.warn(f"stack dump disabled, cannot open {path}: {e}")
    else:
        install_stack_dumper._installed = suffix
        faulthandler.dump_traceback_later(
            stack_dump_s, repeat=True, file=dump_file, exit=False
        )


def run(args: Optional[Sequence[str]] = None) -> None:
    """Main training app: ``sheeprl exp=... [overrides...]``.

    ``--profile`` is a convenience flag equivalent to ``metric.profile=True``
    (whole-run jax.profiler trace on rank 0); windowed capture on long runs
    goes through ``metric.profile_every_n`` instead (howto/observability.md).
    """
    install_stack_dumper()
    overrides = list(args if args is not None else sys.argv[1:])
    if "--profile" in overrides:
        overrides = [o for o in overrides if o != "--profile"] + ["metric.profile=True"]
    cfg = compose(config_name="config", overrides=overrides)
    if cfg.get("num_threads"):
        os.environ.setdefault("XLA_FLAGS", "")
    from sheeprl_tpu.utils.utils import print_config

    # fault-injection harness (howto/resilience.md): cfg.faults rides the
    # env var so spawned decoupled children inherit the armed sites
    if cfg.get("faults"):
        os.environ["SHEEPRL_FAULTS"] = str(cfg.faults)
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.resilience import resolve_auto_resume

        resolve_auto_resume(cfg)
    if cfg.checkpoint.resume_from:
        cfg = resume_from_checkpoint(cfg)
    check_configs(cfg)
    print_config(cfg)
    run_algorithm(cfg)


def eval_algorithm(cfg: dotdict) -> None:
    """Load checkpoint + dispatch registered evaluation (reference cli.py:202)."""
    from sheeprl_tpu.utils.callback import load_checkpoint

    state = load_checkpoint(cfg.checkpoint_path)
    module, entrypoint = find_evaluation(cfg.algo.name)
    eval_module = importlib.import_module(f"{module}.evaluate")
    eval_fn = getattr(eval_module, entrypoint)
    runtime = _build_runtime(cfg)
    eval_fn(runtime, cfg, state)


def evaluation(args: Optional[Sequence[str]] = None) -> None:
    """Evaluation app: ``sheeprl-eval checkpoint_path=... [overrides...]``.

    Loads the run config saved next to the checkpoint, then overrides
    env/fabric for single-device evaluation (reference cli.py:369-405).
    """
    overrides = list(args if args is not None else sys.argv[1:])
    kv = dict(o.split("=", 1) for o in overrides if "=" in o)
    ckpt_path = kv.get("checkpoint_path")
    if not ckpt_path:
        raise ValueError("You must specify `checkpoint_path=...`")
    ckpt_dir = os.path.dirname(os.path.dirname(os.path.abspath(ckpt_path)))
    cfg_path = os.path.join(ckpt_dir, "config.yaml")
    if not os.path.exists(cfg_path):
        raise RuntimeError(f"Cannot find the config file of the checkpoint: {cfg_path}")
    with open(cfg_path) as f:
        run_cfg = dotdict(yaml_load(f.read()))
    app_defaults = _app_config("eval_config")
    capture_video = yaml_load(
        kv.get("env.capture_video", str(app_defaults.get("env", {}).get("capture_video", True)))
    )
    default_seed = app_defaults.get("seed")
    seed = int(kv.get("seed", run_cfg.get("seed", 42 if default_seed is None else default_seed)))
    run_cfg["env"]["capture_video"] = bool(capture_video)
    run_cfg["env"]["num_envs"] = 1
    run_cfg["fabric"] = dotdict(
        {
            "_target_": "sheeprl_tpu.parallel.MeshRuntime",
            "devices": 1,
            "num_nodes": 1,
            "strategy": "auto",
            "accelerator": kv.get(
                "fabric.accelerator",
                app_defaults.get("fabric", {}).get("accelerator")
                or run_cfg["fabric"].get("accelerator", "auto"),
            ),
            "precision": run_cfg["fabric"].get("precision", "32-true"),
        }
    )
    run_cfg["seed"] = seed
    run_cfg["checkpoint_path"] = os.path.abspath(ckpt_path)
    run_cfg["run_name"] = os.path.join(str(run_cfg.get("run_name", "run")), "evaluation")
    cfg = dotdict(run_cfg)
    eval_algorithm(cfg)


def registration(args: Optional[Sequence[str]] = None) -> None:
    """Model-manager registration app:
    ``sheeprl-registration checkpoint_path=... [model_manager overrides...]``
    (reference cli.py:408-448). Requires the optional mlflow backend.

    Loads the run config saved next to the checkpoint, merges any
    ``model_manager.*`` overrides, then logs + registers the configured
    MODELS_TO_REGISTER param trees from the checkpoint state."""
    from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

    if not _IS_MLFLOW_AVAILABLE:
        raise ModuleNotFoundError(
            "mlflow is not installed in this environment; the model-manager registration app "
            "requires it (`pip install mlflow`)"
        )
    overrides = list(args if args is not None else sys.argv[1:])
    kv = dict(o.split("=", 1) for o in overrides if "=" in o)
    ckpt_path = kv.pop("checkpoint_path", None)
    if not ckpt_path:
        raise ValueError("You must specify `checkpoint_path=...`")
    ckpt_dir = os.path.dirname(os.path.dirname(os.path.abspath(ckpt_path)))
    cfg_path = os.path.join(ckpt_dir, "config.yaml")
    if not os.path.exists(cfg_path):
        raise RuntimeError(f"Cannot find the config file of the checkpoint: {cfg_path}")
    with open(cfg_path) as f:
        run_cfg = dotdict(yaml_load(f.read()))
    # apply model_manager / tracking overrides on the saved config
    for key, value in kv.items():
        node = run_cfg
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, dotdict({}))
        node[parts[-1]] = yaml_load(value)
    run_cfg["fabric"] = dotdict(
        {
            "_target_": "sheeprl_tpu.parallel.MeshRuntime",
            "devices": 1,
            "num_nodes": 1,
            "strategy": "auto",
            "accelerator": "cpu",
            "precision": run_cfg["fabric"].get("precision", "32-true"),
        }
    )
    cfg = dotdict(run_cfg)

    from sheeprl_tpu.utils.callback import load_checkpoint
    from sheeprl_tpu.utils.mlflow import register_model_from_checkpoint

    # run/experiment naming + tracking uri defaults from the registration
    # app's entry config (reference sheeprl/configs/model_manager_config.yaml);
    # explicit run.name= / experiment.name= / tracking_uri= overrides win
    app_defaults = _app_config("model_manager_config")
    ctx = {"exp_name": run_cfg.get("exp_name")}
    run_name = run_cfg.get("run", {}).get("name") or _resolve_interp(
        (app_defaults.get("run") or {}).get("name"), ctx
    )
    experiment_name = run_cfg.get("experiment", {}).get("name") or _resolve_interp(
        (app_defaults.get("experiment") or {}).get("name"), ctx
    )
    tracking_uri = run_cfg.get("tracking_uri") or _resolve_interp(
        app_defaults.get("tracking_uri"), ctx
    )

    state = load_checkpoint(os.path.abspath(ckpt_path))
    runtime = _build_runtime(cfg)
    register_model_from_checkpoint(
        runtime,
        cfg,
        state,
        run_name=run_name,
        experiment_name=experiment_name,
        tracking_uri=tracking_uri,
    )
