"""``sheeprl_tpu-agents``: table of every registered algorithm
(reference sheeprl/available_agents.py:7-34)."""

from __future__ import annotations

import sheeprl_tpu  # noqa: F401  (populate registries via import side-effect)
from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry


def available_agents() -> None:
    from rich.console import Console
    from rich.table import Table

    table = Table(title="SheepRL-TPU Agents")
    table.add_column("Module")
    table.add_column("Algorithm")
    table.add_column("Entrypoint")
    table.add_column("Decoupled")
    table.add_column("Evaluated by")

    for module, registrations in algorithm_registry.items():
        for algo in registrations:
            evaluated_by = "Undefined"
            for eval_module, eval_regs in evaluation_registry.items():
                for ev in eval_regs:
                    if algo["name"] in ev["name"]:
                        evaluated_by = f"{eval_module}.{ev['entrypoint']}"
                        break
            table.add_row(
                module, algo["name"], algo["entrypoint"], str(algo["decoupled"]), evaluated_by
            )
    Console().print(table)


if __name__ == "__main__":
    available_agents()
