"""Runtime sanitizers — the dynamic half of the jaxlint tooling.

Everything here is **opt-in** via ``SHEEPRL_SANITIZE=1`` (off = zero
overhead: the hooks return the undecorated objects / null contexts, and
the leak registry is a couple of dict ops per long-lived resource).  Four
pieces:

- **Donation sanitizer** (:func:`guard_donation`, wired inside
  ``MeshRuntime.setup_step``): on CPU/GPU backends XLA often cannot honor
  ``donate_argnums``, so a use-after-donate reads *recycled* memory at a
  timing-dependent step instead of failing — the PR-3 class.  The
  sanitizer waits for the dispatch, then deletes every donated device
  leaf (and NaN-poisons donated host numpy leaves), so ANY later touch
  raises ``Array has been deleted`` deterministically, on every backend.
- **Host-alias guard** (:func:`check_host_sources`, wired inside
  ``MeshRuntime.shard_batch``/``replicate``): refuses device uploads
  whose numpy source is memory numpy does not own — ``np.memmap``
  windows, ``np.frombuffer`` over a bytearray/mmap/shm slot, mmap-mode
  npz members.  CPU ``device_put`` zero-copy aliases these WITHOUT
  keeping the owner alive (the PR-7 freed-npz heap corruption).
- **Transfer guard** (:func:`transfer_sanitizer`, composed into
  ``obs.trace_scope``): scoped ``jax.transfer_guard("disallow")`` around
  hot-loop phases, with an explicit allowlist for the phases whose whole
  point is a transfer (``block_until_ready`` metric fetches, IPC waits).
  Implicit host syncs inside guarded scopes then fail loudly instead of
  silently stalling the step.
- **Leak registry** (:data:`leak_registry`, fed by
  ``parallel/transport.py``, ``parallel/shm_ring.py``,
  ``parallel/pipeline.py`` and ``data/feed.py``): tracks live channels,
  shm segments and worker threads; :func:`session_leak_report` backs the
  suite-wide pytest sweep (tests/conftest.py) that fails the session on
  orphaned ``/dev/shm`` segments or still-alive worker threads — the
  PR-6 leaked-feeder-thread hang, caught at test time.
"""

from __future__ import annotations

import glob
import os
import threading
import weakref
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "DonationSanitizerError",
    "HostAliasError",
    "LeakRegistry",
    "allowed_transfer_scopes",
    "check_host_sources",
    "guard_donation",
    "leak_registry",
    "sanitize_enabled",
    "session_leak_report",
    "shm_orphans",
    "sweep_leaks",
    "transfer_sanitizer",
]

_TRUTHY = ("1", "true", "yes", "on")


class DonationSanitizerError(RuntimeError):
    """A donated buffer was touched after its donating dispatch."""


class HostAliasError(RuntimeError):
    """A device upload zero-copy aliases host memory numpy does not own."""


def sanitize_enabled() -> bool:
    """``SHEEPRL_SANITIZE`` env gate, read per call (cheap: one dict
    lookup) so tests and subprocess children can toggle it."""
    return os.environ.get("SHEEPRL_SANITIZE", "").strip().lower() in _TRUTHY


# ===================================================================== #
# donation sanitizer
# ===================================================================== #
def _leaf_pointer(leaf: Any) -> Optional[int]:
    """Host/device buffer address when obtainable (CPU single-device
    arrays and numpy); None otherwise."""
    try:
        import numpy as np

        if isinstance(leaf, np.ndarray):
            return leaf.ctypes.data if leaf.size else None
        fn = getattr(leaf, "unsafe_buffer_pointer", None)
        if fn is not None:
            return int(fn())
    except Exception:
        pass
    return None


def guard_donation(fn, donate_argnums: Tuple[int, ...], where: str = "jitted step"):
    """Wrap a jitted dispatch so donated inputs die DETERMINISTICALLY.

    After the wrapped call, the outputs are materialized
    (``block_until_ready`` — sanitize mode trades the async-dispatch
    overlap for determinism), then every ``jax.Array`` leaf of each
    donated argument is ``.delete()``-d and every float numpy leaf is
    NaN-poisoned.  Leaves whose buffer is shared with an output
    (passthrough / already-honored donation) are left alone — the
    sanitizer must never corrupt a correct program.  A later touch of a
    deleted leaf raises jax's "Array has been deleted" RuntimeError at
    the EXACT offending line, instead of a heisenbug three PRs later.
    """
    donate_argnums = tuple(donate_argnums)
    if not donate_argnums:
        return fn

    def sanitized(*args, **kwargs):
        import jax
        import numpy as np

        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        out_leaves = jax.tree_util.tree_leaves(out)
        out_ids = {id(l) for l in out_leaves}
        out_ptrs = {p for p in (_leaf_pointer(l) for l in out_leaves) if p is not None}
        for i in donate_argnums:
            if i >= len(args):
                continue
            for leaf in jax.tree_util.tree_leaves(args[i]):
                if id(leaf) in out_ids:
                    continue
                ptr = _leaf_pointer(leaf)
                if ptr is not None and ptr in out_ptrs:
                    continue  # buffer shared with an output: not ours to kill
                if isinstance(leaf, np.ndarray):
                    # poison donated HOST references: CPU device_put may
                    # have zero-copy aliased this buffer; a reuse now
                    # reads NaN instead of plausible stale numbers
                    if ptr is not None and leaf.flags.writeable and leaf.dtype.kind == "f":
                        leaf.fill(np.nan)
                    continue
                delete = getattr(leaf, "delete", None)
                deleted = getattr(leaf, "is_deleted", None)
                if delete is not None and (deleted is None or not deleted()):
                    try:
                        delete()
                    except Exception:
                        pass  # sharded/committed-elsewhere leaves: skip
        return out

    sanitized._donation_sanitizer = where  # introspectable in tests
    sanitized._jitted = getattr(fn, "_jitted", None)
    return sanitized


# ===================================================================== #
# host-alias guard
# ===================================================================== #
def _borrowed_base(arr: Any) -> Optional[str]:
    """Why ``arr``'s memory is NOT owned by the numpy view chain, or None.

    A plain ndarray view keeps its base ndarray alive via refcount — safe.
    The hazardous class is buffers whose lifetime numpy does not manage:
    file-backed memmaps, ``frombuffer`` over mmap/bytearray/memoryview
    (shm slots come in through exactly that path), npz zip members opened
    with ``mmap_mode``.
    """
    import mmap

    import numpy as np

    if isinstance(arr, np.memmap):
        return "np.memmap window"
    base = arr
    while isinstance(base, np.ndarray):
        if isinstance(base, np.memmap):
            return "np.memmap window"
        if base.base is None:
            return None  # owns its data
        base = base.base
    if isinstance(base, mmap.mmap):
        return "mmap-backed buffer (np.load(mmap_mode=...) member or shm slot)"
    if isinstance(base, (bytearray, memoryview)):
        return f"{type(base).__name__}-backed np.frombuffer view"
    if base is not None:
        return f"{type(base).__name__}-backed buffer"
    return None


def check_host_sources(tree: Any, where: str = "device upload") -> None:
    """Raise :class:`HostAliasError` when any numpy leaf of ``tree`` is a
    view over borrowed (non-numpy-owned) memory.  No-op unless
    ``SHEEPRL_SANITIZE`` is on.  Wired into ``MeshRuntime.shard_batch``
    and ``MeshRuntime.replicate`` — the two upload funnels of the algo
    loops."""
    if not sanitize_enabled():
        return
    import jax
    import numpy as np

    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not isinstance(leaf, np.ndarray):
            continue
        why = _borrowed_base(leaf)
        if why:
            pretty = jax.tree_util.keystr(path) or "<root>"
            raise HostAliasError(
                f"{where}: leaf {pretty} is a {why}. CPU device_put would zero-copy alias "
                f"memory whose owner can be freed/recycled under the device array (the "
                f"freed-npz/shm heap-corruption class). Materialize a copy first "
                f"(np.copy / jnp.array(..., copy=True)) or keep the owner alive on host_refs."
            )


# ===================================================================== #
# transfer guard
# ===================================================================== #
# phases whose very purpose is a device<->host transfer (implicit fetches
# included): guard must not fire there
_ALLOW_SCOPES = {
    "block_until_ready",  # the gated metrics fetch (device_get_metrics)
    "action_fetch",  # env-loop action/logprob/value fetch
    "ipc_wait_update",
    "ipc_wait_rollout",
    "replay_sample",  # prioritized draw ships indices/weights host-side
}
# phases that must stay transfer-silent apart from EXPLICIT device_put
_DISALLOW_SCOPES = {
    "host_to_device",  # rollout upload: device_put (explicit) only
    "ipc_send_shard",  # rollout serialization: numpy only, no device reads
    "replay_insert",
}


def _env_scope_set(var: str) -> set:
    raw = os.environ.get(var, "")
    return {s.strip() for s in raw.split(",") if s.strip()}


def allowed_transfer_scopes() -> set:
    return _ALLOW_SCOPES | _env_scope_set("SHEEPRL_SANITIZE_ALLOW")


def transfer_sanitizer(name: str):
    """Transfer-guard context for trace scope ``name`` under sanitize
    mode: ``disallow`` (implicit transfers raise; explicit
    device_put/device_get still work) for the known transfer-silent
    phases, ``allow`` for the allowlisted fetch phases (so they keep
    working inside an outer disallow scope), inert otherwise.  Extend via
    ``SHEEPRL_SANITIZE_ALLOW`` / ``SHEEPRL_SANITIZE_DISALLOW``
    (comma-separated scope names)."""
    if not sanitize_enabled():
        return nullcontext()
    import jax

    if name in allowed_transfer_scopes():
        return jax.transfer_guard("allow")
    if name in (_DISALLOW_SCOPES | _env_scope_set("SHEEPRL_SANITIZE_DISALLOW")):
        return jax.transfer_guard("disallow")
    return nullcontext()


# ===================================================================== #
# leak registry
# ===================================================================== #
class LeakRegistry:
    """Weak registry of long-lived resources (threads / channels / shm
    segments).  Producers register on creation and unregister on clean
    close; whatever is still live at sweep time is a leak candidate.
    Always on — the cost is one dict write per resource lifetime."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._items: Dict[int, Tuple[str, str, Any, str]] = {}  # token -> (kind, name, ref, where)

    def register(self, kind: str, name: str, obj: Any = None, where: str = "") -> int:
        ref: Any = None
        if obj is not None:
            try:
                ref = weakref.ref(obj)
            except TypeError:
                ref = lambda _o=obj: _o  # unweakrefable: hold strongly (rare; shm names pass None)
        with self._lock:
            self._next += 1
            token = self._next
            self._items[token] = (kind, name, ref, where)
        return token

    def unregister(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._lock:
            self._items.pop(token, None)

    def live(self, kind: Optional[str] = None) -> List[Tuple[str, str, str]]:
        """(kind, name, where) entries whose object is still alive (or has
        no tracked object).  GC'd objects are pruned — an abandoned,
        collectable endpoint is not a leak."""
        out: List[Tuple[str, str, str]] = []
        with self._lock:
            items = list(self._items.items())
        dead = []
        for token, (k, name, ref, where) in items:
            obj = ref() if ref is not None else True
            if obj is None:
                dead.append(token)
                continue
            if isinstance(obj, threading.Thread) and not obj.is_alive():
                dead.append(token)
                continue
            if kind is None or k == kind:
                out.append((k, name, where))
        with self._lock:
            for token in dead:
                self._items.pop(token, None)
        return out


leak_registry = LeakRegistry()


def shm_orphans(prefix: str = "sheeprl_") -> List[str]:
    """Names of ``/dev/shm`` segments left behind by this framework."""
    return sorted(os.path.basename(p) for p in glob.glob(f"/dev/shm/{prefix}*"))


def _worker_threads(include_daemon: bool) -> List[threading.Thread]:
    out = []
    for t in threading.enumerate():
        if t is threading.main_thread() or not t.is_alive():
            continue
        name = t.name or ""
        ours = name.startswith("sheeprl")
        if not t.daemon or (include_daemon and ours):
            out.append(t)
    return out


def sweep_leaks(include_daemon_threads: bool = True) -> Dict[str, List[str]]:
    """One leak snapshot: orphaned shm segments, alive worker threads
    (non-daemon always; sheeprl-named daemons when asked), and registry
    entries still live.  Empty dict = clean."""
    report: Dict[str, List[str]] = {}
    orphans = shm_orphans()
    if orphans:
        report["shm_orphans"] = orphans
    threads = _worker_threads(include_daemon_threads)
    if threads:
        report["threads"] = [f"{t.name} (daemon={t.daemon})" for t in threads]
    live = leak_registry.live()
    if live:
        report["registry"] = [f"{k}:{name}" + (f" [{where}]" if where else "") for k, name, where in live]
    return report


def session_leak_report(grace_s: float = 2.0) -> Dict[str, List[str]]:
    """End-of-suite sweep (tests/conftest.py session fixture).

    Gives in-flight teardown a short grace period (GC + thread joins race
    the fixture), then reports only the HARD failures a human must look
    at: orphaned ``/dev/shm`` segments (PR-3 class) and still-alive
    NON-daemon threads (the PR-6 exit-hang class — a daemon thread cannot
    block interpreter exit, a non-daemon one does).  Registry leftovers
    and lingering daemon threads ride along as informational keys
    (``*_warn``) so the failure message shows the whole picture."""
    import gc
    import time

    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        gc.collect()
        hard_threads = [t for t in _worker_threads(include_daemon=False)]
        if not shm_orphans() and not hard_threads:
            break
        time.sleep(0.1)
    report: Dict[str, List[str]] = {}
    orphans = shm_orphans()
    if orphans:
        report["shm_orphans"] = orphans
    hard = _worker_threads(include_daemon=False)
    if hard:
        report["nondaemon_threads"] = [t.name for t in hard]
    soft = [t for t in _worker_threads(include_daemon=True) if t.daemon]
    if soft:
        report["daemon_threads_warn"] = [t.name for t in soft]
    live = leak_registry.live()
    if live:
        report["registry_warn"] = [f"{k}:{name}" + (f" [{where}]" if where else "") for k, name, where in live]
    return report


@contextmanager
def registered(kind: str, name: str, obj: Any = None, where: str = ""):
    """Scope a registration to a with-block (test helper)."""
    token = leak_registry.register(kind, name, obj, where)
    try:
        yield token
    finally:
        leak_registry.unregister(token)
