"""jaxlint checkers — the five JAX-hazard families, as AST passes.

Every checker is a HEURISTIC tuned to this repo's idioms; each docstring
states exactly what it matches and what it deliberately does not, because
the triage contract (fix / suppress inline / baseline with a why) only
works when the rule is predictable.  Golden positive/negative snippet
pairs in ``tests/test_analysis/test_lint.py`` pin each rule.

Shared machinery: import-alias resolution (``np``/``jnp``/``jax`` spelled
any way), a parent map for context-sensitive matches, and a tiny abstract
interpreter that walks statement lists in program order with copy/merge
at branches and a double pass over loop bodies (so a hazard created at
the bottom of a loop is seen by a use at its top on the next iteration).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RawFinding = Tuple[int, int, str, str]  # (line, col, check, message)

_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_DESCEND = _SCOPE_TYPES + (ast.Lambda, ast.ClassDef)

# attribute reads that are safe on a donated/deleted jax.Array (metadata
# lives on the Python object, not the buffer)
_SAFE_DONATED_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "is_deleted", "device", "devices"}

# reads of a traced value through these never force concretization
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
_STATIC_CALLS = {"isinstance", "len", "getattr", "hasattr", "callable", "type", "id"}

# functions that materialize a private copy of their argument — the
# blessed fix idiom for both the donation and the aliasing classes
_CLEANSE_QUALS = {
    "numpy.copy",
    "numpy.array",
    "numpy.ascontiguousarray",
    "jax.numpy.copy",
    "copy.deepcopy",
}
_CLEANSE_NAMES = {"detach_copy", "deepcopy", "arrays_copy"}

_KEYISH_NAME = re.compile(r"(^|_)(key|keys|rng|rngs)$")

# jax.random callables that DERIVE rather than consume (fold_in is exempt
# by design: fold_in(key, i) with distinct i is the blessed per-step idiom)
_PRNG_NONCONSUMING = {"PRNGKey", "key", "fold_in", "wrap_key_data", "key_data", "clone", "key_impl"}

# entry points whose function-valued arguments get traced
_TRACE_ENTRY_QUALS = {
    "jax.jit",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.cond",
    "jax.lax.fori_loop",
    "jax.lax.associative_scan",
}
_TRACE_ENTRY_NAMES = {
    "jit",
    "shard_map",
    "scan",
    "guard_update",
    "scan_remat",
    "checkpoint",
    "remat",
    # Pallas kernel bodies are traced contexts too: the function handed to
    # pl.pallas_call is traced per compile (interpret mode included), so
    # the retrace/host-sync/prng hazards apply verbatim inside it
    "pallas_call",
}
_TRACE_ENTRY_ATTRS = {"setup_step"}


class ModuleContext:
    """Alias table + parent links for one parsed module."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        self.aliases[head] = head
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
            for child in ast.iter_child_nodes(node):
                child._jaxlint_parent = node  # type: ignore[attr-defined]

    def qual(self, node: Optional[ast.AST]) -> Optional[str]:
        """Canonical dotted name ('jax.numpy.asarray') or None."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.qual(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_jaxlint_parent", None)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)


def _walk_shallow(node: ast.AST) -> Iterable[ast.AST]:
    """Walk ``node`` without descending into nested function/class scopes
    (they are analyzed as scopes of their own)."""
    stack = [node]
    first = True
    while stack:
        n = stack.pop()
        if not first and isinstance(n, _SKIP_DESCEND):
            continue
        first = False
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _scopes(tree: ast.Module) -> List[ast.AST]:
    """The module plus every function definition, at any nesting depth."""
    return [tree] + [n for n in ast.walk(tree) if isinstance(n, _SCOPE_TYPES)]


def _assigned_names(stmt: ast.AST) -> Set[str]:
    """Names this statement (re)binds, shallow."""
    out: Set[str] = set()
    for n in _walk_shallow(stmt):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
    return out


def _in_cleanse_call(ctx: ModuleContext, node: ast.AST, stop: ast.AST) -> bool:
    """True when ``node`` sits inside the arguments of a copy-materializing
    call (np.copy / np.array / detach_copy / x.copy() / deepcopy)."""
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.Call):
            q = ctx.qual(anc.func)
            if q in _CLEANSE_QUALS or (q and q.split(".")[-1] in _CLEANSE_NAMES):
                return True
            if isinstance(anc.func, ast.Attribute) and anc.func.attr == "copy":
                return True
        if anc is stop:
            break
    return False


def _int_tuple_literal(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _terminates(body: Sequence[ast.stmt]) -> bool:
    """Control flow cannot fall out of the bottom of this block."""
    return bool(body) and isinstance(body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _merge_branches(pre: Dict[str, int], stmt: ast.If, s_body: Dict[str, int], s_else: Dict[str, int]) -> Dict[str, int]:
    """Post-If state: a branch that ends in return/raise/break/continue
    contributes nothing to the fall-through (an early-returning arm's
    consumptions/donations cannot reach the code below the If)."""
    body_falls = not _terminates(stmt.body)
    else_falls = not _terminates(stmt.orelse)
    if body_falls and else_falls:
        return {**s_else, **s_body}
    if body_falls:
        return s_body
    if else_falls:
        return s_else
    return dict(pre)  # neither falls through: below the If is dead-ish code


def _body_lists(stmt: ast.AST) -> List[List[ast.stmt]]:
    """Nested statement lists of a compound statement (order matters)."""
    lists = []
    for field in ("body", "orelse", "finalbody"):
        b = getattr(stmt, field, None)
        if b:
            lists.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        if h.body:
            lists.append(h.body)
    return lists


# =====================================================================
# (a) use-after-donate
# =====================================================================
def check_donation(ctx: ModuleContext) -> List[RawFinding]:
    """Flags reads of a variable that was passed at a ``donate_argnums``
    position of a donating dispatch, after that dispatch, unless the name
    was reassigned or the read happens inside a copy-materializing call
    (``detach_copy``/``np.copy``/``.copy()`` — the repo's fix idiom).

    Donating dispatchers are recognized syntactically: a name assigned
    from ``jax.jit(...)`` / ``jax.pmap(...)`` / ``*.setup_step(...)`` /
    ``guard_update(...)`` carrying a LITERAL ``donate_argnums``.  Cross-
    function donation (``update_fn = make_update_fn(...)``) is invisible
    to this pass — the runtime donation sanitizer covers that half.
    Metadata reads (``.shape``/``.dtype``/``.is_deleted``) are exempt:
    they live on the Python object, not the donated buffer.
    """
    findings: List[RawFinding] = []
    seen: Set[Tuple[int, int]] = set()

    module_donors = _collect_donors(ctx, ctx.tree.body)
    for scope in _scopes(ctx.tree):
        body = scope.body if isinstance(scope, _SCOPE_TYPES) else ctx.tree.body
        # module-level donors stay callable from any function in the file
        donors = {**module_donors, **_collect_donors(ctx, body)}
        if not donors:
            continue
        _sim_donation(ctx, body, donors, {}, findings, seen)
    return findings


def _collect_donors(ctx: ModuleContext, body: Sequence[ast.stmt]) -> Dict[str, Tuple[int, ...]]:
    donors: Dict[str, Tuple[int, ...]] = {}
    for stmt in body:
        for n in _walk_shallow(stmt):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name)):
                continue
            call = n.value
            if not isinstance(call, ast.Call):
                continue
            q = ctx.qual(call.func) or ""
            leaf = q.split(".")[-1]
            is_dispatcher = (
                q in ("jax.jit", "jax.pmap")
                or leaf in ("setup_step", "guard_update")
                or (isinstance(call.func, ast.Attribute) and call.func.attr in ("setup_step",))
            )
            if not is_dispatcher:
                continue
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    positions = _int_tuple_literal(kw.value)
                    if positions:
                        donors[n.targets[0].id] = positions
    return donors


def _sim_donation(
    ctx: ModuleContext,
    body: Sequence[ast.stmt],
    donors: Dict[str, Tuple[int, ...]],
    state: Dict[str, int],
    findings: List[RawFinding],
    seen: Set[Tuple[int, int]],
) -> Dict[str, int]:
    for stmt in body:
        if isinstance(stmt, _SKIP_DESCEND):
            continue
        if isinstance(stmt, ast.If):
            _sim_stmt_donation(ctx, stmt.test, donors, state, findings, seen, expr_only=True)
            s1 = _sim_donation(ctx, stmt.body, donors, dict(state), findings, seen)
            s2 = _sim_donation(ctx, stmt.orelse, donors, dict(state), findings, seen)
            state = _merge_branches(state, stmt, s1, s2)
            continue
        if isinstance(stmt, (ast.For, ast.While)):
            # two passes: a donation at the bottom of the body must be
            # visible to a read at its top on the next iteration
            state = _sim_donation(ctx, stmt.body, donors, state, findings, seen)
            state = _sim_donation(ctx, stmt.body, donors, state, findings, seen)
            state = _sim_donation(ctx, stmt.orelse, donors, state, findings, seen)
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith, ast.Try)):
            _sim_stmt_donation(ctx, stmt, donors, state, findings, seen, header_only=True)
            for blist in _body_lists(stmt):
                state = _sim_donation(ctx, blist, donors, state, findings, seen)
            continue
        _sim_stmt_donation(ctx, stmt, donors, state, findings, seen)
    return state


def _sim_stmt_donation(
    ctx: ModuleContext,
    stmt: ast.AST,
    donors: Dict[str, Tuple[int, ...]],
    state: Dict[str, int],
    findings: List[RawFinding],
    seen: Set[Tuple[int, int]],
    expr_only: bool = False,
    header_only: bool = False,
) -> None:
    if header_only:
        nodes: List[ast.AST] = []
        for item in getattr(stmt, "items", []) or []:
            nodes.extend(_walk_shallow(item.context_expr))
    else:
        nodes = list(_walk_shallow(stmt))

    # 1) reads of already-donated names
    for n in nodes:
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and n.id in state:
            parent = ctx.parent(n)
            if isinstance(parent, ast.Attribute) and parent.attr in _SAFE_DONATED_ATTRS:
                continue
            if _in_cleanse_call(ctx, n, stmt):
                # the blessed re-materialize idiom: treat as re-blessing
                state.pop(n.id, None)
                continue
            key = (n.lineno, n.col_offset)
            if key not in seen:
                seen.add(key)
                findings.append(
                    (
                        n.lineno,
                        n.col_offset,
                        "use-after-donate",
                        f"'{n.id}' was donated to a jitted dispatch at line {state[n.id]} "
                        f"and is read again here — its buffer belongs to XLA now "
                        f"(copy it BEFORE the donating call, or reassign from the outputs)",
                    )
                )
    if expr_only:
        return
    # 2) donations performed by this statement
    for n in nodes:
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and n.func.id in donors:
            for pos in donors[n.func.id]:
                if pos < len(n.args) and isinstance(n.args[pos], ast.Name):
                    state[n.args[pos].id] = n.lineno
    # 3) rebinds clear the donated mark
    for name in _assigned_names(stmt):
        state.pop(name, None)


# =====================================================================
# (b) zero-copy aliasing
# =====================================================================
_SINK_QUALS = {"jax.device_put", "jax.numpy.asarray"}
_SINK_ATTRS = {"shard_batch", "replicate"}  # MeshRuntime device_put helpers


def check_zero_copy(ctx: ModuleContext) -> List[RawFinding]:
    """Flags ``jax.device_put`` / ``jnp.asarray`` (and the MeshRuntime
    ``shard_batch``/``replicate`` helpers) whose source is BORROWED host
    memory: ``np.frombuffer``, ``np.memmap``, a member of an ``np.load``
    npz handle, a ``memoryview``, or an ``ShmArena.unpack`` slot view
    without ``copy=True``.  CPU ``device_put`` zero-copy aliases such
    memory WITHOUT keeping its owner alive — when the owner is freed
    (npz closed, shm slot recycled, buffer GC'd) the device array reads
    freed memory (the PR-3/PR-7 heap-corruption class).

    Plain ndarray views (slices) are deliberately NOT flagged: a numpy
    view holds a reference to its base, so the aliased memory cannot be
    freed under it.  The hazardous class is exactly the buffers whose
    lifetime numpy does NOT manage.  ``jnp.array`` copies by default and
    is therefore a sink only if called with ``copy=False``.
    """
    findings: List[RawFinding] = []
    for scope in _scopes(ctx.tree):
        body = scope.body if isinstance(scope, _SCOPE_TYPES) else ctx.tree.body
        _sim_zero_copy(ctx, body, {}, set(), findings)
    return findings


def _classify_borrowed(ctx: ModuleContext, node: ast.AST, npz_vars: Set[str]) -> Optional[str]:
    if isinstance(node, ast.Call):
        q = ctx.qual(node.func) or ""
        if q == "numpy.frombuffer":
            return "np.frombuffer view"
        if q == "numpy.memmap":
            return "np.memmap window"
        if q == "memoryview":
            return "memoryview"
        if isinstance(node.func, ast.Attribute) and node.func.attr == "unpack":
            for kw in node.keywords:
                if kw.arg == "copy" and isinstance(kw.value, ast.Constant) and kw.value.value is True:
                    return None
            return "shm-ring slot view (unpack without copy=True)"
        if isinstance(node.func, ast.Attribute) and node.func.attr == "leaf_views":
            # wire.leaf_views returns np.frombuffer views into a pooled
            # recv arena — recycled on frame release, same lifetime class
            # as a shm slot (ISSUE 19)
            return "wire-arena view (leaf_views)"
    if isinstance(node, ast.Subscript):
        base = node.value
        if isinstance(base, ast.Name) and base.id in npz_vars:
            return "npz member (np.load handle)"
        if isinstance(base, ast.Call) and (ctx.qual(base.func) or "") == "numpy.load":
            return "npz member (np.load handle)"
    return None


def _is_np_load(ctx: ModuleContext, node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and (ctx.qual(node.func) or "") == "numpy.load"


def _sink_call(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
    """Returns a human name when ``node`` is a device-upload sink call."""
    if not isinstance(node, ast.Call):
        return None
    q = ctx.qual(node.func) or ""
    if q in _SINK_QUALS:
        return q.replace("numpy", "np").replace("jax.np", "jnp")
    if q == "jax.numpy.array":
        for kw in node.keywords:
            if kw.arg == "copy" and isinstance(kw.value, ast.Constant) and kw.value.value is False:
                return "jnp.array(copy=False)"
        return None
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SINK_ATTRS:
        return f".{node.func.attr}"
    return None


def _sim_zero_copy(
    ctx: ModuleContext,
    body: Sequence[ast.stmt],
    borrowed: Dict[str, str],
    npz_vars: Set[str],
    findings: List[RawFinding],
) -> None:
    for stmt in body:
        if isinstance(stmt, _SKIP_DESCEND):
            continue
        # with np.load(...) as npz: members of npz die at scope exit
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if _is_np_load(ctx, item.context_expr) and isinstance(item.optional_vars, ast.Name):
                    npz_vars.add(item.optional_vars.id)
        # sinks + sources inside this statement
        for n in _walk_shallow(stmt):
            sink = _sink_call(ctx, n)
            if sink and n.args:
                arg = n.args[0]
                hits = _borrowed_exprs(ctx, arg, borrowed, npz_vars)
                for line, col, kind in hits:
                    findings.append(
                        (
                            line,
                            col,
                            "zero-copy-alias",
                            f"{sink} source is a {kind}: CPU device_put zero-copy aliases it "
                            f"without keeping the owner alive — copy first (np.copy / "
                            f"jnp.array(..., copy=True)) or keep the owner on host_refs",
                        )
                    )
        # track borrowed bindings
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            if isinstance(tgt, ast.Name):
                if _is_np_load(ctx, stmt.value):
                    npz_vars.add(tgt.id)
                    borrowed.pop(tgt.id, None)
                else:
                    kind = _classify_borrowed(ctx, stmt.value, npz_vars)
                    if kind:
                        borrowed[tgt.id] = kind
                        npz_vars.discard(tgt.id)
                    else:
                        borrowed.pop(tgt.id, None)
                        npz_vars.discard(tgt.id)
        else:
            for name in _assigned_names(stmt):
                borrowed.pop(name, None)
                npz_vars.discard(name)
        for blist in _body_lists(stmt):
            _sim_zero_copy(ctx, blist, borrowed, npz_vars, findings)


def _borrowed_exprs(
    ctx: ModuleContext, expr: ast.AST, borrowed: Dict[str, str], npz_vars: Set[str]
) -> List[Tuple[int, int, str]]:
    """Borrowed sources reachable in a sink's first argument without
    passing through a copy-materializing call."""
    hits: List[Tuple[int, int, str]] = []
    stack: List[ast.AST] = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            q = ctx.qual(n.func) or ""
            if q in _CLEANSE_QUALS or (q and q.split(".")[-1] in _CLEANSE_NAMES):
                continue  # a copy between source and sink: safe
            if isinstance(n.func, ast.Attribute) and n.func.attr == "copy":
                continue
        kind = _classify_borrowed(ctx, n, npz_vars)
        if kind:
            hits.append((n.lineno, n.col_offset, kind))
            continue
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and n.id in borrowed:
            hits.append((n.lineno, n.col_offset, borrowed[n.id]))
            continue
        stack.extend(ast.iter_child_nodes(n))
    return hits


# =====================================================================
# (c) PRNG hygiene
# =====================================================================
def check_prng(ctx: ModuleContext) -> List[RawFinding]:
    """Two rules.  ``prng-reuse``: the same key NAME consumed by two
    ``jax.random`` draws (or two ``key=``/``rng=`` keyword passes) without
    an intervening reassignment — identical randomness where independent
    streams were intended.  ``fold_in`` is exempt (per-index derivation is
    the blessed multi-use idiom) and so is ``PRNGKey``.  Loop bodies are
    walked twice, so drawing from an un-split key every iteration flags.
    ``prng-discard``: a bare ``jax.random.split(...)`` expression
    statement — keys were derived and immediately dropped.

    Only key-ish names are tracked (assigned from ``jax.random.*`` or
    matching ``key``/``rng``/``*_key``/``*_rng``), so passing unrelated
    values through ``key=``-less calls never flags.
    """
    findings: List[RawFinding] = []
    seen: Set[Tuple[int, int]] = set()
    for scope in _scopes(ctx.tree):
        body = scope.body if isinstance(scope, _SCOPE_TYPES) else ctx.tree.body
        keyish: Set[str] = set()
        if isinstance(scope, _SCOPE_TYPES):
            for a in list(scope.args.args) + list(scope.args.kwonlyargs) + list(scope.args.posonlyargs):
                if _KEYISH_NAME.search(a.arg):
                    keyish.add(a.arg)
        _sim_prng(ctx, body, keyish, {}, findings, seen)
    return findings


def _prng_consumptions(ctx: ModuleContext, stmt: ast.AST) -> List[Tuple[str, int, int, str]]:
    """(name, line, col, how) key consumptions in one statement."""
    out: List[Tuple[str, int, int, str]] = []
    for n in _walk_shallow(stmt):
        if not isinstance(n, ast.Call):
            continue
        q = ctx.qual(n.func) or ""
        if q.startswith("jax.random."):
            leaf = q.split(".")[-1]
            if leaf in _PRNG_NONCONSUMING:
                continue
            if n.args and isinstance(n.args[0], ast.Name):
                out.append((n.args[0].id, n.lineno, n.col_offset, f"jax.random.{leaf}"))
        for kw in n.keywords:
            if kw.arg in ("key", "rng", "rng_key", "seed_key") and isinstance(kw.value, ast.Name):
                out.append((kw.value.id, n.lineno, n.col_offset, f"{kw.arg}= of a call"))
    return out


def _sim_prng(
    ctx: ModuleContext,
    body: Sequence[ast.stmt],
    keyish: Set[str],
    consumed: Dict[str, int],
    findings: List[RawFinding],
    seen: Set[Tuple[int, int]],
) -> Dict[str, int]:
    for stmt in body:
        if isinstance(stmt, _SKIP_DESCEND):
            continue
        if isinstance(stmt, ast.If):
            s1 = _sim_prng(ctx, stmt.body, keyish, dict(consumed), findings, seen)
            s2 = _sim_prng(ctx, stmt.orelse, keyish, dict(consumed), findings, seen)
            consumed = _merge_branches(consumed, stmt, s1, s2)
            continue
        if isinstance(stmt, (ast.For, ast.While)):
            consumed = _sim_prng(ctx, stmt.body, keyish, consumed, findings, seen)
            consumed = _sim_prng(ctx, stmt.body, keyish, consumed, findings, seen)
            consumed = _sim_prng(ctx, stmt.orelse, keyish, consumed, findings, seen)
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith, ast.Try)):
            for blist in _body_lists(stmt):
                consumed = _sim_prng(ctx, blist, keyish, consumed, findings, seen)
            continue
        # discarded split
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            q = ctx.qual(stmt.value.func) or ""
            if q == "jax.random.split":
                key = (stmt.lineno, stmt.col_offset)
                if key not in seen:
                    seen.add(key)
                    findings.append(
                        (stmt.lineno, stmt.col_offset, "prng-discard", "jax.random.split result is discarded")
                    )
        # track keyish bindings from jax.random results
        for n in _walk_shallow(stmt):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                q = ctx.qual(n.value.func) or ""
                if q.startswith("jax.random."):
                    for t in n.targets:
                        for tn in ast.walk(t):
                            if isinstance(tn, ast.Name):
                                keyish.add(tn.id)
        # consumptions
        for name, line, col, how in _prng_consumptions(ctx, stmt):
            if name not in keyish and not _KEYISH_NAME.search(name):
                continue
            keyish.add(name)
            if name in consumed:
                key = (line, col)
                if key not in seen:
                    seen.add(key)
                    findings.append(
                        (
                            line,
                            col,
                            "prng-reuse",
                            f"key '{name}' already consumed at line {consumed[name]} is consumed "
                            f"again by {how} without a split/reassignment — both draws see "
                            f"IDENTICAL randomness",
                        )
                    )
            else:
                consumed[name] = line
        # rebinds reset
        for name in _assigned_names(stmt):
            consumed.pop(name, None)
    return consumed


# =====================================================================
# (d) host-sync-in-hot-path
# =====================================================================
_HOT_SCOPE_CALLS = {"trace_scope", "hot_scope", "transfer_sanitizer"}


def check_host_sync(ctx: ModuleContext) -> List[RawFinding]:
    """Flags device→host sync points inside loop bodies or ``obs.trace``
    hot scopes: ``.item()`` on a device-ish value, ``float()``/``int()``/
    ``bool()`` of one, ``np.asarray``/``np.array`` of one,
    ``jax.device_get``, and implicit truthiness (``if x:``) on one.  Each
    such site stalls the dispatch pipeline once PER ITERATION — the class
    the ``metric.fetch_every`` gate and ``start_async_host_copy`` exist
    to amortize.

    "Device-ish" = the name was assigned (anywhere in the enclosing
    function — flow-insensitive on purpose) from a ``jax.*``/``jnp.*``
    call.  Intended sync points (the action fetch of an env loop) get an
    inline suppression naming the check, which doubles as documentation.
    """
    findings: List[RawFinding] = []
    for scope in _scopes(ctx.tree):
        body = scope.body if isinstance(scope, _SCOPE_TYPES) else ctx.tree.body
        deviceish: Set[str] = set()
        for stmt in body:
            if isinstance(stmt, _SKIP_DESCEND):
                continue  # nested defs are scopes of their own
            for n in _walk_shallow(stmt):
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                    q = ctx.qual(n.value.func) or ""
                    if q.startswith("jax.") and not q.startswith(("jax.device_get", "jax.tree_util")):
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                deviceish.add(t.id)
        for stmt in body:
            if isinstance(stmt, _SKIP_DESCEND):
                continue
            for n in _walk_shallow(stmt):
                site = _host_sync_site(ctx, n, deviceish)
                if site and _in_hot_context(ctx, n, scope):
                    findings.append(site)
    return findings


def _is_hot_with(ctx: ModuleContext, stmt: ast.AST) -> bool:
    for item in getattr(stmt, "items", []) or []:
        e = item.context_expr
        if isinstance(e, ast.Call):
            q = ctx.qual(e.func) or ""
            if q.split(".")[-1] in _HOT_SCOPE_CALLS:
                return True
    return False


def _in_hot_context(ctx: ModuleContext, node: ast.AST, scope: ast.AST) -> bool:
    """Inside a loop body or a ``trace_scope``/``hot_scope`` with-block of
    the SAME function scope (closures called from a loop are invisible —
    documented heuristic boundary)."""
    for anc in ctx.ancestors(node):
        if anc is scope or isinstance(anc, _SKIP_DESCEND):
            return False
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
            return True
        if isinstance(anc, (ast.With, ast.AsyncWith)) and _is_hot_with(ctx, anc):
            return True
    return False


def _host_sync_site(ctx: ModuleContext, n: ast.AST, deviceish: Set[str]) -> Optional[RawFinding]:
    def _deviceish_expr(e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in deviceish
        if isinstance(e, ast.Call):
            q = ctx.qual(e.func) or ""
            return q.startswith("jax.numpy.")
        if isinstance(e, (ast.Subscript, ast.Attribute)):
            return _deviceish_expr(e.value)
        return False

    # implicit truthiness on a device-ish name
    if isinstance(n, (ast.If, ast.While)) and isinstance(n.test, ast.Name) and n.test.id in deviceish:
        return (
            n.test.lineno,
            n.test.col_offset,
            "host-sync",
            f"implicit truthiness of device array '{n.test.id}' in a hot path blocks on the "
            f"device (fetch an explicit host flag instead)",
        )
    if not isinstance(n, ast.Call):
        return None
    q = ctx.qual(n.func) or ""
    if isinstance(n.func, ast.Attribute) and n.func.attr == "item" and not n.args:
        if _deviceish_expr(n.func.value):
            return (n.lineno, n.col_offset, "host-sync", ".item() on a device array syncs per iteration")
        return None
    if q in ("float", "int", "bool") and len(n.args) == 1 and _deviceish_expr(n.args[0]):
        return (
            n.lineno,
            n.col_offset,
            "host-sync",
            f"{q}() of a device value syncs per iteration (fetch once outside the loop, or gate "
            f"with metric.fetch_every)",
        )
    if q in ("numpy.asarray", "numpy.array") and len(n.args) >= 1 and _deviceish_expr(n.args[0]):
        return (
            n.lineno,
            n.col_offset,
            "host-sync",
            "np.asarray of a device array in a hot path is a blocking device→host copy "
            "(start_async_host_copy + fetch late, or hoist out of the loop)",
        )
    if q == "jax.device_get":
        return (
            n.lineno,
            n.col_offset,
            "host-sync",
            "jax.device_get in a hot path blocks per iteration (batch fetches, see "
            "utils.device_get_metrics)",
        )
    return None


# =====================================================================
# (e) retrace hazards
# =====================================================================
def check_retrace(ctx: ModuleContext) -> List[RawFinding]:
    """Inside functions that get TRACED (decorated with / passed to
    ``jax.jit``, ``setup_step``, ``guard_update``, ``shard_map``,
    ``lax.scan`` & friends — nested defs inherit tracedness):

    - ``retrace-fstring``: an f-string / ``str()`` whose expression reads
      a function parameter or a jnp-derived local.  Formatting a tracer
      either raises (concretization) or, with static shapes, silently
      bakes the VALUE into the trace — one recompile per distinct value.
    - ``retrace-branch``: ``if``/``while`` whose test reads a parameter
      or jnp-derived local directly.  Metadata tests (``.shape``,
      ``.dtype``, ``is None``, ``isinstance``, ``len``) are static and
      exempt; value tests need ``jnp.where``/``lax.cond``.
    - ``retrace-set-iter``: iterating a ``set`` (literal or call, unless
      wrapped in ``sorted``) while tracing — pytree leaf order then varies
      per interpreter run, defeating the compilation cache.
    """
    findings: List[RawFinding] = []
    traced = _traced_functions(ctx)
    for fn in traced:
        params = {a.arg for a in list(fn.args.args) + list(fn.args.kwonlyargs) + list(fn.args.posonlyargs)}
        for va in (fn.args.vararg, fn.args.kwarg):
            if va is not None:
                params.add(va.arg)
        tracedish = set(params)
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                q = ctx.qual(n.value.func) or ""
                if q.startswith(("jax.numpy.", "jax.lax.", "jax.nn.")):
                    for t in n.targets:
                        for tn in ast.walk(t):
                            if isinstance(tn, ast.Name):
                                tracedish.add(tn.id)
        _scan_retrace(ctx, fn, tracedish, findings)
    # dedupe (nested traced fns are walked by their parent too)
    return sorted(set(findings))


def _traced_functions(ctx: ModuleContext) -> List[ast.AST]:
    by_name: Dict[str, List[ast.AST]] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, _SCOPE_TYPES):
            by_name.setdefault(n.name, []).append(n)
    traced: Set[ast.AST] = set()

    def q_is_entry(q: str) -> bool:
        return (
            q in _TRACE_ENTRY_QUALS
            or q.split(".")[-1] in _TRACE_ENTRY_NAMES
            or q.split(".")[-1] in _TRACE_ENTRY_ATTRS
        )

    for n in ast.walk(ctx.tree):
        if isinstance(n, _SCOPE_TYPES):
            for dec in n.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                q = ctx.qual(target) or ""
                if q_is_entry(q):
                    traced.add(n)
                elif q in ("functools.partial", "partial") and isinstance(dec, ast.Call) and dec.args:
                    # @partial(jax.jit, static_argnums=...) — traced iff the
                    # partial'd callable is itself a trace entry point
                    if q_is_entry(ctx.qual(dec.args[0]) or ""):
                        traced.add(n)
        if isinstance(n, ast.Call):
            q = ctx.qual(n.func) or ""
            if not q_is_entry(q):
                continue
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                # functools.partial(kernel, ...) handed to an entry point
                # (the pallas_call/scan idiom for static kernel config)
                # traces the partial'd callable
                if (
                    isinstance(arg, ast.Call)
                    and (ctx.qual(arg.func) or "").split(".")[-1] == "partial"
                    and arg.args
                ):
                    arg = arg.args[0]
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    traced.update(by_name[arg.id])
    # nested defs of traced functions are traced as part of them
    out: Set[ast.AST] = set(traced)
    for fn in traced:
        for inner in ast.walk(fn):
            if isinstance(inner, _SCOPE_TYPES) and inner is not fn:
                out.add(inner)
    return sorted(out, key=lambda f: f.lineno)


def _name_is_static_use(ctx: ModuleContext, name: ast.Name, stop: ast.AST) -> bool:
    for anc in ctx.ancestors(name):
        # metadata reads anywhere up the chain (x.shape[0], data["k"].ndim)
        if isinstance(anc, ast.Attribute) and anc.attr in _STATIC_ATTRS:
            return True
        if isinstance(anc, ast.Call):
            q = ctx.qual(anc.func) or ""
            if q.split(".")[-1] in _STATIC_CALLS:
                return True
        if isinstance(anc, ast.Compare) and all(isinstance(op, (ast.Is, ast.IsNot)) for op in anc.ops):
            return True
        if anc is stop:
            break
    return False


def _scan_retrace(ctx: ModuleContext, fn: ast.AST, tracedish: Set[str], findings: List[RawFinding]) -> None:
    for n in ast.walk(fn):
        if isinstance(n, ast.JoinedStr):
            for v in n.values:
                if isinstance(v, ast.FormattedValue):
                    for sub in ast.walk(v.value):
                        if (
                            isinstance(sub, ast.Name)
                            and isinstance(sub.ctx, ast.Load)
                            and sub.id in tracedish
                            and not _name_is_static_use(ctx, sub, n)
                        ):
                            findings.append(
                                (
                                    n.lineno,
                                    n.col_offset,
                                    "retrace-fstring",
                                    f"traced value '{sub.id}' formatted into a string inside a "
                                    f"traced function (concretization error or silent retrace "
                                    f"per value — format OUTSIDE the jitted fn)",
                                )
                            )
                            break
        elif isinstance(n, (ast.If, ast.While)):
            for sub in ast.walk(n.test):
                if (
                    isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                    and sub.id in tracedish
                    and not _name_is_static_use(ctx, sub, n.test)
                ):
                    findings.append(
                        (
                            n.lineno,
                            n.col_offset,
                            "retrace-branch",
                            f"Python branch on traced value '{sub.id}' inside a traced function "
                            f"(TracerBoolConversionError or per-shape retrace — use jnp.where / "
                            f"lax.cond, or mark the arg static)",
                        )
                    )
                    break
        elif isinstance(n, ast.For):
            it = n.iter
            if isinstance(it, ast.Set) or (
                isinstance(it, ast.Call) and (ctx.qual(it.func) or "").split(".")[-1] == "set"
            ):
                findings.append(
                    (
                        n.lineno,
                        n.col_offset,
                        "retrace-set-iter",
                        "iterating a set while tracing: pytree/arg order becomes "
                        "run-dependent and defeats the compilation cache (sort it)",
                    )
                )


# =====================================================================
# entry point
# =====================================================================
_ALL_CHECKERS = (check_donation, check_zero_copy, check_prng, check_host_sync, check_retrace)


def run_checkers(
    tree: ast.Module, source: str, select: Optional[Set[str]] = None
) -> List[RawFinding]:
    ctx = ModuleContext(tree)
    out: List[RawFinding] = []
    for checker in _ALL_CHECKERS:
        for f in checker(ctx):
            if select is None or f[2] in select:
                out.append(f)
    return out
