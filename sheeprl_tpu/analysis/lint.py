"""jaxlint — the AST static-analysis pass over JAX-hazard bug classes.

Engine only: findings, inline suppressions, the committed baseline, file
walking and the CLI.  The JAX-specific checkers live in
:mod:`sheeprl_tpu.analysis.checkers`.

Design notes
------------
- **Checks are heuristics.** Static analysis cannot prove a ``device_put``
  source is freed or that a jitted callee donates; each checker encodes
  the repo's idioms (``runtime.setup_step(..., donate_argnums=...)``,
  ``ShmArena.unpack``, ``np.load`` members, ``trace_scope`` hot phases)
  and errs toward flagging.  The escape hatches are first-class:
  triage every finding into a FIX, an inline suppression with the check
  name, or a baseline entry with a justification — never ignore one.
- **Suppressions**: ``# jaxlint: disable=check-a,check-b`` on the flagged
  line, ``# jaxlint: disable-next=...`` on the line above it, or
  ``# jaxlint: disable-file=...`` anywhere in the file.  ``all`` matches
  every check.
- **Baseline**: a committed JSON file of fingerprinted findings that are
  accepted (with a ``why``) rather than fixed.  Fingerprints hash the
  *source text* of the flagged line (not its line number), so unrelated
  edits above a baselined site do not invalidate it.  Stale entries are
  reported on stderr; ``--write-baseline`` regenerates the file.

Exit codes: 0 clean, 1 unsuppressed findings, 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ordered catalog: check id -> one-line description (the docs table and
# --list-checks are generated from this, so it cannot drift)
CHECKS: Dict[str, str] = {
    "use-after-donate": (
        "read of a variable passed at a donate_argnums position after the donating "
        "dispatch, without an intervening detach_copy/np.copy/reassignment"
    ),
    "zero-copy-alias": (
        "device_put/jnp.asarray whose source is borrowed host memory (np.frombuffer, "
        "np.memmap, npz member, shm-ring slot view) without an explicit copy"
    ),
    "prng-reuse": (
        "the same PRNG key consumed by two traced draws without a split/reassignment "
        "in between (identical randomness, silently)"
    ),
    "prng-discard": "jax.random.split result discarded (the split paid for keys nobody uses)",
    "host-sync": (
        ".item()/float()/bool()/np.asarray/device_get/implicit truthiness on a device "
        "array inside a loop body or obs.trace hot scope (hidden device sync per step)"
    ),
    "retrace-fstring": (
        "traced value formatted into a string inside a jitted/traced function "
        "(concretization error, or a silent retrace per distinct value)"
    ),
    "retrace-branch": (
        "Python branching on a traced value inside a jitted/traced function "
        "(TracerBoolConversionError, or shape-dependent retraces)"
    ),
    "retrace-set-iter": (
        "iteration over a set while building pytrees inside a traced function "
        "(non-deterministic leaf order => cache misses across runs)"
    ),
    "parse-error": "file does not parse (reported, never baselined silently)",
}

_EXCLUDE_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}
_SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*(disable|disable-next|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass
class Finding:
    path: str  # normalized relative posix path
    line: int
    col: int
    check: str
    message: str
    line_text: str = ""
    occurrence: int = 0  # index among identical (path, check, line_text) findings

    @property
    def fingerprint(self) -> str:
        norm = " ".join(self.line_text.split())
        raw = f"{self.path}::{self.check}::{norm}::{self.occurrence}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.check}: {self.message}"


# --------------------------------------------------------------- suppressions
def _parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Per-line and file-level suppressed check sets.

    Comment-aware (tokenize), so a ``# jaxlint:`` inside a string literal
    does not suppress anything.  ``disable`` applies to the comment's own
    line (and, for a comment-only line, to the next code line — the
    natural place above a multi-line statement); ``disable-next`` to the
    following line only.
    """
    per_line: Dict[int, Set[str]] = {}
    file_level: Set[str] = set()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            mode = m.group(1)
            checks = {c.strip() for c in m.group(2).split(",") if c.strip()}
            lineno = tok.start[0]
            own_line_is_comment_only = tok.line.strip().startswith("#")
            if mode == "disable-file":
                file_level |= checks
            elif mode == "disable-next":
                per_line.setdefault(lineno + 1, set()).update(checks)
            else:  # disable
                per_line.setdefault(lineno, set()).update(checks)
                if own_line_is_comment_only:
                    per_line.setdefault(lineno + 1, set()).update(checks)
    except tokenize.TokenError:
        pass  # the ast parse will report the real problem
    return per_line, file_level


def _suppressed(f: Finding, per_line: Dict[int, Set[str]], file_level: Set[str]) -> bool:
    for checks in (file_level, per_line.get(f.line, ())):
        if f.check in checks or "all" in checks:
            return True
    return False


# ------------------------------------------------------------------ baseline
def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "jaxlint_baseline.json")


def load_baseline(path: Optional[str]) -> Dict[str, dict]:
    """fingerprint -> entry.  Missing file = empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != 1:
        raise ValueError(f"unknown baseline version {doc.get('version')!r} in {path}")
    return {e["fingerprint"]: e for e in doc.get("entries", [])}


def write_baseline(path: str, findings: Sequence[Finding], old: Dict[str, dict]) -> None:
    """Regenerate the baseline from the current findings, carrying each
    surviving entry's ``why`` forward; new entries get a TODO placeholder
    the reviewer must replace with a justification."""
    entries = []
    for f in sorted(findings, key=lambda x: (x.path, x.line, x.check)):
        prev = old.get(f.fingerprint, {})
        entries.append(
            {
                "fingerprint": f.fingerprint,
                "check": f.check,
                "path": f.path,
                "line": f.line,
                "line_text": " ".join(f.line_text.split()),
                "why": prev.get("why", "TODO: justify or fix"),
            }
        )
    with open(path, "w") as fp:
        json.dump({"version": 1, "entries": entries}, fp, indent=2, sort_keys=False)
        fp.write("\n")


# ------------------------------------------------------------------- running
def _norm_path(path: str, root: Optional[str] = None) -> str:
    """Repo-stable identity for baselines: relative to ``root`` (default
    cwd) when under it, absolute otherwise; always posix separators."""
    base = os.path.abspath(root or os.getcwd())
    ap = os.path.abspath(path)
    if ap.startswith(base + os.sep):
        ap = ap[len(base) + 1 :]
    return ap.replace(os.sep, "/")


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in _EXCLUDE_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            raise FileNotFoundError(p)


def lint_source(
    source: str, path: str, select: Optional[Set[str]] = None, root: Optional[str] = None
) -> List[Finding]:
    """All unsuppressed findings for one file's source text."""
    import ast

    from sheeprl_tpu.analysis.checkers import run_checkers

    rel = _norm_path(path, root)
    lines = source.splitlines()

    def line_text(n: int) -> str:
        return lines[n - 1] if 1 <= n <= len(lines) else ""

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(rel, int(e.lineno or 1), int(e.offset or 0), "parse-error", str(e.msg), line_text(int(e.lineno or 1)))
        ]
    raw = run_checkers(tree, source, select=select)
    per_line, file_level = _parse_suppressions(source)
    findings: List[Finding] = []
    occ: Dict[Tuple[str, str], int] = {}
    for line, col, check, message in sorted(raw, key=lambda r: (r[0], r[1], r[2])):
        text = line_text(line)
        key = (check, " ".join(text.split()))
        f = Finding(rel, line, col, check, message, text, occ.get(key, 0))
        occ[key] = occ.get(key, 0) + 1
        if not _suppressed(f, per_line, file_level):
            findings.append(f)
    return findings


def lint_paths(
    paths: Sequence[str], select: Optional[Set[str]] = None, root: Optional[str] = None
) -> List[Finding]:
    findings: List[Finding] = []
    for fn in iter_py_files(paths):
        with open(fn, encoding="utf-8") as f:
            source = f.read()
        findings.extend(lint_source(source, fn, select=select, root=root))
    return findings


# ----------------------------------------------------------------------- CLI
def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint",
        description="JAX-hazard static analysis: donation, aliasing, PRNG, host-sync, retrace checks.",
    )
    ap.add_argument("paths", nargs="*", default=["sheeprl_tpu"], help="files/directories to lint")
    ap.add_argument("--baseline", default=None, help="baseline JSON (default: the committed in-package file)")
    ap.add_argument("--no-baseline", action="store_true", help="ignore the baseline entirely")
    ap.add_argument("--write-baseline", action="store_true", help="accept current findings into the baseline")
    ap.add_argument("--select", default=None, help="comma-separated check ids to run (default: all)")
    ap.add_argument("--json", action="store_true", help="machine-readable findings on stdout")
    ap.add_argument("--list-checks", action="store_true", help="print the checker catalog and exit")
    args = ap.parse_args(argv)

    if args.list_checks:
        for check, desc in CHECKS.items():
            print(f"{check:18s} {desc}")
        return 0

    select: Optional[Set[str]] = None
    if args.select:
        select = {c.strip() for c in args.select.split(",") if c.strip()}
        unknown = select - set(CHECKS)
        if unknown:
            print(f"jaxlint: unknown checks: {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    try:
        findings = lint_paths(args.paths, select=select)
    except FileNotFoundError as e:
        print(f"jaxlint: no such file or directory: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    baseline = {} if args.no_baseline else load_baseline(baseline_path)

    if args.write_baseline:
        write_baseline(baseline_path, findings, baseline)
        print(f"jaxlint: wrote {len(findings)} entries to {baseline_path}", file=sys.stderr)
        return 0

    fresh = [f for f in findings if f.fingerprint not in baseline]
    matched = {f.fingerprint for f in findings if f.fingerprint in baseline}
    stale = [e for fp, e in baseline.items() if fp not in matched]

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [dataclasses.asdict(f) | {"fingerprint": f.fingerprint} for f in fresh],
                    "baselined": len(findings) - len(fresh),
                    "stale_baseline": [e["fingerprint"] for e in stale],
                },
                indent=2,
            )
        )
    else:
        for f in fresh:
            print(f.render())
    if stale:
        print(
            f"jaxlint: {len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'} "
            f"(fixed or moved — refresh with --write-baseline):",
            file=sys.stderr,
        )
        for e in stale:
            print(f"  {e['path']}: {e['check']}: {e.get('line_text', '')!r}", file=sys.stderr)
    if fresh:
        n_files = len({f.path for f in fresh})
        print(f"jaxlint: {len(fresh)} finding(s) in {n_files} file(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
