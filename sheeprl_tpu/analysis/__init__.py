"""sheeprl_tpu.analysis — JAX-hazard correctness tooling (ISSUE 9).

Two pillars:

- :mod:`sheeprl_tpu.analysis.lint` (+ :mod:`.checkers`) — ``jaxlint``, an
  AST static-analysis pass over the repo with JAX-specific checkers for
  the bug classes every concurrency PR has shipped at least once:
  use-after-donate, zero-copy host aliasing, PRNG key reuse, host syncs
  in hot loops, and retrace hazards.  Run as ``python -m
  sheeprl_tpu.analysis <paths>`` / the ``jaxlint`` console script /
  ``scripts/jaxlint.py``.  Inline ``# jaxlint: disable=<check>``
  suppressions plus a committed baseline file keep the pass
  clean-by-default over ``sheeprl_tpu/`` in tier-1.
- :mod:`sheeprl_tpu.analysis.sanitizers` — opt-in runtime sanitizers
  (``SHEEPRL_SANITIZE=1``): a donation sanitizer that turns intermittent
  use-after-donate into deterministic failures, a host-alias guard for
  zero-copy uploads of borrowed host memory, scoped
  ``jax.transfer_guard`` wiring for the hot-loop trace scopes, and the
  thread/channel/shm leak registry behind the suite-wide pytest sweep.
"""

from sheeprl_tpu.analysis.lint import CHECKS, Finding, lint_paths, main
from sheeprl_tpu.analysis.sanitizers import (
    DonationSanitizerError,
    HostAliasError,
    check_host_sources,
    guard_donation,
    leak_registry,
    sanitize_enabled,
    session_leak_report,
    shm_orphans,
    transfer_sanitizer,
)

__all__ = [
    "CHECKS",
    "Finding",
    "lint_paths",
    "main",
    "DonationSanitizerError",
    "HostAliasError",
    "check_host_sources",
    "guard_donation",
    "leak_registry",
    "sanitize_enabled",
    "session_leak_report",
    "shm_orphans",
    "transfer_sanitizer",
]
