"""``python -m sheeprl_tpu.analysis`` — run the jaxlint static pass."""

import sys

from sheeprl_tpu.analysis.lint import main

if __name__ == "__main__":
    sys.exit(main())
