"""An elastic pool of serving loops behind one session cache.

One :class:`~sheeprl_tpu.serve.service.InferenceServer` loop serializes
its batches; under a client swarm the queue depth is the saturation
signal.  :class:`ServePool` runs N such loops IN ONE PROCESS, sharing:

- the **session cache / acted-cache / pending guard** (the ``shared``
  dict of :class:`~sheeprl_tpu.serve.sessions.SessionInferenceServer`),
  so a client channel can migrate between workers across a rebalance
  without breaking the exactly-once contract — a request acted by the
  old worker is answered from the shared cache by the new one;
- the **policy closures** — every worker dispatches through the same
  jitted step, so growing the pool reuses the warm per-bucket XLA
  traces: the post-warmup compile counter stays flat across scale
  events (asserted by the swarm e2e test);
- the **params** — :meth:`swap_params` swaps all workers between
  batches (hot-swap semantics unchanged).

Scaling is driven by an :class:`~sheeprl_tpu.scale.autoscaler.Autoscaler`
consuming the pool's own measured pressure (aggregate queue depth per
worker against ``queue_high``/``queue_low``): :meth:`control_tick` is
the whole control loop.  Growing spawns a worker and rebalances the
most-loaded clients onto it; shrinking retires the youngest worker
QUIETLY (it answers everything pending, then exits WITHOUT stop-framing
its clients) and hands its channels to the survivors.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from sheeprl_tpu.scale.autoscaler import Autoscaler

__all__ = ["ServePool"]


class ServePool:
    """Elastic in-process serving pool (module docstring).

    ``factory(index, shared)`` builds one (not yet started) serving loop
    — typically a :class:`SessionInferenceServer` closing over ONE
    jitted policy step; ``shared`` is this pool's cross-worker state
    dict, passed through verbatim.
    """

    def __init__(
        self,
        factory: Callable[[int, Dict[str, Any]], Any],
        *,
        min_workers: int = 1,
        max_workers: int = 4,
        autoscaler: Optional[Autoscaler] = None,
        queue_high: int = 8,
        queue_low: int = 1,
        name: str = "serve_pool",
    ):
        self._factory = factory
        self.min_workers = max(1, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.autoscaler = autoscaler or Autoscaler(
            min_size=self.min_workers, max_size=self.max_workers, name=name
        )
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        self.name = name
        self.shared: Dict[str, Any] = {}
        self.workers: List[Any] = []
        self._assignment: Dict[int, Any] = {}  # client_id -> worker
        self._channels: Dict[int, Any] = {}  # client_id -> channel (for migration)
        self._next_index = 0
        self._lock = threading.RLock()
        self.rebalanced = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServePool":
        with self._lock:
            while len(self.workers) < self.min_workers:
                self._spawn_worker()
        return self

    def _spawn_worker(self):
        w = self._factory(self._next_index, self.shared)
        self._next_index += 1
        self.workers.append(w)
        w.start()
        return w

    def attach(self, client_id: int, channel) -> None:
        """Register one client with the least-loaded worker."""
        with self._lock:
            w = min(self.workers, key=self._load_of)
            self._channels[int(client_id)] = channel
            self._assignment[int(client_id)] = w
            w.attach(client_id, channel)

    def _load_of(self, w) -> int:
        return sum(1 for ww in self._assignment.values() if ww is w)

    def _migrate(self, client_id: int, src, dst) -> None:
        # order matters: drop from the old worker's map first — a frame
        # the old loop already swept is still answered exactly once via
        # the SHARED acted-cache when the client retries against dst
        src.detach(client_id)
        dst.attach(client_id, self._channels[client_id])
        self._assignment[client_id] = dst
        self.rebalanced += 1

    # -------------------------------------------------------------- scaling
    def grow(self) -> bool:
        with self._lock:
            if len(self.workers) >= self.max_workers:
                return False
            w = self._spawn_worker()
            # rebalance: pull clients off the most-loaded survivors until
            # the newcomer carries its fair share
            fair = max(1, len(self._assignment) // len(self.workers))
            inflight = {c for c, _ in self.shared.get("inflight", ())}
            moved = 0
            while moved < fair:
                donors = [ww for ww in self.workers if ww is not w and self._load_of(ww) > 0]
                if not donors:
                    break
                donor = max(donors, key=self._load_of)
                cands = [c for c, ww in self._assignment.items() if ww is donor]
                # prefer quiescent clients: migrating one mid-request
                # strands its reply until the retry (still exactly-once
                # via the shared caches, but a needless latency spike)
                cid = next((c for c in cands if c not in inflight), cands[0])
                self._migrate(cid, donor, w)
                moved += 1
            return True

    def shrink(self) -> bool:
        with self._lock:
            if len(self.workers) <= self.min_workers:
                return False
            w = self.workers.pop()  # youngest first: oldest workers are warmest
        # quiet retire OUTSIDE the lock (it joins the serving thread):
        # everything pending is answered before the channels move
        w.retire()
        with self._lock:
            for cid, ww in list(self._assignment.items()):
                if ww is w:
                    dst = min(self.workers, key=self._load_of)
                    self._migrate(cid, w, dst)
        return True

    def control_tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One autoscaler tick off the pool's own measured load: queue
        rows per worker >= ``queue_high`` is pressure, total queue <=
        ``queue_low`` is slack.  Actuates the decision immediately."""
        with self._lock:
            n = len(self.workers)
            depth = sum(len(w._pending) for w in self.workers)
        pressure = depth >= self.queue_high * n
        slack = depth <= self.queue_low
        reason = f"queue_depth={depth}/{n}w"
        decision = self.autoscaler.observe(n, pressure, slack, reason=reason, now=now)
        if decision is None:
            return None
        if decision["action"] == "grow":
            self.grow()
        else:
            self.shrink()
        return decision

    # ------------------------------------------------------------- plumbing
    def swap_params(self, params, source: str = "direct") -> None:
        with self._lock:
            workers = list(self.workers)
        for w in workers:
            w.swap_params(params, source)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            workers = list(self.workers)
            per_worker = [w.stats() for w in workers]
        out: Dict[str, Any] = {
            "role": "pool",
            "workers": len(workers),
            "rebalanced": self.rebalanced,
            "queue_depth": sum(s.get("queue_depth", 0) for s in per_worker),
            "requests": sum(s.get("requests", 0) for s in per_worker),
            "acted": sum(s.get("acted", 0) for s in per_worker),
            "dedup_hits": sum(s.get("dedup_hits", 0) for s in per_worker),
            "autoscale": self.autoscaler.stats(),
        }
        if per_worker and "sessions" in per_worker[0]:
            out["sessions"] = per_worker[0]["sessions"]  # shared cache: any worker's view
        # merged batch histogram: the compile-surface audit reads this
        hist: Dict[str, int] = {}
        for s in per_worker:
            for k, v in (s.get("batch_hist") or {}).items():
                hist[k] = hist.get(k, 0) + v
        out["batch_hist"] = hist
        return out

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            workers = list(self.workers)
            self.workers = []
        for w in workers:
            try:
                w.close(timeout=timeout)
            except Exception:
                pass
