"""Saturation swarm: hundreds of threaded session clients vs one pool.

The millions-of-users posture of a serving plane is not provable from a
player loop — it needs a client population with realistic arrival
statistics driven until the plane saturates.  :func:`run_swarm` is that
harness: N threaded :class:`~sheeprl_tpu.serve.sessions.SessionClient`
workers, each with a HEAVY-TAILED (lognormal) think time between steps
(bursty arrivals, the property that makes deadline batching and
autoscaling earn their keep), per-client latency recording, and a p99
SLO verdict through the PR-16 tracker grammar.

A coordinator thread ticks alongside the swarm: it feeds the rolling
p99 to the SLO, and — when the caller passes ``control_tick`` (the
:meth:`~sheeprl_tpu.scale.pool.ServePool.control_tick` bound method) —
drives the autoscaler control loop at swarm cadence, so the grow/shrink
trajectory in the report is MEASURED under load, not scripted.

``scripts/swarm.py`` wraps this against a served checkpoint;
``bench.py``'s ``swarm`` section and the scale chaos leg wrap it
in-process.  Every run returns a :class:`SwarmReport` whose dict is the
``benchmarks/results/swarm_*.json`` row format.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from sheeprl_tpu.serve.sessions import SessionClient

__all__ = ["SwarmClient", "SwarmReport", "run_swarm"]


class SwarmClient(threading.Thread):
    """One synthetic user: think (lognormal), step the session, record."""

    def __init__(
        self,
        client: SessionClient,
        obs_fn: Callable[[np.random.Generator, int], list],
        *,
        steps: int,
        rows: int,
        think_mean_s: float,
        think_sigma: float,
        rng: np.random.Generator,
        window: Optional[deque] = None,
        window_lock: Optional[threading.Lock] = None,
    ):
        super().__init__(name=f"swarm-{client.client_id}", daemon=True)
        self.client = client
        self._obs_fn = obs_fn
        self.steps = int(steps)
        self.rows = int(rows)
        # lognormal parameterized by its MEAN (not mu): heavy tail up,
        # median below the mean — the think-time shape of real users
        self._mu = math.log(max(think_mean_s, 1e-6)) - 0.5 * think_sigma**2
        self._sigma = float(think_sigma)
        self._rng = rng
        self._window = window
        self._window_lock = window_lock
        self.latencies_s: List[float] = []
        self.remote = 0
        self.local = 0

    def run(self) -> None:
        for _ in range(self.steps):
            time.sleep(float(self._rng.lognormal(self._mu, self._sigma)))
            arrays = self._obs_fn(self._rng, self.rows)
            t0 = time.monotonic()
            _, source = self.client.step(arrays, self.rows)
            lat = time.monotonic() - t0
            if source == "remote":
                self.remote += 1
                self.latencies_s.append(lat)
                if self._window is not None:
                    with self._window_lock:
                        self._window.append(lat)
            else:
                self.local += 1
        self.client.close_session()

    def percentiles(self) -> Dict[str, float]:
        if not self.latencies_s:
            return {}
        arr = np.sort(np.asarray(self.latencies_s))
        return {
            "p50": round(float(np.percentile(arr, 50)) * 1e3, 3),
            "p99": round(float(np.percentile(arr, 99)) * 1e3, 3),
            "n": len(arr),
        }


class SwarmReport:
    """The swarm run's result row (``as_dict`` is the benchmark JSON)."""

    def __init__(self, data: Dict[str, Any]):
        self.data = data

    def __getitem__(self, k):
        return self.data[k]

    def as_dict(self) -> Dict[str, Any]:
        return self.data

    @property
    def slo_ok(self) -> bool:
        verdict = self.data.get("slo", {}).get("swarm_p99", {})
        return verdict.get("state", "ok") == "ok"


def _latency_histogram(latencies_ms: List[float]) -> Dict[str, int]:
    """Log2-ms buckets ("<=1ms", "<=2ms", ... , ">1024ms")."""
    hist: Dict[str, int] = {}
    for ms in latencies_ms:
        if ms > 1024:
            label = ">1024ms"
        else:
            label = f"<={max(1, 2 ** max(0, math.ceil(math.log2(max(ms, 1e-3)))))}ms"
        hist[label] = hist.get(label, 0) + 1
    return {k: hist[k] for k in sorted(hist, key=lambda s: (s == ">1024ms", len(s), s))}


def run_swarm(
    channels: List[Any],
    *,
    steps: int = 50,
    rows: int = 1,
    obs_fn: Optional[Callable[[np.random.Generator, int], list]] = None,
    obs_dim: int = 4,
    obs_key: str = "state",
    think_mean_ms: float = 2.0,
    think_sigma: float = 1.0,
    seed: int = 0,
    client_kw: Optional[Dict[str, Any]] = None,
    slo_target_ms: float = 250.0,
    slo_budget: float = 0.05,
    control_tick: Optional[Callable[[], Any]] = None,
    tick_interval_s: float = 0.02,
) -> SwarmReport:
    """Drive one swarm to completion and return the report.

    ``channels`` are the client ends of an already-attached transport
    (one per swarm client — the server/pool side must be attached by
    the caller).  ``control_tick`` runs at ``tick_interval_s`` cadence
    on the coordinator thread while the swarm is up.
    """
    from sheeprl_tpu.obs.metrics import SLOTracker

    if obs_fn is None:

        def obs_fn(rng: np.random.Generator, r: int) -> list:
            return [(obs_key, rng.standard_normal((r, obs_dim)).astype(np.float32))]

    window: deque = deque(maxlen=256)
    window_lock = threading.Lock()
    clients: List[SwarmClient] = []
    for i, ch in enumerate(channels):
        sc = SessionClient(ch, i, seed=seed + i, **(client_kw or {}))
        clients.append(
            SwarmClient(
                sc,
                obs_fn,
                steps=steps,
                rows=rows,
                think_mean_s=think_mean_ms / 1e3,
                think_sigma=think_sigma,
                rng=np.random.default_rng(seed * 100_003 + i),
                window=window,
                window_lock=window_lock,
            )
        )
    tracker = SLOTracker(
        slos=[
            {
                "name": "swarm_p99",
                "key": "swarm.latency_ms",
                "percentile": 99,
                "target": float(slo_target_ms),
                "budget": float(slo_budget),
            }
        ]
    )
    t0 = time.monotonic()
    for c in clients:
        c.start()

    def _coordinate() -> None:
        while any(c.is_alive() for c in clients):
            if control_tick is not None:
                try:
                    control_tick()
                except Exception:
                    pass
            with window_lock:
                buf = list(window)
            if len(buf) >= 8:
                p99 = float(np.percentile(np.sort(np.asarray(buf)), 99)) * 1e3
                tracker.observe({"swarm": {"latency_ms": {"p99": round(p99, 3)}}})
            time.sleep(tick_interval_s)

    coordinator = threading.Thread(target=_coordinate, name="swarm-coordinator", daemon=True)
    coordinator.start()
    for c in clients:
        c.join()
    coordinator.join(timeout=5.0)
    wall_s = time.monotonic() - t0

    all_lat_ms = [s * 1e3 for c in clients for s in c.latencies_s]
    remote = sum(c.remote for c in clients)
    local = sum(c.local for c in clients)
    agg: Dict[str, Any] = {}
    if all_lat_ms:
        arr = np.sort(np.asarray(all_lat_ms))
        agg = {
            "p50": round(float(np.percentile(arr, 50)), 3),
            "p95": round(float(np.percentile(arr, 95)), 3),
            "p99": round(float(np.percentile(arr, 99)), 3),
            "n": len(arr),
        }
    slo_sections = {s["name"]: s for s in ({"name": x.name, **x.section()} for x in tracker.slos)}
    report = SwarmReport(
        {
            "clients": len(clients),
            "steps_per_client": int(steps),
            "rows": int(rows),
            "think_mean_ms": float(think_mean_ms),
            "think_sigma": float(think_sigma),
            "wall_s": round(wall_s, 3),
            "actions_per_s": round(remote * rows / wall_s, 1) if wall_s > 0 else 0.0,
            "remote": remote,
            "local_fallbacks": local,
            "dropped": sum(c.steps for c in clients) - remote - local,  # must be 0
            "session_losses": sum(c.client.session_losses for c in clients),
            "session_reopens": sum(c.client.session_reopens for c in clients),
            "latency_ms": agg,
            "latency_hist": _latency_histogram(all_lat_ms),
            "per_client": [c.percentiles() for c in clients],
            "slo": slo_sections,
        }
    )
    return report
