"""sheeprl_tpu.scale — the elastic consumer of three producer surfaces.

PR 15 built the signals (``PlayerSupervisor.autoscale_signal()``, the
alert plane, queue depth + batch histograms on ``/status``); PR 6 built
the actuator (the join machinery that grows a fan-in without stalling
survivors); PR 8 built the serving plane those signals describe.  This
subsystem closes the loop:

- :mod:`~sheeprl_tpu.scale.autoscaler` — the hysteresis decision engine
  (sustained pressure grows, sustained slack shrinks, per-direction
  cooldowns, min/max bounds, a scale-event budget) plus its
  configuration surface;
- :mod:`~sheeprl_tpu.scale.pool` — an elastic pool of serving loops in
  one process sharing the session cache, params, and jit traces, so
  growing capacity never recompiles;
- :mod:`~sheeprl_tpu.scale.swarm` — the saturation harness: hundreds of
  threaded session clients with heavy-tailed think times, per-client
  latency histograms, and a p99 SLO verdict (``scripts/swarm.py`` /
  ``bench.py swarm``).
"""

from sheeprl_tpu.scale.autoscaler import Autoscaler, autoscaler_knobs
from sheeprl_tpu.scale.pool import ServePool
from sheeprl_tpu.scale.swarm import SwarmClient, SwarmReport, run_swarm

__all__ = [
    "Autoscaler",
    "ServePool",
    "SwarmClient",
    "SwarmReport",
    "autoscaler_knobs",
    "run_swarm",
]
