"""Telemetry-driven autoscaling for the elastic player pool + serving plane.

The Ape-X lineage scales ACTOR count to match learner appetite; SEED RL
scales the serving tier to match client load.  :class:`Autoscaler` is
the decision engine both share: the caller feeds it one observation per
control tick — a pressure bit, a slack bit, and the current size — and
it answers with a grow/shrink decision (or None) under the stability
machinery production autoscalers grow scars for:

- **hysteresis windows** — pressure (slack) must hold CONTINUOUSLY for
  ``up_window_s`` (``down_window_s``) before a decision fires; a single
  noisy tick never scales anything, and any contradicting tick resets
  the window;
- **per-direction cooldowns** — after a grow, further grows wait out
  ``up_cooldown_s`` (same for shrinks), so the controller observes the
  effect of one actuation before stacking another.  Opposite directions
  do NOT share a cooldown: a bad grow can be undone promptly;
- **min/max bounds** — the pool never shrinks below ``min_size``
  (availability floor) or grows past ``max_size`` (the spawned-slot
  ceiling the transport hub was built with);
- **a scale-event budget** — a defensive bound on TOTAL decisions per
  run; a flapping signal exhausts the budget and the autoscaler goes
  quiescent instead of thrashing the pool forever.

Every decision lands three ways: a typed flight event (``autoscale``),
the telemetry ``autoscale`` key (:meth:`Autoscaler.stats`, rendered by
``obs.top``/``/status``), and — because the shipped alert pack gains an
``autoscaler_budget_exhausted`` rule — the alert plane.

The WIRING of signals to the pressure/slack bits is the caller's:
``ppo_decoupled`` derives pressure from the learner's fan-in gather wait
(players starving the learner — Ape-X appetite) and any of a set of
firing alert names from ``autoscale_signal()``; the swarm/serve pool
derives it from queue depth and p95 against the SLO.  Keeping the
engine signal-agnostic is what lets one implementation drive both the
player pool and the serving plane.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from sheeprl_tpu.obs import flight

__all__ = ["Autoscaler", "autoscaler_knobs"]


def autoscaler_knobs(cfg) -> Dict[str, Any]:
    """The ``algo.autoscaler.*`` configuration surface, resolved with
    defaults.  ``enabled=false`` (the default) keeps the control loop
    out of the trainer entirely — the pre-PR topology is untouched."""
    sc = cfg.algo.get("autoscaler", None) or {}
    return {
        "enabled": bool(sc.get("enabled", False)),
        "min_players": int(sc.get("min_players", 1)),
        "max_players": int(sc.get("max_players", 0)),  # 0 = the spawned pool size
        "up_window_s": float(sc.get("up_window_s", 2.0)),
        "down_window_s": float(sc.get("down_window_s", 5.0)),
        "up_cooldown_s": float(sc.get("up_cooldown_s", 5.0)),
        "down_cooldown_s": float(sc.get("down_cooldown_s", 10.0)),
        "event_budget": int(sc.get("event_budget", 16)),
        "gather_wait_pressure_s": float(sc.get("gather_wait_pressure_s", 0.05)),
        "gather_wait_slack_s": float(sc.get("gather_wait_slack_s", 0.005)),
        "alert_pressure_names": list(
            sc.get("alert_pressure_names", ["serve_p99_slo", "breaker_open"])
        ),
    }


class Autoscaler:
    """The hysteresis grow/shrink decision engine (module docstring).

    :meth:`observe` is the whole API: one call per control tick with the
    current size and the tick's pressure/slack classification; the
    return value is a decision dict (``action``/``reason``/``size``/
    ``target``) when this tick crossed a hysteresis window, else None.
    The CALLER actuates (spawn/retire/set_capacity) — the engine only
    decides, so it is trivially unit-testable with a fake clock.
    """

    def __init__(
        self,
        *,
        min_size: int = 1,
        max_size: int = 8,
        up_window_s: float = 2.0,
        down_window_s: float = 5.0,
        up_cooldown_s: float = 5.0,
        down_cooldown_s: float = 10.0,
        event_budget: int = 16,
        name: str = "pool",
    ):
        self.min_size = max(0, int(min_size))
        self.max_size = max(self.min_size, int(max_size))
        self.up_window_s = float(up_window_s)
        self.down_window_s = float(down_window_s)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.event_budget = int(event_budget)
        self.name = name
        self._pressure_since: Optional[float] = None
        self._slack_since: Optional[float] = None
        self._last_up = -float("inf")
        self._last_down = -float("inf")
        self.events_used = 0
        self.grows = 0
        self.shrinks = 0
        self.last_decision: Optional[Dict[str, Any]] = None
        self.decisions: List[Dict[str, Any]] = []

    # --------------------------------------------------------------- engine
    def observe(
        self,
        size: int,
        pressure: bool,
        slack: bool,
        reason: str = "",
        now: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """One control tick.  ``pressure`` and ``slack`` are this tick's
        classification of the signal surface (both False = neutral; both
        True is treated as pressure — growing is the safe error)."""
        now = time.monotonic() if now is None else now
        if pressure:
            slack = False
        # hysteresis: windows track CONTINUOUS runs; any contradicting
        # or neutral tick resets the opposite run
        self._pressure_since = (
            (self._pressure_since if self._pressure_since is not None else now)
            if pressure
            else None
        )
        self._slack_since = (
            (self._slack_since if self._slack_since is not None else now) if slack else None
        )
        if self.events_used >= self.event_budget:
            return None
        size = int(size)
        if (
            pressure
            and size < self.max_size
            and now - self._pressure_since >= self.up_window_s
            and now - self._last_up >= self.up_cooldown_s
        ):
            self._last_up = now
            self._pressure_since = None  # a fresh window per decision
            return self._decide("grow", size, size + 1, reason or "pressure", now)
        if (
            slack
            and size > self.min_size
            and now - self._slack_since >= self.down_window_s
            and now - self._last_down >= self.down_cooldown_s
        ):
            self._last_down = now
            self._slack_since = None
            return self._decide("shrink", size, size - 1, reason or "slack", now)
        return None

    def _decide(self, action: str, size: int, target: int, reason: str, now: float) -> Dict[str, Any]:
        self.events_used += 1
        if action == "grow":
            self.grows += 1
        else:
            self.shrinks += 1
        decision = {
            "action": action,
            "size": size,
            "target": target,
            "reason": reason,
            "budget_remaining": self.event_budget - self.events_used,
        }
        self.last_decision = decision
        self.decisions.append(decision)
        flight.fleet_event(
            "autoscale",
            scaler=self.name,
            action=action,
            size=size,
            target=target,
            reason=reason,
        )
        return decision

    # ------------------------------------------------------------ telemetry
    def stats(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The telemetry ``autoscale`` key (obs.top renders it)."""
        now = time.monotonic() if now is None else now
        return {
            "name": self.name,
            "min": self.min_size,
            "max": self.max_size,
            "grows": self.grows,
            "shrinks": self.shrinks,
            "events_used": self.events_used,
            "event_budget": self.event_budget,
            "budget_exhausted": int(self.events_used >= self.event_budget),
            "last_decision": self.last_decision,
            "cooldown": {
                "up_remaining_s": round(max(0.0, self.up_cooldown_s - (now - self._last_up)), 3),
                "down_remaining_s": round(
                    max(0.0, self.down_cooldown_s - (now - self._last_down)), 3
                ),
            },
            "window": {
                "pressure_held_s": round(now - self._pressure_since, 3)
                if self._pressure_since is not None
                else 0.0,
                "slack_held_s": round(now - self._slack_since, 3)
                if self._slack_since is not None
                else 0.0,
            },
        }
