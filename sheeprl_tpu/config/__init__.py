from sheeprl_tpu.config.compose import (
    MISSING,
    ConfigError,
    MissingValueError,
    compose,
    deep_merge,
    dotdict,
    instantiate,
    resolve,
    validate_no_missing,
)

__all__ = [
    "MISSING",
    "ConfigError",
    "MissingValueError",
    "compose",
    "deep_merge",
    "dotdict",
    "instantiate",
    "resolve",
    "validate_no_missing",
]
