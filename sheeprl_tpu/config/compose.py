"""Hydra-style YAML config composition, dependency-free.

The reference framework drives everything through Hydra (+OmegaConf):
a root ``config.yaml`` with a ``defaults`` list, config *groups*
(``algo/``, ``env/``, ``exp/``, ...), ``${...}`` interpolation, dotted
CLI overrides and ``_target_`` object instantiation
(see reference sheeprl/configs/config.yaml and sheeprl/cli.py:358).

Neither hydra nor omegaconf is available here, so this module
re-implements the subset the framework needs:

- ``defaults`` lists with ``_self_``, ``group: option``,
  ``override /group: option`` and ``/group@package: option`` entries;
- ``# @package _global_`` headers (group file merges at the root);
- deep-merge composition, later wins;
- lazy ``${a.b.c}`` interpolation + ``${now:%fmt}`` resolver;
- CLI overrides: ``group=option`` (when ``group/option.yaml`` exists),
  ``a.b.c=value`` (yaml-parsed scalar), ``+a.b=v`` to add new keys,
  ``~a.b`` to delete;
- ``???`` required-value markers, validated on access;
- :func:`instantiate` for ``_target_`` nodes (hydra.utils.instantiate
  equivalent, incl. ``_partial_``).
"""

from __future__ import annotations

import copy
import datetime
import importlib
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import yaml

MISSING = "???"

_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


class _YamlLoader(yaml.SafeLoader):
    """SafeLoader that also parses ``1e-3``-style floats (YAML 1.2 rule)."""


_YamlLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:[-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
        |\.[0-9_]+(?:[eE][-+][0-9]+)?
        |[-+]?\.(?:inf|Inf|INF)
        |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def yaml_load(text: str) -> Any:
    return yaml.load(text, Loader=_YamlLoader)  # noqa: S506


class ConfigError(Exception):
    pass


class MissingValueError(ConfigError):
    pass


# --------------------------------------------------------------------------- #
# dotdict: attribute access over nested dicts (reference utils/utils.py:34)
# --------------------------------------------------------------------------- #
class dotdict(dict):
    """dict with attribute access, recursively applied to nested dicts."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if isinstance(v, dict) and not isinstance(v, dotdict):
                self[k] = dotdict(v)
            elif isinstance(v, list):
                self[k] = [dotdict(x) if isinstance(x, dict) and not isinstance(x, dotdict) else x for x in v]

    def __getattr__(self, name: str) -> Any:
        try:
            v = self[name]
        except KeyError as e:
            raise AttributeError(name) from e
        if v == MISSING:
            raise MissingValueError(f"Missing required config value: '{name}' is '???'")
        return v

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = dotdict(value) if isinstance(value, dict) and not isinstance(value, dotdict) else value

    def __delattr__(self, name: str) -> None:
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __deepcopy__(self, memo):
        return dotdict({k: copy.deepcopy(v, memo) for k, v in self.items()})

    def as_dict(self) -> dict:
        def conv(v):
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, list):
                return [conv(x) for x in v]
            return v

        return conv(self)


# --------------------------------------------------------------------------- #
# merging / path helpers
# --------------------------------------------------------------------------- #
def deep_merge(dst: dict, src: dict) -> dict:
    """Merge ``src`` into ``dst`` (in place), later wins; dicts recurse."""
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            deep_merge(dst[k], v)
        else:
            dst[k] = copy.deepcopy(v)
    return dst


def _set_path(cfg: dict, path: str, value: Any) -> None:
    keys = path.split(".")
    node = cfg
    for k in keys[:-1]:
        if k not in node or not isinstance(node[k], dict):
            node[k] = {}
        node = node[k]
    node[keys[-1]] = value


def _get_path(cfg: dict, path: str) -> Any:
    node: Any = cfg
    for k in path.split("."):
        if isinstance(node, (list, tuple)):
            node = node[int(k)]
        elif isinstance(node, dict):
            node = node[k]
        else:
            raise KeyError(path)
    return node


def _del_path(cfg: dict, path: str) -> None:
    keys = path.split(".")
    node = cfg
    for k in keys[:-1]:
        node = node[k]
    del node[keys[-1]]


# --------------------------------------------------------------------------- #
# interpolation
# --------------------------------------------------------------------------- #
def _resolve_value(expr: str, root: dict, stack: Tuple[str, ...]) -> Any:
    expr = expr.strip()
    if expr.startswith("now:"):
        return datetime.datetime.now().strftime(expr[4:])
    if expr.startswith("oc.env:") or expr.startswith("env:"):
        body = expr.split(":", 1)[1]
        # OmegaConf-compatible comma default first — the default itself may
        # contain colons (URIs): ${oc.env:VAR,http://host:5000}
        if "," in body.split(":", 1)[0]:
            name, _, raw_default = body.partition(",")
            return os.environ.get(name, yaml_load(raw_default))
        name, sep, default = body.partition(":")
        return os.environ.get(name, default if sep else "")
    if expr.startswith("eval:"):
        # restricted arithmetic resolver, used e.g. for derived sizes
        return eval(expr[5:], {"__builtins__": {}}, {})  # noqa: S307
    if expr in stack:
        raise ConfigError(f"Interpolation cycle at '${{{expr}}}' via {stack}")
    try:
        val = _get_path(root, expr)
    except (KeyError, IndexError, ValueError) as e:
        raise ConfigError(f"Interpolation '${{{expr}}}' not found") from e
    return _resolve_node(val, root, stack + (expr,))


def _resolve_node(val: Any, root: dict, stack: Tuple[str, ...] = ()) -> Any:
    if isinstance(val, str):
        m = _INTERP_RE.fullmatch(val.strip())
        if m:  # whole-string interpolation preserves type
            return _resolve_value(m.group(1), root, stack)

        def sub(match: "re.Match[str]") -> str:
            return str(_resolve_value(match.group(1), root, stack))

        out, n = _INTERP_RE.subn(sub, val)
        # handle nested ${a${b}} by iterating until fixpoint (bounded)
        for _ in range(10):
            if not _INTERP_RE.search(out):
                break
            out2 = _INTERP_RE.sub(sub, out)
            if out2 == out:
                break
            out = out2
        return out
    return val


def resolve(cfg: dict, root: Optional[dict] = None) -> dict:
    """Recursively resolve all interpolations; returns a new tree."""
    root = root if root is not None else cfg

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return _resolve_node(node, root)

    return walk(cfg)


# --------------------------------------------------------------------------- #
# composition engine
# --------------------------------------------------------------------------- #
class Composer:
    """Compose a config tree from a config dir, hydra-defaults style."""

    def __init__(self, config_dirs: Sequence[Path]):
        self.config_dirs = [Path(d) for d in config_dirs]
        self._cli_keys: set = set()
        # hydra's package-qualified CLI selections, e.g.
        # ``logger@metric.logger=mlflow`` / ``optim@algo.actor.optimizer=sgd``:
        # {(group, absolute_package): option}. Matched entries are tracked so
        # a typo'd package errors instead of silently doing nothing.
        self._pkg_selections: Dict[Tuple[str, str], str] = {}
        self._pkg_matched: set = set()
        self._load_cache: Dict[str, Tuple[dict, str]] = {}

    # -- file loading ------------------------------------------------------ #
    def _find(self, rel: str) -> Optional[Path]:
        rel = rel if rel.endswith((".yaml", ".yml")) else rel + ".yaml"
        for d in self.config_dirs:
            p = d / rel
            if p.exists():
                return p
        return None

    def _load(self, rel: str) -> Tuple[dict, str]:
        """Return (raw-yaml-dict, package-directive). Parses each file once
        per composer (the mount prediction and the two composition passes
        re-read files); callers get a fresh deep copy since composition
        mutates the dict (defaults pop, merges)."""
        if rel not in self._load_cache:
            p = self._find(rel)
            if p is None:
                raise ConfigError(
                    f"Config file '{rel}' not found in {[str(d) for d in self.config_dirs]}"
                )
            text = p.read_text()
            pkg = "_group_"
            for line in text.splitlines()[:5]:
                m = re.match(r"#\s*@package\s+(\S+)", line.strip())
                if m:
                    pkg = m.group(1)
                    break
            data = yaml_load(text) or {}
            if not isinstance(data, dict):
                raise ConfigError(f"Config file '{rel}' must contain a mapping")
            self._load_cache[rel] = (data, pkg)
        data, pkg = self._load_cache[rel]
        return copy.deepcopy(data), pkg

    def _peek_pkg(self, rel: str) -> str:
        """The file's @package header only — no dict copy."""
        if rel not in self._load_cache:
            self._load(rel)
        return self._load_cache[rel][1]

    # -- defaults handling ------------------------------------------------- #
    @staticmethod
    def _parse_default(entry: Any) -> Tuple[str, Optional[str], bool]:
        """Normalize a defaults entry -> (group_expr, option, is_override)."""
        if isinstance(entry, str):
            return entry, None, False
        if isinstance(entry, dict) and len(entry) == 1:
            (key, option), = entry.items()
            key = str(key).strip()
            is_override = False
            if key.startswith("override "):
                is_override = True
                key = key[len("override "):].strip()
            return key, (None if option is None else str(option)), is_override
        raise ConfigError(f"Bad defaults entry: {entry!r}")

    def _compose_file(
        self,
        rel: str,
        group_prefix: str,
        selections: Dict[str, str],
        mount_prefix: str = "",
    ) -> Tuple[dict, str]:
        """Compose one file with its own defaults list. Returns (tree, pkg).

        ``mount_prefix`` is the absolute package path this file's tree lands
        at ("" for the root / ``_global_`` files) — package-qualified CLI
        selections are matched against it."""
        data, pkg = self._load(rel)
        defaults = data.pop("defaults", None)
        own = data  # content of the file itself (post-defaults-pop)

        if defaults is None:
            return copy.deepcopy(own), pkg

        result: dict = {}
        self_merged = False
        for entry in defaults:
            group_expr, option, is_override = self._parse_default(entry)
            if group_expr == "_self_":
                deep_merge(result, own)
                self_merged = True
                continue
            if option is None and not is_override:
                # bare string entry: include a sibling file of the same group
                # (e.g. `- default` inside algo/ppo.yaml -> algo/default.yaml)
                inc = f"{group_prefix}/{group_expr}" if group_prefix else group_expr
                sub_tree, _ = self._compose_file(inc, group_prefix, selections, mount_prefix)
                deep_merge(result, sub_tree)
                continue
            if is_override:
                # overrides re-select a previously chosen group option; they
                # take effect on the second composition pass (CLI wins)
                key = group_expr.lstrip("/")
                if key not in self._cli_keys:
                    selections[key] = option or ""
                continue

            # group@package syntax
            if "@" in group_expr:
                group, package = group_expr.split("@", 1)
            else:
                group, package = group_expr, None
            group = group.strip()
            absolute = group.startswith("/")
            group_path = group.lstrip("/") if absolute else (
                f"{group_prefix}/{group}" if group_prefix else group
            )
            group_key = group.lstrip("/")
            # CLI/override selection beats the file's default option
            chosen = selections.get(group_key, option)
            if chosen in (None, ""):
                chosen = option
            # package-qualified CLI selection (``group@abs.package=option``)
            # beats everything: it names one specific mount of the group, so
            # e.g. ``optim@algo.actor.optimizer=sgd`` swaps the actor's
            # optimizer without touching the world model's or the critic's
            local_pkg = package if package is not None else group_key.replace("/", ".")
            abs_pkg = f"{mount_prefix}.{local_pkg}" if mount_prefix else local_pkg
            pkg_sel = self._pkg_selections.get((group_key, abs_pkg))
            if pkg_sel is not None:
                chosen = pkg_sel
                self._pkg_matched.add((group_key, abs_pkg))
            if chosen == MISSING or chosen is None:
                if group_key in selections and selections[group_key] not in (None, "", MISSING):
                    chosen = selections[group_key]
                else:
                    raise ConfigError(
                        f"You must specify '{group_key}=<option>' (required group, e.g. 'exp=ppo')"
                    )
            chosen = str(chosen)
            if chosen.endswith((".yaml", ".yml")):
                chosen = chosen.rsplit(".", 1)[0]
            sub_rel = f"{group_path}/{chosen}"
            # predict the mount before recursing so the subtree knows its own
            # absolute package (only the sub-file's @package header is needed)
            if package is not None:
                mount = None if package in ("_global_",) else package
            elif self._peek_pkg(sub_rel) == "_global_":
                mount = None
            else:
                mount = group_key.replace("/", ".")
            child_prefix = (
                mount_prefix if mount is None
                else (f"{mount_prefix}.{mount}" if mount_prefix else mount)
            )
            sub_tree, _ = self._compose_file(sub_rel, group_path, selections, child_prefix)
            if mount is None:
                deep_merge(result, sub_tree)
            else:
                node = result
                for part in mount.split("."):
                    node = node.setdefault(part, {})
                deep_merge(node, sub_tree)
        if not self_merged:
            deep_merge(result, own)
        return result, pkg


def _parse_cli_value(raw: str) -> Any:
    try:
        return yaml_load(raw)
    except yaml.YAMLError:
        return raw


def compose(
    config_name: str = "config",
    overrides: Optional[Sequence[str]] = None,
    config_dirs: Optional[Sequence[str]] = None,
    do_resolve: bool = True,
) -> dotdict:
    """Compose the full config. Equivalent of @hydra.main + OmegaConf.resolve.

    ``overrides`` accepts hydra-style strings: ``exp=ppo``,
    ``algo.total_steps=1024``, ``+extra.key=1``, ``~metric.aggregator``.
    Extra search dirs come from ``SHEEPRL_SEARCH_PATH`` (``;``-separated,
    ``file://`` prefixes allowed) mirroring the reference's hydra plugin
    (hydra_plugins/sheeprl_search_path.py:10-33).
    """
    overrides = list(overrides or [])
    dirs: List[Path] = [Path(d) for d in (config_dirs or [])]
    default_dir = Path(__file__).resolve().parent.parent / "configs"
    if default_dir not in dirs:
        dirs.append(default_dir)
    sp = os.environ.get("SHEEPRL_SEARCH_PATH", "")
    for tok in filter(None, sp.split(";")):
        tok = tok.strip()
        if tok.startswith("file://"):
            tok = tok[len("file://"):]
        elif tok.startswith("pkg://"):
            mod = tok[len("pkg://"):].replace("/", ".")
            try:
                m = importlib.import_module(mod)
                tok = str(Path(m.__file__).parent)
            except Exception:
                continue
        dirs.insert(0, Path(tok))

    composer = Composer(dirs)

    # split overrides into group selections vs value sets
    selections: Dict[str, str] = {}
    sets: List[Tuple[str, Any]] = []
    adds: List[Tuple[str, Any]] = []
    dels: List[str] = []
    for ov in overrides:
        if ov.startswith("~"):
            dels.append(ov[1:])
            continue
        if "=" not in ov:
            raise ConfigError(f"Bad override '{ov}' (expected key=value)")
        key, raw = ov.split("=", 1)
        add = key.startswith("+")
        key = key.lstrip("+")
        # package-qualified group selection (hydra syntax), e.g.
        # ``logger@metric.logger=mlflow``: <group>@<absolute.package>=<option>
        if "@" in key and not add:
            grp, package = key.split("@", 1)
            if "." not in grp and any((d / grp).is_dir() for d in composer.config_dirs):
                if composer._find(f"{grp}/{raw}") is None:
                    raise ConfigError(
                        f"Override '{ov}': group '{grp}' has no option '{raw}' "
                        f"(no {grp}/{raw}.yaml on the search path)"
                    )
                composer._pkg_selections[(grp, package)] = raw
                continue
        # group selection iff a matching option file exists
        if "." not in key and composer._find(f"{key}/{raw}") is not None:
            selections[key] = raw
            continue
        (adds if add else sets).append((key, _parse_cli_value(raw)))

    # Two passes: pass 1 walks the defaults tree so nested `override /group:`
    # entries (e.g. in exp files) land in `selections`; pass 2 composes with
    # the final selection map. CLI selections always win.
    composer._cli_keys = set(selections)
    composer._compose_file(config_name, "", selections)
    # pass 1 may match package selections against mounts that only exist
    # under pre-override selections — only pass 2 (the final tree) counts
    composer._pkg_matched.clear()
    tree, _ = composer._compose_file(config_name, "", selections)
    unmatched = set(composer._pkg_selections) - composer._pkg_matched
    if unmatched:
        grp, package = sorted(unmatched)[0]
        raise ConfigError(
            f"Override '{grp}@{package}={composer._pkg_selections[(grp, package)]}' "
            f"matched no defaults entry: no '{grp}' group is mounted at package "
            f"'{package}' in the composed tree"
        )
    for key, val in sets + adds:
        _set_path(tree, key, val)
    for key in dels:
        try:
            _del_path(tree, key)
        except KeyError:
            pass
    if do_resolve:
        tree = resolve(tree)
    return dotdict(tree)


# --------------------------------------------------------------------------- #
# instantiate (_target_), hydra.utils.instantiate equivalent
# --------------------------------------------------------------------------- #
def _locate(path: str) -> Any:
    parts = path.split(".")
    for i in range(len(parts), 0, -1):
        try:
            mod = importlib.import_module(".".join(parts[:i]))
        except ImportError:
            continue
        obj = mod
        try:
            for p in parts[i:]:
                obj = getattr(obj, p)
        except AttributeError:
            continue
        return obj
    raise ImportError(f"Cannot locate '{path}'")


def instantiate(node: Any, *args, **overrides) -> Any:
    """Instantiate a ``_target_`` config node (recursively).

    Supports ``_partial_: true`` (returns functools.partial) and
    ``_args_`` positional arguments, like hydra.utils.instantiate.
    """
    import functools

    if isinstance(node, (list, tuple)):
        return type(node)(instantiate(x) for x in node)
    if not isinstance(node, dict):
        return node
    if "_target_" not in node:
        return {k: instantiate(v) for k, v in node.items()}
    node = dict(node)
    target = node.pop("_target_")
    partial = bool(node.pop("_partial_", False))
    pos = list(node.pop("_args_", [])) + list(args)
    kwargs = {k: instantiate(v) for k, v in node.items()}
    kwargs.update(overrides)
    fn = _locate(target) if isinstance(target, str) else target
    if partial:
        return functools.partial(fn, *pos, **kwargs)
    return fn(*pos, **kwargs)


def validate_no_missing(cfg: dict, path: str = "") -> List[str]:
    """Return key-paths whose value is the ``???`` marker."""
    missing = []
    for k, v in cfg.items():
        p = f"{path}.{k}" if path else str(k)
        if isinstance(v, dict):
            missing.extend(validate_no_missing(v, p))
        elif v == MISSING:
            missing.append(p)
    return missing
