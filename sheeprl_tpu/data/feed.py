"""Host→HBM streaming: double-buffered device prefetch.

The reference hides host→device latency in CUDA's async copy semantics;
on TPU we overlap explicitly: a background thread samples from the (host,
numpy) replay buffer and ``jax.device_put``s the next batch while the
current one trains (SURVEY.md §7 "host/device pipeline", BASELINE north
star "host→HBM streaming with device-side prefetch").
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import jax

from sheeprl_tpu.obs.trace import trace_scope


def batched_feed(
    local_data: Dict[str, Any], n_batches: int, depth: int = 2, sharding: Any = None
) -> "DevicePrefetcher":
    """Prefetcher over the leading (n_samples) axis of a sampled buffer dict:
    yields ``n_batches`` batches, each ``device_put`` on the worker thread
    so the host->HBM copy of batch i+1 overlaps gradient step i. uint8
    image data stays uint8 (4x less host memory traffic and upload; the
    jitted train steps normalize on device); everything else is float32.

    Drop-in for the Dreamer-family gradient-step loops' per-step
    ``jnp.asarray(v[i])`` conversion.  Pass ``sharding`` (e.g.
    ``runtime.batch_sharding(axis=1)``) so multi-device runs place each
    device's batch columns directly — an unsharded device_put lands
    replicated and the train step computes redundantly on every device."""
    import numpy as np

    counter = iter(range(n_batches))

    def producer() -> Optional[Dict[str, Any]]:
        i = next(counter, None)
        if i is None:
            return None
        return {
            k: np.asarray(v[i]) if getattr(v, "dtype", None) == np.uint8 else np.asarray(v[i], dtype=np.float32)
            for k, v in local_data.items()
        }

    return DevicePrefetcher(producer, sharding=sharding, depth=depth)


class DevicePrefetcher:
    """Iterator wrapping a batch-producing callable with an N-deep device
    prefetch queue.

    ``producer()`` must return a pytree of numpy arrays (or None to stop).
    Batches are ``device_put`` on the worker thread so the accelerator copy
    overlaps the training step.
    """

    def __init__(
        self,
        producer: Callable[[], Optional[Dict[str, Any]]],
        sharding: Any = None,
        depth: int = 2,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._producer = producer
        self._sharding = sharding
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._worker, name="sheeprl-prefetcher", daemon=True)
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        self._leak_token = leak_registry.register(
            "thread", "sheeprl-prefetcher", self._thread, where="DevicePrefetcher"
        )
        self._thread.start()

    def _worker(self) -> None:
        try:
            while not self._stop.is_set():
                batch = self._producer()
                if batch is None:
                    self._queue.put(None)
                    return
                # named span in any active profiler trace: upload stalls of
                # the replay feed show on the worker thread's timeline
                with trace_scope("host_to_device"):
                    if self._sharding is not None:
                        batch = jax.device_put(batch, self._sharding)
                    else:
                        batch = jax.device_put(batch)
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on next __next__
            self._error = e
            try:
                self._queue.put(None, timeout=0.1)
            except queue.Full:
                pass

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        while True:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            try:
                item = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                raise StopIteration
            return item

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        while not self._queue.empty():
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        from sheeprl_tpu.analysis.sanitizers import leak_registry

        leak_registry.unregister(getattr(self, "_leak_token", None))
        self._leak_token = None

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
