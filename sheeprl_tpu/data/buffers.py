"""Replay / rollout buffers: host-side dict-of-numpy, time-major (T, B, *).

TPU-native counterpart of reference sheeprl/data/buffers.py (ReplayBuffer:20,
SequentialReplayBuffer:363, EnvIndependentReplayBuffer:529, EpisodeBuffer:746,
get_tensor:1158). Storage and index math mirror the reference exactly —
wrap-around adds, next-obs validity at the write head, sequence start-index
windows — because those edge cases are battle-tested. What changes for TPU:

- ``get_array`` converts to ``jax.Array`` (``jax.device_put``) instead of
  torch tensors, with the int64→int32 / float64→float32 TPU dtype mapping;
- ``sample_arrays`` returns a pytree ready for ``device_put`` / donation;
- asynchronous host→HBM streaming lives in sheeprl_tpu/data/feed.py
  (double-buffered prefetch), not here.
"""

from __future__ import annotations

import logging
import os
import shutil
import uuid
from itertools import compress
from pathlib import Path
from typing import Any, Dict, Optional, Sequence, Type, Union

import numpy as np

from sheeprl_tpu.utils.memmap import MemmapArray
from sheeprl_tpu.utils.utils import NUMPY_TO_JAX_DTYPE

_VALID_MEMMAP_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")


def get_array(
    array: Union[np.ndarray, MemmapArray],
    dtype: Any = None,
    clone: bool = False,
    device: Any = None,
):
    """numpy/Memmap -> jax.Array with the TPU dtype map (ref get_tensor:1158)."""
    import jax
    import jax.numpy as jnp

    if isinstance(array, MemmapArray):
        array = array.array
    if clone:
        array = np.array(array)
    else:
        array = np.asarray(array)
    if dtype is None:
        dtype = NUMPY_TO_JAX_DTYPE.get(array.dtype, None)
    out = jnp.asarray(array, dtype=dtype)
    if device is not None:
        out = jax.device_put(out, device)
    return out


class ReplayBuffer:
    """Circular dict-of-arrays buffer, shapes (buffer_size, n_envs, *)."""

    batch_axis: int = 1

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        memmap_mode: str = "r+",
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        self._buf: Dict[str, Union[np.ndarray, MemmapArray]] = {}
        if self._memmap:
            if self._memmap_mode not in _VALID_MEMMAP_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_VALID_MEMMAP_MODES}")
            if self._memmap_dir is None:
                raise ValueError("memmap=True requires 'memmap_dir' to be set")
            self._memmap_dir = Path(self._memmap_dir)
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._pos = 0
        self._full = False
        self._rng: np.random.Generator = np.random.default_rng()

    # ------------------------------------------------------------------ #
    @property
    def buffer(self) -> Dict[str, np.ndarray]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> bool:
        return self._full

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> bool:
        return len(self._buf) == 0

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # ------------------------------------------------------------------ #
    @staticmethod
    def _validate(data: Dict[str, np.ndarray]) -> None:
        if not isinstance(data, dict):
            raise ValueError(f"'data' must be a dict of numpy arrays, got {type(data)}")
        shapes = {}
        for k, v in data.items():
            if not isinstance(v, np.ndarray):
                raise ValueError(f"'data[{k}]' must be a numpy array, got {type(v)}")
            if v.ndim < 2:
                raise RuntimeError(
                    f"'data' must have at least 2 dims [sequence_length, n_envs, ...]; '{k}' has shape {v.shape}"
                )
            shapes[k] = v.shape[:2]
        if len(set(shapes.values())) > 1:
            raise RuntimeError(f"Arrays in 'data' must agree in the first 2 dims, got {shapes}")

    def add(self, data: Union["ReplayBuffer", Dict[str, np.ndarray]], validate_args: bool = False) -> None:
        """Insert (T, n_envs, *) rows at the write head, wrapping circularly."""
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            self._validate(data)
        data_len = next(iter(data.values())).shape[0]
        next_pos = (self._pos + data_len) % self._buffer_size
        if next_pos <= self._pos or (data_len > self._buffer_size and not self._full):
            idxes = np.concatenate(
                [np.arange(self._pos, self._buffer_size), np.arange(0, next_pos)]
            ).astype(np.intp)
        else:
            idxes = np.arange(self._pos, next_pos, dtype=np.intp)
        if data_len > self._buffer_size:
            # keep only the most recent buffer_size rows (+ the wrapped tail)
            data = {k: v[-self._buffer_size - next_pos:] for k, v in data.items()}
        if self.empty:
            for k, v in data.items():
                shape = (self._buffer_size, self._n_envs, *v.shape[2:])
                if self._memmap:
                    self._buf[k] = MemmapArray(
                        filename=Path(self._memmap_dir) / f"{k}.memmap",
                        dtype=v.dtype,
                        shape=shape,
                        mode=self._memmap_mode,
                    )
                else:
                    self._buf[k] = np.empty(shape, dtype=v.dtype)
        for k, v in data.items():
            self._buf[k][idxes] = v
        if self._pos + data_len >= self._buffer_size:
            self._full = True
        self._pos = next_pos

    # ------------------------------------------------------------------ #
    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Uniform sample -> dict of (n_samples, batch_size, *).

        When ``sample_next_obs`` the row at the write head is excluded since
        its next-obs would be stale (see reference sample:223 and the SB3
        discussion it links).
        """
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer, call 'add' first")
        if self._full:
            first_range_end = self._pos - 1 if sample_next_obs else self._pos
            second_range_end = (
                self._buffer_size if first_range_end >= 0 else self._buffer_size + first_range_end
            )
            valid = np.concatenate(
                [np.arange(0, first_range_end), np.arange(self._pos, second_range_end)]
            ).astype(np.intp)
            batch_idxes = valid[self._rng.integers(0, len(valid), size=(batch_size * n_samples,))]
        else:
            max_pos = self._pos - 1 if sample_next_obs else self._pos
            if max_pos == 0:
                raise RuntimeError(
                    "Cannot sample next observations with a single transition in the buffer"
                )
            batch_idxes = self._rng.integers(0, max_pos, size=(batch_size * n_samples,), dtype=np.intp)
        out = self._get_samples(batch_idxes, sample_next_obs=sample_next_obs, clone=clone)
        out = {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in out.items()}
        # fault site (resilience/faults.py): scribble this replay batch
        # with garbage — silent data corruption reaching the learner, the
        # adversary the training sentinel's z-score monitor must catch
        from sheeprl_tpu.resilience.faults import fault_arg, fault_point

        if fault_point("rb_corrupt"):
            scale = fault_arg("rb_corrupt") or 1e8
            for k, v in out.items():
                if v.dtype.kind == "f":
                    # copy first: the views may alias the live buffer
                    noise = self._rng.standard_normal(v.shape).astype(v.dtype)
                    out[k] = np.asarray(noise * v.dtype.type(scale))
        return out

    def _get_samples(
        self, batch_idxes: np.ndarray, sample_next_obs: bool = False, clone: bool = False
    ) -> Dict[str, np.ndarray]:
        if self.empty:
            raise RuntimeError("The buffer has not been initialized, add data first")
        env_idxes = self._rng.integers(0, self._n_envs, size=(len(batch_idxes),), dtype=np.intp)
        flat = (batch_idxes * self._n_envs + env_idxes).ravel()
        if sample_next_obs:
            flat_next = (((batch_idxes + 1) % self._buffer_size) * self._n_envs + env_idxes).ravel()
        samples: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = np.asarray(v)
            flat_v = arr.reshape(-1, *arr.shape[2:])
            samples[k] = np.take(flat_v, flat, axis=0)
            if clone:
                samples[k] = samples[k].copy()
            if sample_next_obs and k in self._obs_keys:
                samples[f"next_{k}"] = np.take(flat_v, flat_next, axis=0)
                if clone:
                    samples[f"next_{k}"] = samples[f"next_{k}"].copy()
        return samples

    def sample_arrays(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        dtype: Any = None,
        device: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """sample() then convert to jax arrays (reference sample_tensors:291)."""
        samples = self.sample(
            batch_size=batch_size,
            sample_next_obs=sample_next_obs,
            clone=clone,
            n_samples=n_samples,
            **kwargs,
        )
        return {k: get_array(v, dtype=dtype, device=device) for k, v in samples.items()}

    def to_arrays(self, dtype: Any = None, clone: bool = False, device: Any = None) -> Dict[str, Any]:
        """Whole-buffer conversion (reference to_tensor:109)."""
        return {k: get_array(v, dtype=dtype, clone=clone, device=device) for k, v in self._buf.items()}

    # ------------------------------------------------------------------ #
    def __getitem__(self, key: str) -> Union[np.ndarray, MemmapArray]:
        if not isinstance(key, str):
            raise TypeError("'key' must be a string")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized, add data first")
        return self._buf.get(key)

    def __setitem__(self, key: str, value: Union[np.ndarray, MemmapArray]) -> None:
        if not isinstance(value, (np.ndarray, MemmapArray)):
            raise ValueError(f"value must be np.ndarray or MemmapArray, got {type(value)}")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized, add data first")
        if tuple(value.shape[:2]) != (self._buffer_size, self._n_envs):
            raise RuntimeError(
                f"'value' must have leading dims (buffer_size, n_envs), got {value.shape}"
            )
        if self._memmap:
            filename = (
                value.filename
                if isinstance(value, MemmapArray)
                else Path(self._memmap_dir) / f"{key}.memmap"
            )
            self._buf[key] = MemmapArray.from_array(value, filename=filename, mode=self._memmap_mode)
        else:
            self._buf[key] = np.copy(value.array if isinstance(value, MemmapArray) else value)

    def __getstate__(self):
        state = self.__dict__.copy()
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)


class SequentialReplayBuffer(ReplayBuffer):
    """Samples contiguous sequences (n_samples, seq_len, batch, *), ignoring
    episode boundaries; wrap-around-safe start windows (ref sample:395-465)."""

    batch_axis: int = 2

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        batch_dim = batch_size * n_samples
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer, call 'add' first")
        if not self._full and self._pos - sequence_length + 1 < 1:
            raise ValueError(
                f"Cannot sample a sequence of length {sequence_length}. Data added so far: {self._pos}"
            )
        if self._full and sequence_length > self._buffer_size:
            raise ValueError(
                f"The sequence length ({sequence_length}) is greater than the buffer size ({self._buffer_size})"
            )

        if self._full:
            # valid starts: [0, pos - L] plus [pos, buffer_size) minus wrapped
            # tail that would cross the write head
            first_range_end = self._pos - sequence_length + 1
            second_range_end = (
                self._buffer_size if first_range_end >= 0 else self._buffer_size + first_range_end
            )
            valid = np.concatenate(
                [np.arange(0, max(first_range_end, 0)), np.arange(self._pos, second_range_end)]
            ).astype(np.intp)
            start_idxes = valid[self._rng.integers(0, len(valid), size=(batch_dim,))]
        else:
            start_idxes = self._rng.integers(
                0, self._pos - sequence_length + 1, size=(batch_dim,), dtype=np.intp
            )
        chunk = np.arange(sequence_length, dtype=np.intp)[None, :]
        idxes = (start_idxes[:, None] + chunk) % self._buffer_size
        return self._get_seq_samples(
            idxes, batch_size, n_samples, sequence_length, sample_next_obs=sample_next_obs, clone=clone
        )

    def _get_seq_samples(
        self,
        batch_idxes: np.ndarray,
        batch_size: int,
        n_samples: int,
        sequence_length: int,
        sample_next_obs: bool = False,
        clone: bool = False,
    ) -> Dict[str, np.ndarray]:
        flat_batch_idxes = batch_idxes.ravel()
        # each sequence stays within one env
        if self._n_envs == 1:
            env_idxes = np.zeros(flat_batch_idxes.shape[0], dtype=np.intp)
        else:
            env_idxes = self._rng.integers(0, self._n_envs, size=(batch_size * n_samples,), dtype=np.intp)
            env_idxes = np.repeat(env_idxes, sequence_length)
        flat = (flat_batch_idxes * self._n_envs + env_idxes).ravel()
        samples: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = np.asarray(v)
            flat_v = arr.reshape(-1, *arr.shape[2:])
            taken = np.take(flat_v, flat, axis=0)
            batched = taken.reshape(n_samples, batch_size, sequence_length, *taken.shape[1:])
            samples[k] = np.swapaxes(batched, 1, 2)
            if clone:
                samples[k] = samples[k].copy()
            if sample_next_obs:
                flat_next = (((flat_batch_idxes + 1) % self._buffer_size) * self._n_envs + env_idxes).ravel()
                taken_n = np.take(flat_v, flat_next, axis=0)
                batched_n = taken_n.reshape(n_samples, batch_size, sequence_length, *taken_n.shape[1:])
                samples[f"next_{k}"] = np.swapaxes(batched_n, 1, 2)
                if clone:
                    samples[f"next_{k}"] = samples[f"next_{k}"].copy()
        return samples


class EnvIndependentReplayBuffer:
    """One sub-buffer per environment (ref EnvIndependentReplayBuffer:529):
    per-env memmap subdirs, routed adds, multinomial sample split."""

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        memmap_mode: str = "r+",
        buffer_cls: Type[ReplayBuffer] = ReplayBuffer,
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap:
            if memmap_mode not in _VALID_MEMMAP_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_VALID_MEMMAP_MODES}")
            if memmap_dir is None:
                raise ValueError("memmap=True requires 'memmap_dir' to be set")
            memmap_dir = Path(memmap_dir)
        self._buf: Sequence[ReplayBuffer] = [
            buffer_cls(
                buffer_size=buffer_size,
                n_envs=1,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=memmap_dir / f"env_{i}" if memmap else None,
                memmap_mode=memmap_mode,
                **kwargs,
            )
            for i in range(n_envs)
        ]
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._rng: np.random.Generator = np.random.default_rng()
        self._concat_along_axis = buffer_cls.batch_axis

    @property
    def buffer(self) -> Sequence[ReplayBuffer]:
        return tuple(self._buf)

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> Sequence[bool]:
        return tuple(b.full for b in self._buf)

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> Sequence[bool]:
        return tuple(b.empty for b in self._buf)

    @property
    def is_memmap(self) -> Sequence[bool]:
        return tuple(b.is_memmap for b in self._buf)

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)
        for i, b in enumerate(self._buf):
            b.seed(None if seed is None else seed + i)

    def add(
        self,
        data: Union[ReplayBuffer, Dict[str, np.ndarray]],
        indices: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if indices is None:
            indices = tuple(range(self._n_envs))
        elif len(indices) != next(iter(data.values())).shape[1]:
            raise ValueError(
                f"The length of 'indices' ({len(indices)}) must equal the envs dim of 'data' "
                f"({next(iter(data.values())).shape[1]})"
            )
        for data_idx, env_idx in enumerate(indices):
            env_data = {k: v[:, data_idx: data_idx + 1] for k, v in data.items()}
            self._buf[env_idx].add(env_data, validate_args=validate_args)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        bs_per_buf = np.bincount(self._rng.integers(0, self._n_envs, (batch_size,)))
        per_buf = [
            b.sample(batch_size=bs, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs)
            for b, bs in zip(self._buf, bs_per_buf)
            if bs > 0
        ]
        samples: Dict[str, np.ndarray] = {}
        for k in per_buf[0].keys():
            samples[k] = np.concatenate([s[k] for s in per_buf], axis=self._concat_along_axis)
        return samples

    def sample_arrays(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        dtype: Any = None,
        device: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(
            batch_size=batch_size,
            sample_next_obs=sample_next_obs,
            clone=clone,
            n_samples=n_samples,
            **kwargs,
        )
        return {k: get_array(v, dtype=dtype, device=device) for k, v in samples.items()}


class EpisodeBuffer:
    """Whole-episode store with per-episode (optionally memmapped) dirs,
    minimum-length validation, oldest-episode eviction and prioritize_ends
    sampling (ref EpisodeBuffer:746)."""

    batch_axis: int = 2

    def __init__(
        self,
        buffer_size: int,
        minimum_episode_length: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        prioritize_ends: bool = False,
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        memmap_mode: str = "r+",
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if minimum_episode_length <= 0:
            raise ValueError(
                f"The sequence length must be greater than zero, got: {minimum_episode_length}"
            )
        if buffer_size < minimum_episode_length:
            raise ValueError(
                f"The sequence length must be lower than the buffer size, got: bs = {buffer_size} "
                f"and sl = {minimum_episode_length}"
            )
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._buffer_size = buffer_size
        self._minimum_episode_length = minimum_episode_length
        self._prioritize_ends = prioritize_ends
        self._open_episodes: list = [[] for _ in range(n_envs)]
        self._cum_lengths: list = []
        self._buf: list = []
        self._rng: np.random.Generator = np.random.default_rng()
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        if self._memmap:
            if self._memmap_mode not in _VALID_MEMMAP_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_VALID_MEMMAP_MODES}")
            if self._memmap_dir is None:
                raise ValueError("memmap=True requires 'memmap_dir' to be set")
            self._memmap_dir = Path(self._memmap_dir)
            self._memmap_dir.mkdir(parents=True, exist_ok=True)

    # ------------------------------------------------------------------ #
    @property
    def prioritize_ends(self) -> bool:
        return self._prioritize_ends

    @prioritize_ends.setter
    def prioritize_ends(self, value: bool) -> None:
        self._prioritize_ends = value

    @property
    def buffer(self) -> Sequence[Dict[str, np.ndarray]]:
        return self._buf

    @property
    def obs_keys(self) -> Sequence[str]:
        return self._obs_keys

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def minimum_episode_length(self) -> int:
        return self._minimum_episode_length

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def full(self) -> bool:
        return (
            self._cum_lengths[-1] + self._minimum_episode_length > self._buffer_size
            if len(self._buf) > 0
            else False
        )

    def __len__(self) -> int:
        return self._cum_lengths[-1] if len(self._buf) > 0 else 0

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # ------------------------------------------------------------------ #
    def add(
        self,
        data: Union[ReplayBuffer, Dict[str, np.ndarray]],
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        """Split incoming (T, n_envs, *) chunks on done boundaries into
        per-env open episodes; closed episodes are validated and stored."""
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            ReplayBuffer._validate(data)
            if "terminated" not in data and "truncated" not in data:
                raise RuntimeError(
                    f"The episode must contain the `terminated` and the `truncated` keys, got: {data.keys()}"
                )
            if env_idxes is not None and (np.asarray(env_idxes) >= self._n_envs).any():
                raise ValueError(
                    f"The env indices must be in [0, {self._n_envs}), given {env_idxes}"
                )
        if env_idxes is None:
            env_idxes = range(self._n_envs)
        for i, env in enumerate(env_idxes):
            env_data = {k: v[:, i] for k, v in data.items()}
            done = np.logical_or(env_data["terminated"], env_data["truncated"])
            ends = done.nonzero()[0].tolist()
            if len(ends) == 0:
                self._open_episodes[env].append(env_data)
                continue
            ends.append(len(done))
            start = 0
            for ep_end in ends:
                episode = {k: env_data[k][start: ep_end + 1] for k in env_data}
                if len(np.logical_or(episode["terminated"], episode["truncated"])) > 0:
                    self._open_episodes[env].append(episode)
                start = ep_end + 1
                open_ep = self._open_episodes[env]
                if open_ep and bool(
                    np.logical_or(open_ep[-1]["terminated"][-1], open_ep[-1]["truncated"][-1])
                ):
                    self._save_episode(open_ep)
                    self._open_episodes[env] = []

    def _save_episode(self, episode_chunks: Sequence[Dict[str, np.ndarray]]) -> None:
        if len(episode_chunks) == 0:
            raise RuntimeError("Invalid episode: an empty sequence was given")
        episode = {
            k: np.concatenate([c[k] for c in episode_chunks], axis=0) for k in episode_chunks[0]
        }
        ends = np.logical_or(episode["terminated"], episode["truncated"])
        ep_len = ends.shape[0]
        if len(ends.nonzero()[0]) != 1 or not ends[-1]:
            raise RuntimeError("The episode must contain exactly one done, at its last step")
        if ep_len < self._minimum_episode_length:
            raise RuntimeError(
                f"Episode too short (at least {self._minimum_episode_length} steps), got: {ep_len} steps"
            )
        if ep_len > self._buffer_size:
            raise RuntimeError(f"Episode too long (at most {self._buffer_size} steps), got: {ep_len} steps")

        if self.full or len(self) + ep_len > self._buffer_size:
            cum = np.array(self._cum_lengths)
            mask = (len(self) - cum + ep_len) <= self._buffer_size
            last_to_remove = int(mask.argmax())
            if self._memmap and self._memmap_dir is not None:
                for _ in range(last_to_remove + 1):
                    first = self._buf[0]
                    dirname = os.path.dirname(str(next(iter(first.values())).filename))
                    self._buf.pop(0)
                    try:
                        shutil.rmtree(dirname)
                    except Exception as e:
                        logging.error(e)
            else:
                self._buf = self._buf[last_to_remove + 1:]
            cum = cum[last_to_remove + 1:] - cum[last_to_remove]
            self._cum_lengths = cum.tolist()
        self._cum_lengths.append(len(self) + ep_len)
        if self._memmap:
            ep_dir = self._memmap_dir / f"episode_{uuid.uuid4()}"
            ep_dir.mkdir(parents=True, exist_ok=True)
            stored = {}
            for k, v in episode.items():
                stored[k] = MemmapArray(
                    filename=str(ep_dir / f"{k}.memmap"), dtype=v.dtype, shape=v.shape, mode=self._memmap_mode
                )
                stored[k][:] = v
            episode = stored
        self._buf.append(episode)

    # ------------------------------------------------------------------ #
    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Sample fixed-length windows within episodes ->
        (n_samples, sequence_length, batch_size, *)."""
        if batch_size <= 0:
            raise ValueError(f"Batch size must be greater than 0, got: {batch_size}")
        if n_samples <= 0:
            raise ValueError(f"The number of samples must be greater than 0, got: {n_samples}")
        lengths = np.array(self._cum_lengths) - np.array([0] + self._cum_lengths[:-1])
        if sample_next_obs:
            valid_mask = lengths > sequence_length
        else:
            valid_mask = lengths >= sequence_length
        valid_episodes = list(compress(self._buf, valid_mask)) if len(self._buf) else []
        if len(valid_episodes) == 0:
            raise RuntimeError(
                "No valid episodes in the buffer: add at least one episode of length >= "
                f"{sequence_length}"
            )
        chunk = np.arange(sequence_length, dtype=np.intp)[None, :]
        n_per_ep = np.bincount(self._rng.integers(0, len(valid_episodes), (batch_size * n_samples,)))
        gathered: Dict[str, list] = {k: [] for k in valid_episodes[0].keys()}
        if sample_next_obs:
            gathered.update({f"next_{k}": [] for k in self._obs_keys})
        for i, n in enumerate(n_per_ep):
            if n == 0:
                continue
            ep = valid_episodes[i]
            ep_len = np.logical_or(np.asarray(ep["terminated"]), np.asarray(ep["truncated"])).shape[0]
            if sample_next_obs:
                ep_len -= 1
            upper = ep_len - sequence_length + 1
            if self._prioritize_ends:
                upper += sequence_length
            start_idxes = np.minimum(
                self._rng.integers(0, upper, size=(n,)).reshape(-1, 1),
                ep_len - sequence_length,
            ).astype(np.intp)
            indices = start_idxes + chunk
            for k in valid_episodes[0].keys():
                arr = np.asarray(ep[k])
                gathered[k].append(
                    np.take(arr, indices.ravel(), axis=0).reshape(n, sequence_length, *arr.shape[1:])
                )
                if sample_next_obs and k in self._obs_keys:
                    gathered[f"next_{k}"].append(arr[indices + 1])
        samples: Dict[str, np.ndarray] = {}
        for k, v in gathered.items():
            if len(v) > 0:
                samples[k] = np.moveaxis(
                    np.concatenate(v, axis=0).reshape(n_samples, batch_size, sequence_length, *v[0].shape[2:]),
                    2,
                    1,
                )
                if clone:
                    samples[k] = samples[k].copy()
        return samples

    def sample_arrays(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        dtype: Any = None,
        device: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(batch_size, sample_next_obs, n_samples, clone, sequence_length)
        return {k: get_array(v, dtype=dtype, device=device) for k, v in samples.items()}
