"""HBM-resident replay cache with on-device sequence sampling.

Why this exists (TPU-first design, no reference counterpart): the
reference's training loop re-reads every minibatch from a host-RAM buffer
(sheeprl dreamer_v3.py:628-641 samples torch tensors per gradient step),
which is free over PCIe but catastrophic over a remote-device link — on
the tunneled v5e used for this repo's benchmarks the host->HBM path moves
~10-14 MB/s, so a DV3-S batch (T=64, B=16 of 64x64x3 uint8 = 12.6 MB)
costs ~1 s per gradient step against a 16 ms train step (98% of the loop
is transfer).  The fix is to keep the replay window IN HBM: each policy
step uploads only the new frames (n_envs x ~12 KB), and sampling becomes
an on-device gather that feeds the jitted train step with zero host
round-trips.

Semantics mirror ``EnvIndependentReplayBuffer`` over
``SequentialReplayBuffer`` (data/buffers.py:299,387): one ring per env
with an independent write head, env chosen uniformly per batch element,
sequence starts uniform over the valid wrap-around-safe window (never
crossing the write head), windows contiguous within a single env.  The
host buffer stays the source of truth for checkpointing — this cache is
derived state, rebuilt from the host buffer on resume
(:meth:`load_from`).

Gating: ``buffer.device_cache`` (True / False / "auto"; env override
``SHEEPRL_DEVICE_CACHE``).  "auto" enables on single-device accelerator
meshes when the estimated footprint fits ``buffer.device_cache_budget_gb``
(default 6.0) — exactly the remote-link regime where it pays.  Multi-host
data parallelism keeps the host path (each process feeds its own shard).
Single-process multi-device meshes route to
:class:`ShardedDeviceReplayCache` — env-sharded rings over the mesh batch
axes — when opted in (``device_cache=True``) or whenever
``buffer.prioritized`` needs the device sampler: uniform draws stay
device-local (stratified), prioritized ones run per-shard sum-trees with
one psum'd total-mass reduction per draw (howto/sharding.md), for both
the sequence and flat-transition buffer families.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from sheeprl_tpu.utils.jax_compat import shard_map

__all__ = [
    "DeviceReplayCache",
    "ShardedDeviceReplayCache",
    "device_cache_setting",
    "maybe_create_for",
    "maybe_create_for_transitions",
    "sequence_batches",
]


# one ring array must stay gather-addressable with int32 linear offsets on
# TPU (2^31, with a 1 MiB margin); see DeviceReplayCache._ensure
_INT32_SAFE_BOUND = 2**31 - 2**20


def _store_dtype(dt) -> np.dtype:
    dt = np.dtype(dt)
    return np.dtype(np.float32) if dt == np.float64 else dt


def device_cache_setting(cfg) -> str:
    """Resolve ``buffer.device_cache`` with its env override to one of
    "on" / "off" / "auto"."""
    val = cfg.buffer.get("device_cache", "auto")
    env = os.environ.get("SHEEPRL_DEVICE_CACHE")
    if env is not None:
        val = env
    s = str(val).lower()
    if s in ("1", "true", "on", "yes"):
        return "on"
    if s in ("0", "false", "off", "no"):
        return "off"
    return "auto"


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("n_envs",))
def _append(bufs, row, pos, mask, *, n_envs):
    """Write one row per env at its own ring position, where mask says so.

    bufs: {k: (cap, n_envs, *feat)}; row: {k: (n_envs, *feat)};
    pos (n_envs,) i32 write heads; mask (n_envs,) bool.
    """
    envs = jnp.arange(n_envs)
    out = {}
    for k, buf in bufs.items():
        cur = buf[pos, envs]  # (n_envs, *feat)
        m = mask.reshape((n_envs,) + (1,) * (cur.ndim - 1))
        new = jnp.where(m, row[k].astype(buf.dtype), cur)
        out[k] = buf.at[pos, envs].set(new)
    return out


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("n_envs",))
def _append_window(bufs, block, pos, mask, valid, *, n_envs):
    """Write T consecutive rows per env starting at its ring position.

    bufs: {k: (cap, n_envs, *feat)}; block: {k: (T, n_envs, *feat)};
    pos (n_envs,) i32 write heads; mask (n_envs,) bool; valid (T,) bool —
    rows with ``valid[t]`` False are padding and leave the ring untouched.
    One dispatch for the whole window: the per-row path costs one jit
    dispatch + H2D per env step, which on a remote link dominates an
    off-policy algo's steady state once training itself is
    dispatch-batched.  Callers pad every window to a FIXED length with the
    tail masked off (see :meth:`DeviceReplayCache.add`), so only one or
    two window shapes ever trace — per-length retraces used to recompile
    this kernel for every distinct flush length (ADVICE r5).
    """
    t_len = next(iter(block.values())).shape[0]
    cap = next(iter(bufs.values())).shape[0]
    envs = jnp.arange(n_envs)

    def body(t, bufs):
        p = (pos + t) % cap
        row_mask = jnp.logical_and(mask, valid[t])
        out = {}
        for k, buf in bufs.items():
            cur = buf[p, envs]
            m = row_mask.reshape((n_envs,) + (1,) * (cur.ndim - 1))
            row = jax.lax.dynamic_index_in_dim(block[k], t, 0, keepdims=False)
            out[k] = buf.at[p, envs].set(jnp.where(m, row.astype(buf.dtype), cur))
        return out

    return jax.lax.fori_loop(0, t_len, body, bufs)


def _transition_window(pos, filled, *, cap, next_keys):
    """Masked index space shared by the flat-transition samplers: the
    oldest stored row (``base``) and the count of sampleable rows — the
    row at the write head is excluded when next-obs are gathered (its
    successor is stale).  SAC-family buffers add all envs in lockstep, so
    pos/filled are shared scalars (element 0 of the per-env vectors).
    Hoisted so the uniform and prioritized samplers agree on validity by
    construction instead of forking the mask logic."""
    p0 = pos[0]
    f0 = filled[0]
    count = f0 - (1 if next_keys else 0)
    base = jnp.where(f0 >= cap, p0, 0)
    return base, count


def _gather_transitions(bufs, rows, envs, *, n_samples, batch_size, cap, next_keys, kernel="lax"):
    """Flat-transition gather shared by the uniform and prioritized
    samplers: (flat,) row/env indices -> (n_samples, batch, *feat) dicts,
    next row = (row + 1) % cap for ``next_keys``.  ``kernel="pallas"``
    fuses every key's gather (+ the next-row fan) into ONE
    ops/pallas_gather.py kernel — identical bytes, one launch."""
    if kernel == "pallas":
        from sheeprl_tpu.ops.pallas_gather import gather_transitions_fused

        flat = gather_transitions_fused(bufs, rows, envs, next_keys=next_keys)
        return {
            k: g.reshape(n_samples, batch_size, *g.shape[1:]) for k, g in flat.items()
        }
    out = {}
    for k, buf in bufs.items():
        g = buf[rows, envs]  # (flat, *feat)
        out[k] = g.reshape(n_samples, batch_size, *buf.shape[2:])
    if next_keys:  # jaxlint: disable=retrace-branch — static obs-key tuple, not a tracer
        nrows = (rows + 1) % cap
        for k in next_keys:
            g = bufs[k][nrows, envs]
            out[f"next_{k}"] = g.reshape(n_samples, batch_size, *bufs[k].shape[2:])
    return out


@functools.partial(
    jax.jit,
    static_argnames=("n_samples", "batch_size", "cap", "n_envs", "next_keys", "kernel"),
)
def _sample_transitions(
    bufs, key, pos, filled, *, n_samples, batch_size, cap, n_envs, next_keys, kernel="lax"
):
    """Gather (n_samples, batch, *feat) flat transitions, mirroring
    ``ReplayBuffer.sample``: rows uniform over stored history, env uniform
    per element (see :func:`_transition_window` for the validity mask)."""
    flat = n_samples * batch_size
    k_env, k_row = jax.random.split(key)
    envs = jax.random.randint(k_env, (flat,), 0, n_envs)
    base, count = _transition_window(pos, filled, cap=cap, next_keys=next_keys)
    u = jax.random.uniform(k_row, (flat,))
    offs = jnp.minimum((u * count).astype(jnp.int32), count - 1)
    rows = (base + offs) % cap
    return _gather_transitions(
        bufs, rows, envs, n_samples=n_samples, batch_size=batch_size, cap=cap,
        next_keys=next_keys, kernel=kernel,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_samples", "batch_size", "cap", "n_envs", "next_keys", "depth", "kernel"),
)
def _sample_transitions_prioritized(
    bufs, tree, key, pos, filled, beta, *, n_samples, batch_size, cap, n_envs, next_keys, depth,
    kernel="lax",
):
    """Proportional prioritized counterpart of :func:`_sample_transitions`:
    (row, env) cells drawn from the sum-tree (leaf = row * n_envs + env),
    validity by construction — unwritten cells carry zero priority, and
    the per-env write-head row is zeroed in a functional tree copy when
    next-obs are gathered (same exclusion as :func:`_transition_window`).
    Returns the batch dict + ``is_weights`` (β-annealed, batch-max
    normalized) and the sampled leaf indices for ``update_priorities``.

    ``kernel="pallas"`` runs the whole draw through the fused
    ops/pallas_per.py descent (head-row exclusions folded in — no
    functional tree copy) + one fused multi-key gather."""
    from sheeprl_tpu.replay.priority_tree import _tree_sample, _tree_zeroed

    flat = n_samples * batch_size
    # live-cell count N for the IS correction w = (N * P(i))^-beta
    n_live = jnp.sum(filled) - (n_envs if next_keys else 0)
    if kernel == "pallas":  # jaxlint: disable=retrace-branch — static kernel-selection string
        from sheeprl_tpu.ops.pallas_per import sum_tree_sample

        head_leaves = None
        if next_keys:  # jaxlint: disable=retrace-branch — static obs-key tuple, not a tracer
            head_rows = (pos - 1) % cap  # per-env newest row: its successor is stale
            head_leaves = head_rows * n_envs + jnp.arange(n_envs)
        leaves, w = sum_tree_sample(
            tree, key, beta, n_live, n=flat, depth=depth, exclude_idx=head_leaves
        )
    else:
        t = tree
        if next_keys:  # jaxlint: disable=retrace-branch — static obs-key tuple, not a tracer
            head_rows = (pos - 1) % cap  # per-env newest row: its successor is stale
            head_leaves = head_rows * n_envs + jnp.arange(n_envs)
            t = _tree_zeroed(t, head_leaves, jnp.ones((n_envs,), bool), depth=depth)
        leaves, w = _tree_sample(t, key, beta, n_live, n=flat, depth=depth)
    rows = leaves // n_envs
    envs = leaves % n_envs
    out = _gather_transitions(
        bufs, rows, envs, n_samples=n_samples, batch_size=batch_size, cap=cap,
        next_keys=next_keys, kernel=kernel,
    )
    out["is_weights"] = w.reshape(n_samples, batch_size, 1)
    return out, leaves.reshape(n_samples, batch_size)


def _gather_windows(bufs, key, pos, filled, *, n_samples, batch_size, seq_len, cap, n_envs, kernel="lax"):
    """Core window gather shared by the single-device jit and the
    per-device body of the sharded sampler (shapes are whatever the
    caller's shard holds).  ``kernel="pallas"`` fuses every key's window
    gather into ONE ops/pallas_gather.py kernel (identical bytes)."""
    flat = n_samples * batch_size
    k_env, k_start = jax.random.split(key)
    envs = jax.random.randint(k_env, (flat,), 0, n_envs)
    counts = filled - seq_len + 1  # (n_envs,) — caller guarantees >= 1
    base = jnp.where(filled >= cap, pos, 0)
    c_e = counts[envs]
    u = jax.random.uniform(k_start, (flat,))
    offs = jnp.minimum((u * c_e).astype(jnp.int32), c_e - 1)
    starts = (base[envs] + offs) % cap
    return _window_gather_out(
        bufs, starts, envs, n_samples=n_samples, batch_size=batch_size, seq_len=seq_len,
        cap=cap, kernel=kernel,
    )


def _window_gather_out(bufs, starts, envs, *, n_samples, batch_size, seq_len, cap, kernel):
    """(flat,) starts/envs -> {k: (n_samples, L, B, *feat)} — the shared
    tail of the uniform and prioritized sequence samplers."""
    if kernel == "pallas":
        from sheeprl_tpu.ops.pallas_gather import gather_windows_fused

        flat_out = gather_windows_fused(bufs, starts, envs, seq_len=seq_len)
        out = {}
        for k, g in flat_out.items():
            g = g.reshape(n_samples, batch_size, seq_len, *g.shape[2:])
            out[k] = jnp.swapaxes(g, 1, 2)  # (n_samples, L, B, *feat)
        return out
    t_idx = (starts[:, None] + jnp.arange(seq_len)[None, :]) % cap  # (flat, L)
    e_idx = envs[:, None]
    out = {}
    for k, buf in bufs.items():
        g = buf[t_idx, e_idx]  # (flat, L, *feat)
        g = g.reshape(n_samples, batch_size, seq_len, *buf.shape[2:])
        out[k] = jnp.swapaxes(g, 1, 2)  # (n_samples, L, B, *feat)
    return out


@functools.partial(
    jax.jit, static_argnames=("n_samples", "batch_size", "seq_len", "cap", "n_envs", "kernel")
)
def _sample(bufs, key, pos, filled, *, n_samples, batch_size, seq_len, cap, n_envs, kernel="lax"):
    """Gather (n_samples, seq_len, batch, *feat) sequence windows.

    Valid starts per env mirror SequentialReplayBuffer.sample: the stored
    rows span logical times [pos - filled, pos); any L-window inside that
    span is valid, i.e. ``filled - L + 1`` starts beginning at the oldest
    row (ring index ``pos`` when full, 0 otherwise).
    """
    return _gather_windows(
        bufs, key, pos, filled,
        n_samples=n_samples, batch_size=batch_size, seq_len=seq_len,
        cap=cap, n_envs=n_envs, kernel=kernel,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_samples", "batch_size", "seq_len", "cap", "n_envs", "depth", "kernel"),
)
def _sample_prioritized(
    bufs, tree, key, pos, filled, beta, *, n_samples, batch_size, seq_len, cap, n_envs, depth,
    kernel="lax",
):
    """Prioritized sequence-START sampling (Dreamer family, behind
    ``buffer.prioritized``): window starts drawn proportional to their
    cell's priority instead of uniformly.  Validity matches
    :func:`_gather_windows` exactly — the L-1 rows immediately preceding
    each env's write head cannot start a full window (zeroed in a
    functional tree copy on the lax path; folded into the fused descent
    as mass corrections on the pallas path — the L-1 rows are distinct
    modulo a capacity ``can_sample`` bounds below by the window length,
    so the distinct-exclusions contract holds by construction).
    Returns the window batch + the sampled start leaves (the caller may
    decay them — recency-biased replay without a TD signal)."""
    from sheeprl_tpu.replay.priority_tree import _tree_sample, _tree_zeroed

    flat = n_samples * batch_size
    n_live = jnp.sum(jnp.maximum(filled - seq_len + 1, 0))
    inv_leaves = None
    if seq_len > 1:  # jaxlint: disable=retrace-branch — static (python int) window length
        offs = jnp.arange(1, seq_len)  # (L-1,)
        inv_rows = (pos[None, :] - offs[:, None]) % cap  # (L-1, n_envs)
        inv_leaves = (inv_rows * n_envs + jnp.arange(n_envs)[None, :]).reshape(-1)
    if kernel == "pallas":  # jaxlint: disable=retrace-branch — static kernel-selection string
        from sheeprl_tpu.ops.pallas_per import sum_tree_sample

        leaves, _w = sum_tree_sample(
            tree, key, beta, n_live, n=flat, depth=depth, exclude_idx=inv_leaves
        )
    else:
        t = tree
        if inv_leaves is not None:
            t = _tree_zeroed(t, inv_leaves, jnp.ones(inv_leaves.shape, bool), depth=depth)
        leaves, _w = _tree_sample(t, key, beta, n_live, n=flat, depth=depth)
    starts = leaves // n_envs
    envs = leaves % n_envs
    out = _window_gather_out(
        bufs, starts, envs, n_samples=n_samples, batch_size=batch_size, seq_len=seq_len,
        cap=cap, kernel=kernel,
    )
    return out, leaves


@contextlib.contextmanager
def sequence_batches(rb, device_cache, runtime, n_samples, batch_size, seq_len, key, **sample_kwargs):
    """Uniform train-loop feed: yields an iterable of per-gradient-step
    batch dicts — an on-device gather when the cache is usable, else the
    host ``rb.sample`` + ``batched_feed`` prefetch path.  Call OUTSIDE the
    train timer so host sampling keeps its historical accounting.
    ``sample_kwargs`` (e.g. DV2's prioritize_ends) go to the host sampler;
    the cache path only exists for plain sequential buffers, where they
    are no-ops."""
    if device_cache is not None and device_cache.can_sample(seq_len):
        if getattr(device_cache, "prioritized", False) and device_cache._tree is not None:
            # prioritized sequence-START sampling (Dreamer family): biased
            # by design like DV2's prioritize_ends — no IS reweighting of
            # the world-model losses, so β is irrelevant here
            yield device_cache.sample_per(n_samples, batch_size, seq_len, key, beta=0.0)
        else:
            yield device_cache.sample(n_samples, batch_size, seq_len, key)
        return
    from sheeprl_tpu.data.feed import batched_feed

    local_data = rb.sample(
        batch_size, sequence_length=seq_len, n_samples=n_samples, **sample_kwargs
    )
    with batched_feed(
        local_data, n_samples, sharding=runtime.batch_sharding(axis=1)
    ) as feed:
        yield feed


def maybe_create_for_transitions(cfg, runtime, rb, state=None):
    """SAC-family factory: a cache mirroring a plain flat-transition
    ``ReplayBuffer`` (uniform rows, optional next-obs).  Pass ``state`` iff
    ``rb`` was restored — the cache refills from it."""
    from sheeprl_tpu.data.buffers import ReplayBuffer

    if type(rb) is not ReplayBuffer:
        return None
    cache = DeviceReplayCache.maybe_create(
        cfg, runtime, capacity=rb.buffer_size, n_envs=rb.n_envs
    )
    if cache is None:
        # multi-device: the env-sharded cache keeps transitions (and the
        # PER sum-trees) on the mesh — uniform draws stay device-local,
        # prioritized ones pay one psum'd mass reduction per draw
        cache = _maybe_create_sharded(cfg, runtime, rb.buffer_size, rb.n_envs)
    if cache is not None and state is not None:
        cache.load_from_replay(rb)
        if cache.prioritized:
            cache.load_priority_state(state.get("replay_priority"))
    return cache


def _maybe_create_sharded(cfg, runtime, capacity: int, n_envs: int):
    """Shared multi-device gating for both buffer families: the env-sharded
    cache applies on single-process multi-device meshes when explicitly
    opted in (``buffer.device_cache=True``) OR when ``buffer.prioritized``
    requires the device sampler (the sum-trees live with the cache —
    there is no host PER path to fall back to, so blockers are a hard
    config error rather than a silent uniform downgrade)."""
    mode = device_cache_setting(cfg)
    prioritized = bool(cfg.buffer.get("prioritized", False))
    if runtime.device_count <= 1:
        return None
    if mode == "off" or not (mode == "on" or prioritized):
        return None
    blockers = []
    if jax.process_count() != 1:
        blockers.append("multi-process run (each process feeds its own shard)")
    if n_envs % runtime.device_count:
        blockers.append(f"n_envs ({n_envs}) not divisible by {runtime.device_count} devices")
    if blockers:
        if prioritized:
            # PER without the device sampler would silently train on a
            # different (uniform) distribution — refuse loudly instead
            raise ValueError(
                "buffer.prioritized=True needs the env-sharded device cache on a "
                "multi-device mesh, which this run cannot build: " + "; ".join(blockers)
            )
        print(
            "DeviceReplayCache: buffer.device_cache=True ignored — "
            + "; ".join(blockers)
            + "; keeping the host feed path"
        )
        return None
    cache = ShardedDeviceReplayCache(
        capacity,
        n_envs,
        runtime,
        prioritized=prioritized,
        per_alpha=float(cfg.buffer.get("per_alpha", 0.6)),
        per_eps=float(cfg.buffer.get("per_eps", 1e-6)),
        per_decay=cfg.buffer.get("per_decay_on_sample", None),
        kernel=str(cfg.buffer.get("per_kernel", "lax")),
    )
    print(
        f"DeviceReplayCache: env-sharded replay window enabled "
        f"(capacity {capacity} x {n_envs} envs over "
        f"{runtime.device_count} devices"
        + (", prioritized per-shard sum-trees" if prioritized else "")
        + ")"
    )
    return cache


def maybe_create_for(cfg, runtime, rb, state=None):
    """One-line factory for the training loops: a cache mirroring ``rb``
    when it is an EnvIndependentReplayBuffer and gating allows (EpisodeBuffer
    replay — DV2's prioritize_ends mode — keeps the host path).  Pass
    ``state`` iff ``rb`` was restored from a checkpoint — the cache then
    refills from it (a non-restored rb is empty, so the refill is a no-op
    either way; the flag just documents intent at the call sites)."""
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer

    if not isinstance(rb, EnvIndependentReplayBuffer):
        return None
    cache = DeviceReplayCache.maybe_create(
        cfg, runtime, capacity=rb.buffer_size, n_envs=rb.n_envs
    )
    if cache is None:
        cache = _maybe_create_sharded(cfg, runtime, rb.buffer_size, rb.n_envs)
    if cache is not None and state is not None:
        cache.load_from(rb)
        if cache.prioritized:
            cache.load_priority_state(state.get("replay_priority"))
    return cache


class DeviceReplayCache:
    """Device mirror of a sequential replay buffer (see module docstring).

    Created lazily on the first :meth:`add` (dtypes/shapes come from the
    first ``step_data`` row).  All arrays live on ``device`` (the runtime's
    training device); appends donate the buffers so updates are in-place.
    """

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        device=None,
        budget_bytes: Optional[int] = None,
        conservative: bool = False,
        prioritized: bool = False,
        per_alpha: float = 0.6,
        per_eps: float = 1e-6,
        per_decay: Optional[float] = None,
        kernel: str = "lax",
    ):
        if capacity <= 0 or n_envs <= 0:
            raise ValueError(f"capacity ({capacity}) and n_envs ({n_envs}) must be positive")
        self.capacity = int(capacity)
        self.n_envs = int(n_envs)
        self._device = device
        self._budget = budget_bytes
        self._conservative = conservative
        # prioritized replay (Schaul et al., 2016): a device sum-tree over
        # the (row, env) cells rides next to the rings; False keeps the
        # uniform samplers untouched (bit-exact with the pre-PER code)
        self.prioritized = bool(prioritized)
        self.per_alpha = float(per_alpha)
        self.per_eps = float(per_eps)
        self.per_decay = per_decay if per_decay is None else float(per_decay)
        from sheeprl_tpu.replay.priority_tree import resolve_per_kernel

        # data-plane kernel selection (buffer.per_kernel): routes the
        # sum-tree descent/scatter AND the batch gathers through the fused
        # ops/ kernels; "lax" keeps the pre-kernel paths bit-exact
        self.kernel = resolve_per_kernel(kernel)
        self._tree = None
        self._bufs: Optional[Dict[str, jax.Array]] = None
        self._pos = np.zeros(n_envs, dtype=np.int32)
        self._filled = np.zeros(n_envs, dtype=np.int32)
        # fixed dispatch length for windowed appends: the first windowed
        # add sets it and every later window is padded (masked tail) or
        # grows it, so _append_window traces at most one or two shapes
        # instead of one per distinct flush length
        self._window_pad: Optional[int] = None
        self.active = True  # flips False if the first row busts the budget

    # ------------------------------------------------------------- admin
    def estimate_bytes(self, row: Dict[str, np.ndarray]) -> int:
        total = 0
        for v in row.values():
            feat = v.shape[2:]
            total += (
                self.capacity
                * self.n_envs
                * int(np.prod(feat, dtype=np.int64) or 1)
                * _store_dtype(v.dtype).itemsize
            )
        return total

    def _per_device_envs(self) -> int:
        """Env count addressed by one device's gather (the sharded subclass
        holds 1/n_dev of the env axis per device)."""
        return self.n_envs

    def _admit(self, row: Dict[str, np.ndarray]) -> bool:
        """Size gates shared by the fresh-run (`_ensure`) and resume
        (`load_from*`) allocation paths.  Flips ``active`` off (host feed
        path) instead of erroring."""
        if self._budget is not None:
            est = self.estimate_bytes(row)
            if est > self._budget:
                self.active = False
                print(
                    f"DeviceReplayCache: estimated {est / 1e9:.2f} GB exceeds the "
                    f"{self._budget / 1e9:.2f} GB budget — staying on the host path"
                )
                return False
        if self._conservative:
            try:
                ring_cap_gb = float(os.environ.get("SHEEPRL_DEVICE_CACHE_MAX_RING_GB", "1.5"))
            except ValueError:
                print(
                    "DeviceReplayCache: could not parse SHEEPRL_DEVICE_CACHE_MAX_RING_GB "
                    "— using the 1.5 GB default"
                )
                ring_cap_gb = 1.5
        for k, v in row.items():
            feat_elems = int(np.prod(v.shape[2:], dtype=np.int64) or 1)
            nbytes = (
                self.capacity
                * self._per_device_envs()
                * feat_elems
                * _store_dtype(v.dtype).itemsize
            )
            # int32-addressability gate: the window/transition gathers index
            # one (capacity, n_envs, *feat) array and XLA's TPU gather
            # lowering linearizes offsets in int32 — past 2^31 the address
            # math overflows and CRASHES the TPU worker.  Bytes always
            # dominate elements (itemsize >= 1), so bytes are the check.
            if nbytes > _INT32_SAFE_BOUND:
                self.active = False
                print(
                    f"DeviceReplayCache: array '{k}' ring would be {nbytes / 1e9:.2f} GB "
                    f"— beyond int32-safe gather addressing (2^31 bytes); staying on "
                    f"the host path (shrink buffer.size to enable)"
                )
                return False
            # auto mode additionally stays inside the empirically proven
            # envelope: on the tunneled v5e, single ring arrays >= ~1.8 GB
            # crash the TPU worker within minutes of interleaved
            # append/sample/train dispatch (DV2 walker, 18750 and 25000
            # frames/env), while <= ~1.23 GB rings have run clean for many
            # chain-hours (DV3/SAC).  Mechanism unconfirmed (no server-side
            # logs through the tunnel) — so "auto" refuses the unproven
            # region and explicit buffer.device_cache=True trusts the user
            # (override: SHEEPRL_DEVICE_CACHE_MAX_RING_GB).
            if self._conservative and nbytes > ring_cap_gb * 1e9:
                self.active = False
                print(
                    f"DeviceReplayCache: array '{k}' ring would be "
                    f"{nbytes / 1e9:.2f} GB > {ring_cap_gb:.2f} GB auto-mode cap "
                    f"(proven-stable envelope on tunneled TPU; see "
                    f"SHEEPRL_DEVICE_CACHE_MAX_RING_GB) — staying on the host path"
                )
                return False
        return True

    def _ensure(self, row: Dict[str, np.ndarray]) -> bool:
        if self._bufs is not None:
            return True
        if not self.active:
            return False
        if not self._admit(row):
            return False
        self._bufs = {
            # f64 host rows (numpy default zeros) store as f32 — the
            # train steps consume f32 anyway (mirrors batched_feed)
            k: self._zeros((self.capacity, self.n_envs, *v.shape[2:]), _store_dtype(v.dtype))
            for k, v in row.items()
        }
        self._ensure_tree()
        return True

    def _ensure_tree(self) -> None:
        if self.prioritized and self._tree is None:
            from sheeprl_tpu.replay.priority_tree import PriorityTree

            self._tree = PriorityTree(
                self.capacity * self.n_envs,
                alpha=self.per_alpha,
                eps=self.per_eps,
                device=self._device,
                kernel=self.kernel,
            )

    def _seed_tree_window(
        self, start: np.ndarray, t_len: int, mask_np: np.ndarray, valid: Optional[np.ndarray] = None
    ) -> None:
        """Priority-seed the cells just written (max-priority insert,
        Schaul §3.3) — also what keeps ring OVERWRITE correct: the evicted
        transition's stale priority is replaced, never sampled again.
        ``valid`` mirrors the padded windowed append (padding rows leave
        the tree untouched, and the pad keeps this write's trace count
        matching ``_append_window``'s)."""
        if self._tree is None:
            return
        rows = (start[None, :] + np.arange(t_len)[:, None]) % self.capacity  # (T, n_envs)
        leaves = rows * self.n_envs + np.arange(self.n_envs)[None, :]
        active = np.broadcast_to(mask_np[None, :], leaves.shape)
        if valid is not None:
            active = active & valid[:, None]
        self._tree.seed_max(leaves.reshape(-1), np.ascontiguousarray(active).reshape(-1))

    # ---- array-placement hooks (the sharded subclass overrides ONLY these)
    def _zeros(self, shape, dtype):
        with jax.default_device(self._device) if self._device is not None else contextlib.nullcontext():
            return jnp.zeros(shape, dtype=dtype)

    def _put_host(self, host: np.ndarray) -> jax.Array:
        return jax.device_put(host, self._device) if self._device is not None else jnp.asarray(host)

    def _place_row(self, row: Dict[str, np.ndarray]):
        return row  # uncommitted host arrays; the _append jit places them

    def _place_block(self, block: Dict[str, np.ndarray]):
        return block  # uncommitted host arrays; the _append_window jit places them

    # ------------------------------------------------------------- write
    def add(self, data: Dict[str, np.ndarray], indices: Optional[Sequence[int]] = None) -> None:
        """Mirror of ``EnvIndependentReplayBuffer.add``: ``data`` is
        (T, n_envs_in, *feat); ``indices`` routes columns to env rings
        (default: all envs in order).  T > 1 goes through the windowed
        append — one jit dispatch for the whole block (training loops that
        dispatch-batch their gradient steps batch their appends the same
        way; see sac.py)."""
        if not self.active:
            return
        first = next(iter(data.values()))
        t_len, n_in = first.shape[:2]
        if indices is None:
            if n_in != self.n_envs:
                raise ValueError(f"data has {n_in} env columns, cache has {self.n_envs}")
            indices = range(self.n_envs)
        idx = np.asarray(list(indices), dtype=np.int64)
        if len(idx) != n_in:
            raise ValueError(f"indices ({len(idx)}) must match data env columns ({n_in})")
        if not self._ensure({k: v[:, :1] for k, v in data.items()}):
            return
        if set(data.keys()) != set(self._bufs.keys()):
            # e.g. a resume that flipped buffer.sample_next_obs changes the
            # stored key set; the host path tolerates it, so fall back
            print(
                "DeviceReplayCache: step keys "
                f"{sorted(data.keys())} != cached keys {sorted(self._bufs.keys())} "
                "— cache disabled, training continues on the host feed path"
            )
            self.active = False
            self._bufs = None
            return
        mask_np = np.zeros(self.n_envs, dtype=bool)
        mask_np[idx] = True
        advance = t_len  # write heads move by the FULL window, even when
        if t_len > self.capacity:  # only the last `capacity` rows survive
            data = {k: v[-self.capacity:] for k, v in data.items()}
            t_len = self.capacity
        if t_len == 1:
            row = {}
            for k, v in data.items():
                full_row = np.zeros((self.n_envs, *v.shape[2:]), dtype=v.dtype)
                full_row[idx] = v[0]
                row[k] = full_row
            row = self._place_row(row)
            self._bufs = _append(
                self._bufs, row, jnp.asarray(self._pos), jnp.asarray(mask_np), n_envs=self.n_envs
            )
            self._seed_tree_window(self._pos, 1, mask_np)
        else:
            # pad to the fixed dispatch length (masked tail) so a short
            # final flush reuses the steady-state trace instead of
            # recompiling _append_window for its one-off length
            if self._window_pad is None or t_len > self._window_pad:
                self._window_pad = t_len
            pad = self._window_pad
            block = {}
            for k, v in data.items():
                full = np.zeros((pad, self.n_envs, *v.shape[2:]), dtype=v.dtype)
                full[:t_len, idx] = v
                block[k] = full
            block = self._place_block(block)
            valid = np.arange(pad) < t_len
            # truncated windows start where sequential adds would have put
            # the first SURVIVING row: pos advanced by the dropped prefix
            start = (self._pos + (advance - t_len)) % self.capacity
            self._bufs = _append_window(
                self._bufs,
                block,
                jnp.asarray(start),
                jnp.asarray(mask_np),
                jnp.asarray(valid),
                n_envs=self.n_envs,
            )
            self._seed_tree_window(start, pad, mask_np, valid=valid)
        self._pos[idx] = (self._pos[idx] + advance) % self.capacity
        self._filled[idx] = np.minimum(self._filled[idx] + advance, self.capacity)

    def load_from(self, rb) -> None:
        """Bulk re-fill from an ``EnvIndependentReplayBuffer`` (resume path):
        one staged host copy + one device_put per key (no per-slab device
        round-trips; the transfer itself is the floor on a slow link).
        Shape mismatches (resumes that changed buffer.size or env count)
        deactivate the cache — the host feed path still trains fine."""
        if not self.active:
            return
        subs = rb.buffer
        if len(subs) != self.n_envs or any(b.buffer_size != self.capacity for b in subs):
            # unreachable from maybe_create_for (which sizes the cache from
            # this rb); direct callers get a hard error
            raise ValueError(
                f"host buffer ({len(subs)} envs x "
                f"{subs[0].buffer_size if subs else 0}) does not match the "
                f"cache ({self.n_envs} x {self.capacity})"
            )
        example = None
        for b in subs:
            if b.buffer:
                example = {k: np.asarray(v[:1]) for k, v in b.buffer.items()}
                break
        if example is None:
            return  # nothing stored yet
        if not self._admit(example):
            return
        bufs = {}
        for k, v0 in example.items():
            parts = []
            for b in subs:
                if b.buffer and k in b.buffer:
                    parts.append(np.asarray(b.buffer[k]))
                else:
                    parts.append(np.zeros((self.capacity, 1, *v0.shape[2:]), v0.dtype))
            host = np.ascontiguousarray(
                np.concatenate(parts, axis=1), dtype=_store_dtype(v0.dtype)
            )  # (cap, n_envs, *feat)
            bufs[k] = self._put_host(host)
        self._bufs = bufs
        self._pos = np.asarray([b._pos for b in subs], dtype=np.int32)
        self._filled = np.asarray(
            [b.buffer_size if b.full else b._pos for b in subs], dtype=np.int32
        )
        self._reseed_tree_filled()

    # ------------------------------------------------------------- read
    def can_sample(self, seq_len: int) -> bool:
        return self.active and self._bufs is not None and bool(np.all(self._filled >= seq_len))

    def sample(self, n_samples: int, batch_size: int, seq_len: int, key) -> List[Dict[str, jax.Array]]:
        """Draw ``n_samples`` independent (seq_len, batch, *feat) batches as
        a list of device dicts (one per gradient step), mirroring the host
        path's ``rb.sample(...)`` + per-sample feed."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if not self.can_sample(seq_len):
            raise ValueError(
                f"Cannot sample a sequence of length {seq_len}. "
                f"Data added so far: {int(self._filled.min())}"
            )
        out = _sample(
            self._bufs,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            n_samples=int(n_samples),
            batch_size=int(batch_size),
            seq_len=int(seq_len),
            cap=self.capacity,
            n_envs=self.n_envs,
            kernel=self.kernel,
        )
        return [{k: v[i] for k, v in out.items()} for i in range(n_samples)]

    def sample_transitions(
        self,
        n_samples: int,
        batch_size: int,
        key,
        sample_next_obs: bool = False,
        obs_keys: Sequence[str] = (),
    ) -> Dict[str, jax.Array]:
        """Flat-transition draw mirroring ``ReplayBuffer.sample`` — returns
        one device dict shaped (n_samples, batch, *feat) (+ ``next_<k>``
        for ``obs_keys`` when ``sample_next_obs``)."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        need = 2 if sample_next_obs else 1
        if not (self.active and self._bufs is not None and int(self._filled.min()) >= need):
            raise ValueError("Not enough data in the device cache, add first")
        return _sample_transitions(
            self._bufs,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            n_samples=int(n_samples),
            batch_size=int(batch_size),
            cap=self.capacity,
            n_envs=self.n_envs,
            next_keys=tuple(obs_keys) if sample_next_obs else (),
            kernel=self.kernel,
        )

    def can_sample_transitions(self, sample_next_obs: bool = False) -> bool:
        need = 2 if sample_next_obs else 1
        return self.active and self._bufs is not None and bool(np.all(self._filled >= need))

    # ------------------------------------------------- prioritized replay
    def _reseed_tree_filled(self) -> None:
        """Resume fallback: every stored cell enters at the initial
        priority (uniform-at-start) — used when no saved tree state is
        available; ``load_priority_state`` overwrites it when one is."""
        if not self.prioritized or self._bufs is None:
            return
        self._ensure_tree()
        base = np.where(self._filled >= self.capacity, self._pos, 0)  # (n_envs,)
        offs = (np.arange(self.capacity)[:, None] - base[None, :]) % self.capacity
        stored = offs < self._filled[None, :]  # (cap, n_envs) cell-filled mask
        vals = stored.astype(np.float32).reshape(-1)
        n = self.capacity * self.n_envs
        self._tree.set_priorities(np.arange(n), vals)

    def update_priorities(self, idx, td_abs) -> None:
        """TD-error feedback hook for the train loops: ``idx`` is the
        leaf-index array returned by the prioritized samplers (any shape),
        ``td_abs`` the matching |δ|.  Stays on device end to end."""
        if self._tree is None:
            return
        idx = jnp.asarray(idx).reshape(-1)
        self._tree.update(idx, jnp.asarray(td_abs).reshape(-1))

    def sample_transitions_per(
        self,
        n_samples: int,
        batch_size: int,
        key,
        beta: float,
        sample_next_obs: bool = False,
        obs_keys: Sequence[str] = (),
    ):
        """Prioritized flat-transition draw: like :meth:`sample_transitions`
        plus an ``is_weights`` key (n_samples, batch, 1); returns
        ``(batch_dict, idx)`` where ``idx`` feeds
        :meth:`update_priorities` after the train step."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        need = 2 if sample_next_obs else 1
        if not (self.active and self._bufs is not None and int(self._filled.min()) >= need):
            raise ValueError("Not enough data in the device cache, add first")
        if self._tree is None:
            raise RuntimeError("prioritized sampling requested on a cache built without prioritized=True")
        return _sample_transitions_prioritized(
            self._bufs,
            self._tree.tree,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            jnp.asarray(float(beta), jnp.float32),
            n_samples=int(n_samples),
            batch_size=int(batch_size),
            cap=self.capacity,
            n_envs=self.n_envs,
            next_keys=tuple(obs_keys) if sample_next_obs else (),
            depth=self._tree.depth,
            kernel=self.kernel,
        )

    def sample_per(
        self, n_samples: int, batch_size: int, seq_len: int, key, beta: float
    ) -> List[Dict[str, jax.Array]]:
        """Prioritized sequence-start draw (Dreamer family): same output
        layout as :meth:`sample`; start cells drawn proportional to
        priority.  With ``per_decay`` set, sampled starts are decayed
        afterwards — recency-biased replay without a TD signal (fresh
        windows keep max priority until visited)."""
        if not self.can_sample(seq_len):
            raise ValueError(
                f"Cannot sample a sequence of length {seq_len}. "
                f"Data added so far: {int(self._filled.min())}"
            )
        if self._tree is None:
            raise RuntimeError("prioritized sampling requested on a cache built without prioritized=True")
        out, leaves = _sample_prioritized(
            self._bufs,
            self._tree.tree,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            jnp.asarray(float(beta), jnp.float32),
            n_samples=int(n_samples),
            batch_size=int(batch_size),
            seq_len=int(seq_len),
            cap=self.capacity,
            n_envs=self.n_envs,
            depth=self._tree.depth,
            kernel=self.kernel,
        )
        if self.per_decay is not None:
            self._tree.scale(leaves, self.per_decay)
        return [{k: v[i] for k, v in out.items()} for i in range(n_samples)]

    def priority_state(self) -> Optional[Dict[str, Any]]:
        """Checkpoint payload for the tree (None when not prioritized) —
        rides the CheckpointManager snapshot next to the host buffer."""
        return self._tree.state_dict() if self._tree is not None else None

    def load_priority_state(self, state: Optional[Dict[str, Any]]) -> None:
        if not self.prioritized or not self.active or self._bufs is None:
            return
        self._ensure_tree()
        if state is None:
            self._reseed_tree_filled()
        else:
            self._tree.load_state_dict(state)

    def load_from_replay(self, rb) -> None:
        """Refill from a plain (flat-transition) ``ReplayBuffer``."""
        if not self.active:
            return
        if rb.buffer_size != self.capacity or rb.n_envs != self.n_envs:
            # unreachable from maybe_create_for_transitions (which sizes the
            # cache from this rb); direct callers get a hard error
            raise ValueError(
                f"host buffer ({rb.n_envs} envs x {rb.buffer_size}) does not "
                f"match the cache ({self.n_envs} x {self.capacity})"
            )
        if not rb.buffer:
            return  # nothing stored yet
        example = {k: np.asarray(v[:1]) for k, v in rb.buffer.items()}
        if not self._admit(example):
            return
        self._bufs = {
            k: (
                jax.device_put(
                    np.ascontiguousarray(np.asarray(v), dtype=_store_dtype(v.dtype)),
                    self._device,
                )
                if self._device is not None
                else jnp.asarray(np.ascontiguousarray(np.asarray(v), dtype=_store_dtype(v.dtype)))
            )
            for k, v in rb.buffer.items()
        }
        pos = int(rb._pos)
        filled = self.capacity if rb.full else pos
        self._pos = np.full(self.n_envs, pos, dtype=np.int32)
        self._filled = np.full(self.n_envs, filled, dtype=np.int32)
        self._reseed_tree_filled()

    # ------------------------------------------------------------ factory
    @classmethod
    def maybe_create(cls, cfg, runtime, capacity: int, n_envs: int) -> Optional["DeviceReplayCache"]:
        """Create when gating allows (see module docstring), else None."""
        mode = device_cache_setting(cfg)
        prioritized = bool(cfg.buffer.get("prioritized", False))
        if mode == "off":
            if prioritized:
                # the sum-tree lives with the cache — disabling the cache
                # while asking for PER is a config contradiction, not a
                # silent downgrade to uniform sampling
                raise ValueError(
                    "buffer.prioritized=True requires the device sampler, but "
                    "buffer.device_cache=False disables it; drop one of the two "
                    "(device_cache=auto enables the cache wherever PER needs it)"
                )
            return None
        if runtime.device_count != 1 or jax.process_count() != 1:
            # multi-device: both buffer families route to the env-sharded
            # variant via _maybe_create_sharded (prioritized included)
            return None
        if mode == "auto" and runtime.device.platform == "cpu" and not prioritized:
            return None  # host-platform run: device_put is free, no win
        budget_gb = float(cfg.buffer.get("device_cache_budget_gb", 6.0))
        cache = cls(
            capacity,
            n_envs,
            device=runtime.device,
            budget_bytes=int(budget_gb * 1e9) if mode == "auto" else None,
            conservative=mode == "auto",
            prioritized=prioritized,
            per_alpha=float(cfg.buffer.get("per_alpha", 0.6)),
            per_eps=float(cfg.buffer.get("per_eps", 1e-6)),
            per_decay=cfg.buffer.get("per_decay_on_sample", None),
            kernel=str(cfg.buffer.get("per_kernel", "lax")),
        )
        print(
            f"DeviceReplayCache: HBM-resident replay window enabled "
            f"(capacity {capacity} x {n_envs} envs, mode={mode}"
            + (", prioritized" if prioritized else "")
            + ")"
        )
        return cache


class ShardedDeviceReplayCache(DeviceReplayCache):
    """Env-sharded cache for single-process multi-device meshes.

    Each device holds the rings of ``n_envs / n_devices`` environments
    (buffers sharded ``P(None, BATCH_AXES)`` over the env axis) and
    uniform sampling draws each device's ``batch / n_devices`` rows from
    its OWN envs inside a ``shard_map`` — appends and gathers stay
    device-local, and the sampled batch comes out already sharded on the
    batch axis exactly as ``runtime.batch_sharding(axis=1)`` lays it out
    for the train step.

    Uniform sampling semantics vs the host path: env choice becomes
    STRATIFIED (exactly batch/n_devices rows from each device's env
    subset) instead of globally uniform — identical marginals, slightly
    lower variance.  Start-window validity per env is unchanged.

    **Prioritized** sampling is fully supported via per-shard sub-trees
    (:class:`~sheeprl_tpu.replay.priority_tree.ShardedPriorityTree`):
    each draw costs ONE psum'd total-mass reduction placing every shard's
    mass interval in the global CDF, each shard descends its own sub-tree
    for the draws it owns, and the batch is assembled with a masked psum
    — so the sampled marginals are IDENTICAL to a single global sum-tree
    (pinned by tests/test_parallel/test_sharding.py).  The assembled PER
    batch is replicated (the psum is the price of exact global
    proportionality); the train step's batch constraint re-slices it.

    Storage and ring/append/refill logic are inherited — this class
    overrides only the array-placement hooks, the tree flavor, and the
    samplers."""

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        runtime,
        budget_bytes: Optional[int] = None,
        prioritized: bool = False,
        per_alpha: float = 0.6,
        per_eps: float = 1e-6,
        per_decay: Optional[float] = None,
        kernel: str = "lax",
    ):
        n_dev = runtime.device_count
        if n_envs % n_dev:
            raise ValueError(f"n_envs ({n_envs}) must divide over {n_dev} devices")
        super().__init__(
            capacity,
            n_envs,
            device=None,
            budget_bytes=budget_bytes,
            prioritized=prioritized,
            per_alpha=per_alpha,
            per_eps=per_eps,
            per_decay=per_decay,
            kernel=kernel,
        )
        self._runtime = runtime
        self._n_dev = n_dev
        from jax.sharding import NamedSharding, PartitionSpec as P

        from sheeprl_tpu.parallel.sharding import BATCH_AXES

        self._axes = BATCH_AXES
        self._fsdp_size = int(runtime.mesh.shape[BATCH_AXES[1]])
        self._env_sharding = NamedSharding(runtime.mesh, P(None, BATCH_AXES))
        self._row_sharding = NamedSharding(runtime.mesh, P(BATCH_AXES))
        self._sharded_sample_fns = {}

    def _ensure_tree(self) -> None:
        if self.prioritized and self._tree is None:
            from sheeprl_tpu.replay.priority_tree import ShardedPriorityTree

            self._tree = ShardedPriorityTree(
                self.capacity,
                self.n_envs,
                self._n_dev,
                self._runtime.mesh,
                alpha=self.per_alpha,
                eps=self.per_eps,
                kernel=self.kernel,
            )

    def _flat_rank(self):
        """Flattened shard index inside a shard_map body (the env slice
        this device owns — matches the P(None, BATCH_AXES) split order)."""
        return (
            jax.lax.axis_index(self._axes[0]) * self._fsdp_size
            + jax.lax.axis_index(self._axes[1])
        )

    # ---- placement hooks: same logic as the base, sharded arrays
    def _per_device_envs(self) -> int:
        # each device's shard_map gather addresses only its env slice
        return self.n_envs // self._n_dev

    def _zeros(self, shape, dtype):
        # device-native zeros: the rings are donated by _append, and a
        # donated buffer must never zero-copy alias a host numpy temp
        return jax.device_put(jnp.zeros(shape, dtype), self._env_sharding)

    def _put_host(self, host: np.ndarray) -> jax.Array:
        return jax.device_put(host, self._env_sharding)

    def _place_row(self, row):
        return {k: jax.device_put(v, self._row_sharding) for k, v in row.items()}

    def _place_block(self, block):
        # (T, n_envs, *feat): env axis is dim 1, same layout as the rings
        return {k: jax.device_put(v, self._env_sharding) for k, v in block.items()}

    # ---- per-device stratified sampler
    def sample(self, n_samples: int, batch_size: int, seq_len: int, key) -> List[Dict[str, jax.Array]]:
        if batch_size % self._n_dev:
            raise ValueError(
                f"batch_size ({batch_size}) must divide over {self._n_dev} devices"
            )
        if not self.can_sample(seq_len):
            raise ValueError(
                f"Cannot sample a sequence of length {seq_len}. "
                f"Data added so far: {int(self._filled.min())}"
            )
        geom = (int(n_samples), int(batch_size), int(seq_len), tuple(sorted(self._bufs)))
        fn = self._sharded_sample_fns.get(geom)
        if fn is None:
            fn = self._build_sharded_sample(*geom[:3])
            self._sharded_sample_fns[geom] = fn
        out = fn(self._bufs, jnp.asarray(key), jnp.asarray(self._pos), jnp.asarray(self._filled))
        return [{k: v[i] for k, v in out.items()} for i in range(n_samples)]

    def _build_sharded_sample(self, n_samples, batch_size, seq_len):
        from jax.sharding import PartitionSpec as P

        mesh = self._runtime.mesh
        axes = self._axes
        cap, n_envs, n_dev = self.capacity, self.n_envs, self._n_dev
        kernel = self.kernel

        def body(bufs_l, key, pos_l, filled_l):
            # per-device independent stream; each device samples its own envs
            k = jax.random.fold_in(key, self._flat_rank())
            return _gather_windows(
                bufs_l, k, pos_l, filled_l,
                n_samples=n_samples, batch_size=batch_size // n_dev,
                seq_len=seq_len, cap=cap, n_envs=n_envs // n_dev, kernel=kernel,
            )

        buf_specs = {k: P(None, axes) for k in self._bufs}
        out_specs = {k: P(None, None, axes) for k in self._bufs}
        sharded = shard_map(
            body, mesh=mesh,
            in_specs=(buf_specs, P(), P(axes), P(axes)),
            out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(sharded)

    # --------------------------------------------- sharded flat transitions
    def sample_transitions(
        self,
        n_samples: int,
        batch_size: int,
        key,
        sample_next_obs: bool = False,
        obs_keys: Sequence[str] = (),
    ) -> Dict[str, jax.Array]:
        """Stratified uniform flat-transition draw: each device gathers
        ``batch / n_devices`` rows from its own env columns (same
        marginals as the global uniform draw; zero collectives)."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if batch_size % self._n_dev:
            raise ValueError(f"batch_size ({batch_size}) must divide over {self._n_dev} devices")
        need = 2 if sample_next_obs else 1
        if not (self.active and self._bufs is not None and int(self._filled.min()) >= need):
            raise ValueError("Not enough data in the device cache, add first")
        nk = tuple(obs_keys) if sample_next_obs else ()
        geom = ("transitions", int(n_samples), int(batch_size), nk, tuple(sorted(self._bufs)))
        fn = self._sharded_sample_fns.get(geom)
        if fn is None:
            fn = self._build_sharded_sample_transitions(int(n_samples), int(batch_size), nk)
            self._sharded_sample_fns[geom] = fn
        return fn(self._bufs, jnp.asarray(key), jnp.asarray(self._pos), jnp.asarray(self._filled))

    def _build_sharded_sample_transitions(self, n_samples, batch_size, next_keys):
        from jax.sharding import PartitionSpec as P

        mesh = self._runtime.mesh
        axes = self._axes
        cap, n_dev = self.capacity, self._n_dev
        n_local = self.n_envs // n_dev
        b_local = batch_size // n_dev
        kernel = self.kernel

        def body(bufs_l, key, pos_l, filled_l):
            k = jax.random.fold_in(key, self._flat_rank())
            flat = n_samples * b_local
            k_env, k_row = jax.random.split(k)
            envs = jax.random.randint(k_env, (flat,), 0, n_local)
            base, count = _transition_window(pos_l, filled_l, cap=cap, next_keys=next_keys)
            u = jax.random.uniform(k_row, (flat,))
            offs = jnp.minimum((u * count).astype(jnp.int32), count - 1)
            rows = (base + offs) % cap
            return _gather_transitions(
                bufs_l, rows, envs,
                n_samples=n_samples, batch_size=b_local, cap=cap, next_keys=next_keys,
                kernel=kernel,
            )

        buf_specs = {k: P(None, axes) for k in self._bufs}
        out_keys = list(self._bufs) + [f"next_{k}" for k in next_keys]
        out_specs = {k: P(None, axes) for k in out_keys}
        sharded = shard_map(
            body, mesh=mesh,
            in_specs=(buf_specs, P(), P(axes), P(axes)),
            out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(sharded)

    # ------------------------------------------------- sharded prioritized
    def sample_transitions_per(
        self,
        n_samples: int,
        batch_size: int,
        key,
        beta: float,
        sample_next_obs: bool = False,
        obs_keys: Sequence[str] = (),
    ):
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        need = 2 if sample_next_obs else 1
        if not (self.active and self._bufs is not None and int(self._filled.min()) >= need):
            raise ValueError("Not enough data in the device cache, add first")
        if self._tree is None:
            raise RuntimeError("prioritized sampling requested on a cache built without prioritized=True")
        nk = tuple(obs_keys) if sample_next_obs else ()
        geom = ("per_transitions", int(n_samples), int(batch_size), nk, tuple(sorted(self._bufs)))
        fn = self._sharded_sample_fns.get(geom)
        if fn is None:
            fn = self._build_sharded_per(int(n_samples), int(batch_size), None, nk)
            self._sharded_sample_fns[geom] = fn
        out, leaves = fn(
            self._bufs,
            self._tree.trees,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            jnp.asarray(float(beta), jnp.float32),
        )
        return out, leaves

    def sample_per(
        self, n_samples: int, batch_size: int, seq_len: int, key, beta: float
    ) -> List[Dict[str, jax.Array]]:
        if not self.can_sample(seq_len):
            raise ValueError(
                f"Cannot sample a sequence of length {seq_len}. "
                f"Data added so far: {int(self._filled.min())}"
            )
        if self._tree is None:
            raise RuntimeError("prioritized sampling requested on a cache built without prioritized=True")
        geom = ("per_windows", int(n_samples), int(batch_size), int(seq_len), tuple(sorted(self._bufs)))
        fn = self._sharded_sample_fns.get(geom)
        if fn is None:
            fn = self._build_sharded_per(int(n_samples), int(batch_size), int(seq_len), ())
            self._sharded_sample_fns[geom] = fn
        out, leaves = fn(
            self._bufs,
            self._tree.trees,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            jnp.asarray(0.0, jnp.float32),
        )
        if self.per_decay is not None:
            self._tree.scale(leaves, self.per_decay)
        return [{k: v[i] for k, v in out.items()} for i in range(n_samples)]

    def _build_sharded_per(self, n_samples, batch_size, seq_len, next_keys):
        """One builder for both prioritized shapes: ``seq_len=None`` gives
        the flat-transition sampler (+ IS weights), an int gives the
        sequence-START sampler (Dreamer family; no IS reweighting).

        The body runs per shard: zero this shard's invalid cells in a
        functional sub-tree copy, draw globally via
        :func:`~sheeprl_tpu.replay.priority_tree.shard_proportional_draw`
        (ONE psum'd total-mass reduction), gather rows for the draws this
        shard owns, and masked-psum the batch together — exact global
        proportional marginals, replicated output."""
        from jax.sharding import PartitionSpec as P

        from sheeprl_tpu.replay.priority_tree import (
            _tree_zeroed_local,
            shard_proportional_draw,
        )

        mesh = self._runtime.mesh
        axes = self._axes
        cap, n_envs, n_dev = self.capacity, self.n_envs, self._n_dev
        n_local = n_envs // n_dev
        depth = self._tree.depth
        flat = n_samples * batch_size
        windows = seq_len is not None
        kernel = self.kernel

        def body(bufs_l, trees_l, key, pos_l, filled_l, beta):
            r = self._flat_rank()
            t = trees_l[0]
            # shard-local sampling exclusions (invalid window starts /
            # stale-next-obs head rows): the lax path pre-zeroes a
            # functional sub-tree copy; the pallas path folds them into
            # the fused descent as mass corrections (no copy)
            excl = None
            if windows and seq_len > 1:  # jaxlint: disable=retrace-branch — static window length
                offs = jnp.arange(1, seq_len)  # (L-1,)
                inv_rows = (pos_l[None, :] - offs[:, None]) % cap  # (L-1, n_local)
                excl = (inv_rows * n_local + jnp.arange(n_local)[None, :]).reshape(-1)
            if not windows and next_keys:  # jaxlint: disable=retrace-branch — static obs-key tuple
                head_rows = (pos_l - 1) % cap  # per-env newest row: successor is stale
                excl = head_rows * n_local + jnp.arange(n_local)
            if kernel == "pallas":
                leaf, mass, own, total = shard_proportional_draw(
                    t, key, r, n_dev, axes, n=flat, depth=depth,
                    kernel="pallas", exclude_idx=excl,
                )
            else:
                if excl is not None:
                    t = _tree_zeroed_local(t, excl, depth)
                leaf, mass, own, total = shard_proportional_draw(
                    t, key, r, n_dev, axes, n=flat, depth=depth
                )
            rows = leaf // n_local
            env_l = leaf % n_local
            cell_global = rows * n_envs + (r * n_local + env_l)
            leaves_out = jax.lax.psum(jnp.where(own, cell_global, 0), axes)

            out = {}
            if windows:
                t_idx = (rows[:, None] + jnp.arange(seq_len)[None, :]) % cap  # (flat, L)
                e_idx = env_l[:, None]
                for k, buf in bufs_l.items():
                    g = buf[t_idx, e_idx]  # (flat, L, *feat)
                    m = own.reshape((flat,) + (1,) * (g.ndim - 1))
                    g = jax.lax.psum(jnp.where(m, g, jnp.zeros((), g.dtype)), axes)
                    g = g.reshape(n_samples, batch_size, seq_len, *buf.shape[2:])
                    out[k] = jnp.swapaxes(g, 1, 2)  # (n_samples, L, B, *feat)
            else:
                gathered = _gather_transitions(
                    bufs_l, rows, env_l,
                    n_samples=n_samples, batch_size=batch_size, cap=cap, next_keys=next_keys,
                )
                own_b = own.reshape(n_samples, batch_size)
                for k, g in gathered.items():
                    m = own_b.reshape(own_b.shape + (1,) * (g.ndim - 2))
                    out[k] = jax.lax.psum(jnp.where(m, g, jnp.zeros((), g.dtype)), axes)
                # IS weights from the psum-assembled per-draw masses (all
                # shards agree, so the batch-max normalization is global)
                mass_global = jax.lax.psum(jnp.where(own, mass, 0.0), axes)
                live_local = jnp.sum(filled_l) - (n_local if next_keys else 0)
                n_live = jax.lax.psum(live_local.astype(jnp.float32), axes)
                probs = jnp.maximum(mass_global, jnp.finfo(jnp.float32).tiny) / jnp.maximum(
                    total, jnp.finfo(jnp.float32).tiny
                )
                w = (jnp.maximum(n_live, 1.0) * probs) ** (-beta)
                w = w / jnp.max(w)
                out["is_weights"] = w.reshape(n_samples, batch_size, 1)
            return out, leaves_out.reshape(n_samples, batch_size)

        buf_specs = {k: P(None, axes) for k in self._bufs}
        out_keys = list(self._bufs) + [f"next_{k}" for k in next_keys]
        if not windows:
            out_keys.append("is_weights")
        out_specs = ({k: P() for k in out_keys}, P())
        sharded = shard_map(
            body, mesh=mesh,
            in_specs=(buf_specs, P(axes, None), P(), P(axes), P(axes), P()),
            out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(sharded)
