"""HBM-resident replay cache with on-device sequence sampling.

Why this exists (TPU-first design, no reference counterpart): the
reference's training loop re-reads every minibatch from a host-RAM buffer
(sheeprl dreamer_v3.py:628-641 samples torch tensors per gradient step),
which is free over PCIe but catastrophic over a remote-device link — on
the tunneled v5e used for this repo's benchmarks the host->HBM path moves
~10-14 MB/s, so a DV3-S batch (T=64, B=16 of 64x64x3 uint8 = 12.6 MB)
costs ~1 s per gradient step against a 16 ms train step (98% of the loop
is transfer).  The fix is to keep the replay window IN HBM: each policy
step uploads only the new frames (n_envs x ~12 KB), and sampling becomes
an on-device gather that feeds the jitted train step with zero host
round-trips.

Semantics mirror ``EnvIndependentReplayBuffer`` over
``SequentialReplayBuffer`` (data/buffers.py:299,387): one ring per env
with an independent write head, env chosen uniformly per batch element,
sequence starts uniform over the valid wrap-around-safe window (never
crossing the write head), windows contiguous within a single env.  The
host buffer stays the source of truth for checkpointing — this cache is
derived state, rebuilt from the host buffer on resume
(:meth:`load_from`).

Gating: ``buffer.device_cache`` (True / False / "auto"; env override
``SHEEPRL_DEVICE_CACHE``).  "auto" enables on single-device accelerator
meshes when the estimated footprint fits ``buffer.device_cache_budget_gb``
(default 6.0) — exactly the remote-link regime where it pays.  Multi-host
/ multi-device data parallelism keeps the host path (each process feeds
its own shard; a replicated cache would multiply HBM cost).
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DeviceReplayCache", "device_cache_setting"]


def _store_dtype(dt) -> np.dtype:
    dt = np.dtype(dt)
    return np.dtype(np.float32) if dt == np.float64 else dt


def device_cache_setting(cfg) -> str:
    """Resolve ``buffer.device_cache`` with its env override to one of
    "on" / "off" / "auto"."""
    val = cfg.buffer.get("device_cache", "auto")
    env = os.environ.get("SHEEPRL_DEVICE_CACHE")
    if env is not None:
        val = env
    s = str(val).lower()
    if s in ("1", "true", "on", "yes"):
        return "on"
    if s in ("0", "false", "off", "no"):
        return "off"
    return "auto"


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("n_envs",))
def _append(bufs, row, pos, mask, *, n_envs):
    """Write one row per env at its own ring position, where mask says so.

    bufs: {k: (cap, n_envs, *feat)}; row: {k: (n_envs, *feat)};
    pos (n_envs,) i32 write heads; mask (n_envs,) bool.
    """
    envs = jnp.arange(n_envs)
    out = {}
    for k, buf in bufs.items():
        cur = buf[pos, envs]  # (n_envs, *feat)
        m = mask.reshape((n_envs,) + (1,) * (cur.ndim - 1))
        new = jnp.where(m, row[k].astype(buf.dtype), cur)
        out[k] = buf.at[pos, envs].set(new)
    return out


@functools.partial(
    jax.jit,
    static_argnames=("n_samples", "batch_size", "cap", "n_envs", "next_keys"),
)
def _sample_transitions(bufs, key, pos, filled, *, n_samples, batch_size, cap, n_envs, next_keys):
    """Gather (n_samples, batch, *feat) flat transitions, mirroring
    ``ReplayBuffer.sample``: rows uniform over stored history (the row at
    the write head excluded when next-obs are gathered — its successor is
    stale), env uniform per element, next row = (row + 1) % cap.  SAC-family
    buffers add all envs in lockstep, so pos/filled are shared scalars here
    (the caller passes per-env vectors; element 0 is used)."""
    flat = n_samples * batch_size
    k_env, k_row = jax.random.split(key)
    envs = jax.random.randint(k_env, (flat,), 0, n_envs)
    p0 = pos[0]
    f0 = filled[0]
    count = f0 - (1 if next_keys else 0)
    base = jnp.where(f0 >= cap, p0, 0)
    u = jax.random.uniform(k_row, (flat,))
    offs = jnp.minimum((u * count).astype(jnp.int32), count - 1)
    rows = (base + offs) % cap
    out = {}
    for k, buf in bufs.items():
        g = buf[rows, envs]  # (flat, *feat)
        out[k] = g.reshape(n_samples, batch_size, *buf.shape[2:])
    if next_keys:
        nrows = (rows + 1) % cap
        for k in next_keys:
            g = bufs[k][nrows, envs]
            out[f"next_{k}"] = g.reshape(n_samples, batch_size, *bufs[k].shape[2:])
    return out


@functools.partial(
    jax.jit, static_argnames=("n_samples", "batch_size", "seq_len", "cap", "n_envs")
)
def _sample(bufs, key, pos, filled, *, n_samples, batch_size, seq_len, cap, n_envs):
    """Gather (n_samples, seq_len, batch, *feat) sequence windows.

    Valid starts per env mirror SequentialReplayBuffer.sample: the stored
    rows span logical times [pos - filled, pos); any L-window inside that
    span is valid, i.e. ``filled - L + 1`` starts beginning at the oldest
    row (ring index ``pos`` when full, 0 otherwise).
    """
    flat = n_samples * batch_size
    k_env, k_start = jax.random.split(key)
    envs = jax.random.randint(k_env, (flat,), 0, n_envs)
    counts = filled - seq_len + 1  # (n_envs,) — caller guarantees >= 1
    base = jnp.where(filled >= cap, pos, 0)
    c_e = counts[envs]
    u = jax.random.uniform(k_start, (flat,))
    offs = jnp.minimum((u * c_e).astype(jnp.int32), c_e - 1)
    starts = (base[envs] + offs) % cap
    t_idx = (starts[:, None] + jnp.arange(seq_len)[None, :]) % cap  # (flat, L)
    e_idx = envs[:, None]
    out = {}
    for k, buf in bufs.items():
        g = buf[t_idx, e_idx]  # (flat, L, *feat)
        g = g.reshape(n_samples, batch_size, seq_len, *buf.shape[2:])
        out[k] = jnp.swapaxes(g, 1, 2)  # (n_samples, L, B, *feat)
    return out


@contextlib.contextmanager
def sequence_batches(rb, device_cache, runtime, n_samples, batch_size, seq_len, key, **sample_kwargs):
    """Uniform train-loop feed: yields an iterable of per-gradient-step
    batch dicts — an on-device gather when the cache is usable, else the
    host ``rb.sample`` + ``batched_feed`` prefetch path.  Call OUTSIDE the
    train timer so host sampling keeps its historical accounting.
    ``sample_kwargs`` (e.g. DV2's prioritize_ends) go to the host sampler;
    the cache path only exists for plain sequential buffers, where they
    are no-ops."""
    if device_cache is not None and device_cache.can_sample(seq_len):
        yield device_cache.sample(n_samples, batch_size, seq_len, key)
        return
    from sheeprl_tpu.data.feed import batched_feed

    local_data = rb.sample(
        batch_size, sequence_length=seq_len, n_samples=n_samples, **sample_kwargs
    )
    with batched_feed(
        local_data, n_samples, sharding=runtime.batch_sharding(axis=1)
    ) as feed:
        yield feed


def maybe_create_for_transitions(cfg, runtime, rb, state=None):
    """SAC-family factory: a cache mirroring a plain flat-transition
    ``ReplayBuffer`` (uniform rows, optional next-obs).  Pass ``state`` iff
    ``rb`` was restored — the cache refills from it."""
    from sheeprl_tpu.data.buffers import ReplayBuffer

    if type(rb) is not ReplayBuffer:
        return None
    cache = DeviceReplayCache.maybe_create(
        cfg, runtime, capacity=rb.buffer_size, n_envs=rb.n_envs
    )
    if cache is not None and state is not None:
        cache.load_from_replay(rb)
    return cache


def maybe_create_for(cfg, runtime, rb, state=None):
    """One-line factory for the training loops: a cache mirroring ``rb``
    when it is an EnvIndependentReplayBuffer and gating allows (EpisodeBuffer
    replay — DV2's prioritize_ends mode — keeps the host path).  Pass
    ``state`` iff ``rb`` was restored from a checkpoint — the cache then
    refills from it (a non-restored rb is empty, so the refill is a no-op
    either way; the flag just documents intent at the call sites)."""
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer

    if not isinstance(rb, EnvIndependentReplayBuffer):
        return None
    cache = DeviceReplayCache.maybe_create(
        cfg, runtime, capacity=rb.buffer_size, n_envs=rb.n_envs
    )
    if cache is not None and state is not None:
        cache.load_from(rb)
    return cache


class DeviceReplayCache:
    """Device mirror of a sequential replay buffer (see module docstring).

    Created lazily on the first :meth:`add` (dtypes/shapes come from the
    first ``step_data`` row).  All arrays live on ``device`` (the runtime's
    training device); appends donate the buffers so updates are in-place.
    """

    def __init__(self, capacity: int, n_envs: int, device=None, budget_bytes: Optional[int] = None):
        if capacity <= 0 or n_envs <= 0:
            raise ValueError(f"capacity ({capacity}) and n_envs ({n_envs}) must be positive")
        self.capacity = int(capacity)
        self.n_envs = int(n_envs)
        self._device = device
        self._budget = budget_bytes
        self._bufs: Optional[Dict[str, jax.Array]] = None
        self._pos = np.zeros(n_envs, dtype=np.int32)
        self._filled = np.zeros(n_envs, dtype=np.int32)
        self.active = True  # flips False if the first row busts the budget

    # ------------------------------------------------------------- admin
    def estimate_bytes(self, row: Dict[str, np.ndarray]) -> int:
        total = 0
        for v in row.values():
            feat = v.shape[2:]
            total += (
                self.capacity
                * self.n_envs
                * int(np.prod(feat, dtype=np.int64) or 1)
                * _store_dtype(v.dtype).itemsize
            )
        return total

    def _ensure(self, row: Dict[str, np.ndarray]) -> bool:
        if self._bufs is not None:
            return True
        if not self.active:
            return False
        if self._budget is not None:
            est = self.estimate_bytes(row)
            if est > self._budget:
                self.active = False
                print(
                    f"DeviceReplayCache: estimated {est / 1e9:.2f} GB exceeds the "
                    f"{self._budget / 1e9:.2f} GB budget — staying on the host path"
                )
                return False
        with jax.default_device(self._device) if self._device is not None else contextlib.nullcontext():
            self._bufs = {
                # f64 host rows (numpy default zeros) store as f32 — the
                # train steps consume f32 anyway (mirrors batched_feed)
                k: jnp.zeros((self.capacity, self.n_envs, *v.shape[2:]), dtype=_store_dtype(v.dtype))
                for k, v in row.items()
            }
        return True

    # ------------------------------------------------------------- write
    def add(self, data: Dict[str, np.ndarray], indices: Optional[Sequence[int]] = None) -> None:
        """Mirror of ``EnvIndependentReplayBuffer.add``: ``data`` is
        (T, n_envs_in, *feat); ``indices`` routes columns to env rings
        (default: all envs in order).  T > 1 loops host-side (the training
        loops append single rows)."""
        if not self.active:
            return
        first = next(iter(data.values()))
        t_len, n_in = first.shape[:2]
        if indices is None:
            if n_in != self.n_envs:
                raise ValueError(f"data has {n_in} env columns, cache has {self.n_envs}")
            indices = range(self.n_envs)
        idx = np.asarray(list(indices), dtype=np.int64)
        if len(idx) != n_in:
            raise ValueError(f"indices ({len(idx)}) must match data env columns ({n_in})")
        if not self._ensure({k: v[:, :1] for k, v in data.items()}):
            return
        if set(data.keys()) != set(self._bufs.keys()):
            # e.g. a resume that flipped buffer.sample_next_obs changes the
            # stored key set; the host path tolerates it, so fall back
            print(
                "DeviceReplayCache: step keys "
                f"{sorted(data.keys())} != cached keys {sorted(self._bufs.keys())} "
                "— cache disabled, training continues on the host feed path"
            )
            self.active = False
            self._bufs = None
            return
        mask_np = np.zeros(self.n_envs, dtype=bool)
        mask_np[idx] = True
        for t in range(t_len):
            row = {}
            for k, v in data.items():
                full_row = np.zeros((self.n_envs, *v.shape[2:]), dtype=v.dtype)
                full_row[idx] = v[t]
                row[k] = full_row
            self._bufs = _append(
                self._bufs, row, jnp.asarray(self._pos), jnp.asarray(mask_np), n_envs=self.n_envs
            )
            self._pos[idx] = (self._pos[idx] + 1) % self.capacity
            self._filled[idx] = np.minimum(self._filled[idx] + 1, self.capacity)

    def load_from(self, rb) -> None:
        """Bulk re-fill from an ``EnvIndependentReplayBuffer`` (resume path):
        one staged host copy + one device_put per key (no per-slab device
        round-trips; the transfer itself is the floor on a slow link).
        Shape mismatches (resumes that changed buffer.size or env count)
        deactivate the cache — the host feed path still trains fine."""
        if not self.active:
            return
        subs = rb.buffer
        if len(subs) != self.n_envs or any(b.buffer_size != self.capacity for b in subs):
            # unreachable from maybe_create_for (which sizes the cache from
            # this rb); direct callers get a hard error
            raise ValueError(
                f"host buffer ({len(subs)} envs x "
                f"{subs[0].buffer_size if subs else 0}) does not match the "
                f"cache ({self.n_envs} x {self.capacity})"
            )
        example = None
        for b in subs:
            if b.buffer:
                example = {k: np.asarray(v[:1]) for k, v in b.buffer.items()}
                break
        if example is None:
            return  # nothing stored yet
        if self._budget is not None and self.estimate_bytes(example) > self._budget:
            self.active = False
            return
        bufs = {}
        for k, v0 in example.items():
            parts = []
            for b in subs:
                if b.buffer and k in b.buffer:
                    parts.append(np.asarray(b.buffer[k]))
                else:
                    parts.append(np.zeros((self.capacity, 1, *v0.shape[2:]), v0.dtype))
            host = np.ascontiguousarray(
                np.concatenate(parts, axis=1), dtype=_store_dtype(v0.dtype)
            )  # (cap, n_envs, *feat)
            bufs[k] = (
                jax.device_put(host, self._device) if self._device is not None else jnp.asarray(host)
            )
        self._bufs = bufs
        self._pos = np.asarray([b._pos for b in subs], dtype=np.int32)
        self._filled = np.asarray(
            [b.buffer_size if b.full else b._pos for b in subs], dtype=np.int32
        )

    # ------------------------------------------------------------- read
    def can_sample(self, seq_len: int) -> bool:
        return self.active and self._bufs is not None and bool(np.all(self._filled >= seq_len))

    def sample(self, n_samples: int, batch_size: int, seq_len: int, key) -> List[Dict[str, jax.Array]]:
        """Draw ``n_samples`` independent (seq_len, batch, *feat) batches as
        a list of device dicts (one per gradient step), mirroring the host
        path's ``rb.sample(...)`` + per-sample feed."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if not self.can_sample(seq_len):
            raise ValueError(
                f"Cannot sample a sequence of length {seq_len}. "
                f"Data added so far: {int(self._filled.min())}"
            )
        out = _sample(
            self._bufs,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            n_samples=int(n_samples),
            batch_size=int(batch_size),
            seq_len=int(seq_len),
            cap=self.capacity,
            n_envs=self.n_envs,
        )
        return [{k: v[i] for k, v in out.items()} for i in range(n_samples)]

    def sample_transitions(
        self,
        n_samples: int,
        batch_size: int,
        key,
        sample_next_obs: bool = False,
        obs_keys: Sequence[str] = (),
    ) -> Dict[str, jax.Array]:
        """Flat-transition draw mirroring ``ReplayBuffer.sample`` — returns
        one device dict shaped (n_samples, batch, *feat) (+ ``next_<k>``
        for ``obs_keys`` when ``sample_next_obs``)."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        need = 2 if sample_next_obs else 1
        if not (self.active and self._bufs is not None and int(self._filled.min()) >= need):
            raise ValueError("Not enough data in the device cache, add first")
        return _sample_transitions(
            self._bufs,
            jnp.asarray(key),
            jnp.asarray(self._pos),
            jnp.asarray(self._filled),
            n_samples=int(n_samples),
            batch_size=int(batch_size),
            cap=self.capacity,
            n_envs=self.n_envs,
            next_keys=tuple(obs_keys) if sample_next_obs else (),
        )

    def can_sample_transitions(self, sample_next_obs: bool = False) -> bool:
        need = 2 if sample_next_obs else 1
        return self.active and self._bufs is not None and bool(np.all(self._filled >= need))

    def load_from_replay(self, rb) -> None:
        """Refill from a plain (flat-transition) ``ReplayBuffer``."""
        if not self.active:
            return
        if rb.buffer_size != self.capacity or rb.n_envs != self.n_envs:
            # unreachable from maybe_create_for_transitions (which sizes the
            # cache from this rb); direct callers get a hard error
            raise ValueError(
                f"host buffer ({rb.n_envs} envs x {rb.buffer_size}) does not "
                f"match the cache ({self.n_envs} x {self.capacity})"
            )
        if not rb.buffer:
            return  # nothing stored yet
        example = {k: np.asarray(v[:1]) for k, v in rb.buffer.items()}
        if self._budget is not None and self.estimate_bytes(example) > self._budget:
            self.active = False
            return
        self._bufs = {
            k: (
                jax.device_put(
                    np.ascontiguousarray(np.asarray(v), dtype=_store_dtype(v.dtype)),
                    self._device,
                )
                if self._device is not None
                else jnp.asarray(np.ascontiguousarray(np.asarray(v), dtype=_store_dtype(v.dtype)))
            )
            for k, v in rb.buffer.items()
        }
        pos = int(rb._pos)
        filled = self.capacity if rb.full else pos
        self._pos = np.full(self.n_envs, pos, dtype=np.int32)
        self._filled = np.full(self.n_envs, filled, dtype=np.int32)

    # ------------------------------------------------------------ factory
    @classmethod
    def maybe_create(cls, cfg, runtime, capacity: int, n_envs: int) -> Optional["DeviceReplayCache"]:
        """Create when gating allows (see module docstring), else None."""
        mode = device_cache_setting(cfg)
        if mode == "off":
            return None
        if runtime.device_count != 1 or jax.process_count() != 1:
            if mode == "on":
                print(
                    "DeviceReplayCache: buffer.device_cache=True ignored — the cache "
                    "is single-device only (a replicated cache multiplies HBM cost); "
                    "multi-device runs keep the host feed path"
                )
            return None
        if mode == "auto" and runtime.device.platform == "cpu":
            return None  # host-platform run: device_put is free, no win
        budget_gb = float(cfg.buffer.get("device_cache_budget_gb", 6.0))
        cache = cls(
            capacity,
            n_envs,
            device=runtime.device,
            budget_bytes=int(budget_gb * 1e9) if mode == "auto" else None,
        )
        print(
            f"DeviceReplayCache: HBM-resident replay window enabled "
            f"(capacity {capacity} x {n_envs} envs, mode={mode})"
        )
        return cache

