from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
    get_array,
)
from sheeprl_tpu.data.feed import DevicePrefetcher

__all__ = [
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "ReplayBuffer",
    "SequentialReplayBuffer",
    "get_array",
    "DevicePrefetcher",
]
