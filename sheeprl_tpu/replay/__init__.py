"""Reverb-style replay subsystem: device-resident prioritized sampling,
samples-per-insert rate control, and a remote replay service for the
N-player decoupled topology.

Three pillars (Cassirer et al., 2021; Schaul et al., 2016 — see PAPERS.md):

- :mod:`sheeprl_tpu.replay.priority_tree` — a JAX binary sum-tree living
  in device memory alongside the ``DeviceReplayCache`` rings: O(log n)
  proportional sampling inside the jitted sample step, β-annealed
  importance-sampling weights, batched priority updates from the train
  steps' TD errors;
- :mod:`sheeprl_tpu.replay.rate_limiter` — a SamplesPerInsert limiter
  with Reverb semantics (target ratio + error budget) that throttles
  whichever side of the collect/train pipeline runs ahead, in coupled
  loops and across the decoupled transport (credit messages);
- :mod:`sheeprl_tpu.replay.service` — the ReplayWriter/ReplayServer pair
  that runs the buffer in the trainer process and accepts inserts from N
  players over the PR-4 ``queue|shm|tcp`` transports, so decoupled
  off-policy runs get player→replay-writer→prioritized-sampler instead
  of ad-hoc sampled-batch shipping.
"""

from sheeprl_tpu.replay.priority_tree import (
    PriorityTree,
    per_beta_schedule,
    priority_from_td,
)
from sheeprl_tpu.replay.rate_limiter import RateLimiter, rate_limiter_from_cfg
from sheeprl_tpu.replay.service import (
    RB_CREDIT_TAG,
    RB_INSERT_TAG,
    ReplayServer,
    ReplayWriter,
    remote_replay_setting,
)

__all__ = [
    "PriorityTree",
    "per_beta_schedule",
    "priority_from_td",
    "RateLimiter",
    "rate_limiter_from_cfg",
    "RB_CREDIT_TAG",
    "RB_INSERT_TAG",
    "ReplayServer",
    "ReplayWriter",
    "remote_replay_setting",
]
