"""SamplesPerInsert rate control for the replay path (Reverb semantics).

When collection and training are decoupled — across processes (the
N-player topology) or merely across threads (the overlapped pipeline) —
the effective replay ratio becomes an accident of relative process
speeds: a fast trainer over-fits the early buffer, a fast collector
starves training of gradient steps.  Reverb (Cassirer et al., 2021, §2.3)
makes the ratio a first-class constraint: a limiter tracks the signed
error between observed samples and the target ``samples_per_insert``
ratio and blocks whichever side runs ahead once the error leaves a
configured budget.

Accounting (Reverb's ``SampleToInsertRatio``): with ``spi`` the target
samples-per-insert, the tracked quantity is::

    diff = inserts * spi - samples          # "sample credit"

- an insert is allowed when ``diff + spi <= max_diff`` (collecting more
  would let training fall too far behind);
- a sample is allowed when ``diff - n >= min_diff`` AND at least
  ``min_size_to_sample`` items were inserted (training more would race
  ahead of collection);
- ``error_buffer`` centers the ``[min_diff, max_diff]`` window on the
  point where exactly ``min_size_to_sample`` items are in and none were
  sampled, i.e. ``min_size_to_sample*spi ± error_buffer``.

Units are TRANSITIONS on both sides (one env frame in, one sampled batch
element out), so ``spi ≈ replay_ratio * batch_size / n_envs_per_step``
relates it to the ``Ratio`` schedule's gradient-steps-per-policy-step.

Single-thread (coupled) loops cannot block themselves: they use the
non-blocking ``sample_allowance``/``insert_allowed`` queries to throttle
whichever side is ahead (skip the gradient dispatch / hold the env step
accounting).  Decoupled loops block for real: players wait on insert
credits granted over the transport (see :mod:`sheeprl_tpu.replay.service`)
and the trainer waits in :meth:`await_can_sample`.  Every stall is
counted and timed — the stats ride the telemetry ``replay`` key so a
throttled run is visible in ``telemetry.jsonl``, not just slow.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

__all__ = ["RateLimiter", "rate_limiter_from_cfg"]


class RateLimiter:
    """Thread-safe samples-per-insert limiter with an error budget."""

    def __init__(
        self,
        samples_per_insert: float,
        *,
        min_size_to_sample: int = 1,
        error_buffer: Optional[float] = None,
        min_diff: Optional[float] = None,
        max_diff: Optional[float] = None,
    ):
        if samples_per_insert <= 0:
            raise ValueError(f"samples_per_insert must be > 0, got {samples_per_insert}")
        if min_size_to_sample < 1:
            raise ValueError(f"min_size_to_sample must be >= 1, got {min_size_to_sample}")
        self.spi = float(samples_per_insert)
        self.min_size_to_sample = int(min_size_to_sample)
        if error_buffer is None and min_diff is None and max_diff is None:
            # a window this tight would deadlock a batched sampler; default
            # to one batch-ish of slack on each side
            error_buffer = max(self.spi, 1.0)
        center = self.min_size_to_sample * self.spi
        if error_buffer is not None:
            if min_diff is not None or max_diff is not None:
                raise ValueError("pass either error_buffer or explicit min_diff/max_diff, not both")
            self.min_diff = center - float(error_buffer)
            self.max_diff = center + float(error_buffer)
        else:
            self.min_diff = float(min_diff) if min_diff is not None else float("-inf")
            self.max_diff = float(max_diff) if max_diff is not None else float("inf")
        if self.min_diff > self.max_diff:
            raise ValueError(f"min_diff ({self.min_diff}) > max_diff ({self.max_diff})")
        self._cond = threading.Condition()
        self.inserts = 0
        self.samples = 0
        self.insert_stalls = 0
        self.sample_stalls = 0
        self.insert_stall_s = 0.0
        self.sample_stall_s = 0.0

    # ----------------------------------------------------------- queries
    def _diff(self) -> float:
        return self.inserts * self.spi - self.samples

    def can_insert(self, n: int = 1) -> bool:
        with self._cond:
            return self._can_insert(n)

    def _can_insert(self, n: int) -> bool:
        return self._diff() + n * self.spi <= self.max_diff

    def can_sample(self, n: int = 1) -> bool:
        with self._cond:
            return self._can_sample(n)

    def _can_sample(self, n: int) -> bool:
        return self.inserts >= self.min_size_to_sample and self._diff() - n >= self.min_diff

    def insert_allowance(self, max_n: int) -> int:
        """How many of ``max_n`` inserts are allowed right now."""
        with self._cond:
            room = self.max_diff - self._diff()
            return max(0, min(int(max_n), int(room // self.spi)))

    def sample_allowance(self, max_n: int) -> int:
        """How many of ``max_n`` samples are allowed right now (0 until
        ``min_size_to_sample`` items are in)."""
        with self._cond:
            if self.inserts < self.min_size_to_sample:
                return 0
            return max(0, min(int(max_n), int(self._diff() - self.min_diff)))

    # ----------------------------------------------------------- records
    def insert(self, n: int = 1) -> None:
        """Record ``n`` inserted items (never blocks; pair with
        :meth:`await_can_insert` for enforcement)."""
        with self._cond:
            self.inserts += int(n)
            self._cond.notify_all()

    def sample(self, n: int = 1) -> None:
        with self._cond:
            self.samples += int(n)
            self._cond.notify_all()

    # ---------------------------------------------------------- blocking
    def _await(self, check, n: int, timeout: Optional[float], stall_attr: str, alive) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            if check(n):
                return True
            setattr(self, stall_attr + "_stalls", getattr(self, stall_attr + "_stalls") + 1)
            t0 = time.monotonic()
            try:
                while not check(n):
                    if alive is not None and not alive():
                        return False
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        return False
                    self._cond.wait(timeout=0.2 if remaining is None else min(0.2, remaining))
                return True
            finally:
                setattr(
                    self, stall_attr + "_stall_s", getattr(self, stall_attr + "_stall_s") + time.monotonic() - t0
                )

    def await_can_insert(self, n: int = 1, timeout: Optional[float] = None, alive=None) -> bool:
        """Block until ``n`` inserts are allowed; False on timeout or when
        ``alive()`` turns false.  Stall count/seconds are recorded."""
        return self._await(self._can_insert, n, timeout, "insert", alive)

    def await_can_sample(self, n: int = 1, timeout: Optional[float] = None, alive=None) -> bool:
        return self._await(self._can_sample, n, timeout, "sample", alive)

    # --------------------------------------------------------- telemetry
    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "spi_target": self.spi,
                "inserts": self.inserts,
                "samples": self.samples,
                "spi_observed": round(self.samples / self.inserts, 4) if self.inserts else None,
                "error": round(self._diff(), 2),
                "min_diff": self.min_diff,
                "max_diff": self.max_diff,
                "insert_stalls": self.insert_stalls,
                "sample_stalls": self.sample_stalls,
                "insert_stall_s": round(self.insert_stall_s, 3),
                "sample_stall_s": round(self.sample_stall_s, 3),
            }

    # -------------------------------------------------------- checkpoint
    def state_dict(self) -> Dict[str, int]:
        with self._cond:
            return {"inserts": self.inserts, "samples": self.samples}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        with self._cond:
            self.inserts = int(state["inserts"])
            self.samples = int(state["samples"])
            self._cond.notify_all()


# fields accepted under ``buffer.rate_limiter`` (hydra dict)
def rate_limiter_from_cfg(cfg, *, default_min_size: int = 1) -> Optional[RateLimiter]:
    """Build a limiter from ``cfg.buffer.rate_limiter`` or return None
    when rate control is off (``samples_per_insert`` null/absent)."""
    rl_cfg = cfg.buffer.get("rate_limiter", None) or {}
    spi = rl_cfg.get("samples_per_insert", None)
    if spi is None:
        return None
    min_size = rl_cfg.get("min_size_to_sample", None)
    error_buffer = rl_cfg.get("error_buffer", None)
    return RateLimiter(
        float(spi),
        min_size_to_sample=int(min_size) if min_size is not None else int(default_min_size),
        error_buffer=float(error_buffer) if error_buffer is not None else None,
    )
