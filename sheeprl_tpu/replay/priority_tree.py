"""Device-resident binary sum-tree for proportional prioritized replay.

Prioritized Experience Replay (Schaul et al., 2016) samples transition i
with probability p_i^α / Σ p^α and corrects the induced bias with
importance-sampling weights w_i = (N · P(i))^-β.  The classical host
implementation is a mutable array-backed segment tree; here the tree is a
single flat ``jax.Array`` living on the training device next to the
``DeviceReplayCache`` rings, so sampling stays inside the jitted sample
step — an O(log n) vectorized descent, no host round-trips — exactly the
property that makes the device cache pay on remote-link TPU setups.

Layout: 1-based heap in a ``(2·P,)`` float32 array where ``P`` is the
leaf count padded to a power of two; index 0 is unused, the root (total
mass) sits at 1, leaves at ``[P, 2·P)``.  All kernels take the depth
``log2(P)`` statically, so the per-level loops unroll into a fixed
gather/scatter chain XLA fuses well.

Batched updates with duplicate leaf indices are safe: the leaf scatter
picks one writer per duplicate (callers that can produce duplicates —
``update_priorities`` with a batch that sampled the same transition
twice — pass equal values per duplicate within one call), and parents
are rebuilt bottom-up from the final child values, so the tree is always
internally consistent.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "PriorityTree",
    "ShardedPriorityTree",
    "per_beta_schedule",
    "priority_from_td",
    "resolve_per_kernel",
    "shard_proportional_draw",
]


def resolve_per_kernel(value) -> str:
    """Validate ``buffer.per_kernel``: ``lax`` (default — the gather/
    scatter-chain kernels below, bit-exact with the pre-kernel tree) or
    ``pallas`` (ops/pallas_per.py fused kernels, interpret mode off-TPU)."""
    s = str(value).lower()
    if s not in ("lax", "pallas"):
        raise ValueError(f"buffer.per_kernel must be 'lax' or 'pallas', got {value!r}")
    return s


def priority_from_td(td_abs, alpha: float, eps: float):
    """Schaul proportional priority: (|δ| + ε)^α (works on jnp or np)."""
    return (abs(td_abs) + eps) ** alpha


def per_beta_schedule(beta0: float, beta_end: float, total_steps: int):
    """Linear β annealing (Schaul §3.4: anneal the IS correction toward 1
    as training converges).  Returns ``step -> β`` on host floats."""
    beta0 = float(beta0)
    beta_end = float(beta_end)
    span = max(int(total_steps), 1)

    def beta(step: int) -> float:
        frac = min(max(float(step) / span, 0.0), 1.0)
        return beta0 + (beta_end - beta0) * frac

    return beta


def _write_impl(tree, leaf_idx, values, active, depth):
    """Set ``leaf_idx`` to ``values`` where ``active``, keep the rest, and
    rebuild the touched ancestor paths bottom-up.

    Inactive entries are REDIRECTED to heap slot 0 (unused by the 1-based
    layout) instead of writing their current value back: a masked-out
    duplicate of an active leaf would otherwise win the one-writer-per-
    duplicate scatter and silently drop the active write — exactly what
    the sharded tree's per-shard ownership masks produce (every global
    batch of leaves contains each local leaf once per shard, active on
    exactly one)."""
    p = 1 << depth
    node = jnp.where(active, leaf_idx.astype(jnp.int32) + p, 0)
    tree = tree.at[node].set(jnp.where(active, values.astype(tree.dtype), tree[0]))
    for _ in range(depth):
        node = node >> 1  # inactive chains stay parked at slot 0
        tree = tree.at[node].set(tree[2 * node] + tree[2 * node + 1])
    return tree


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("depth",))
def _tree_write(tree, leaf_idx, values, active, *, depth):
    return _write_impl(tree, leaf_idx, values, active, depth)


@functools.partial(jax.jit, static_argnames=("depth",))
def _tree_zeroed(tree, leaf_idx, active, *, depth):
    """Functional copy with ``leaf_idx`` zeroed where ``active`` — the
    sampling-time exclusion mask (write-head rows whose next-obs is stale,
    ring cells too close to the head to start a full sequence).  The
    stored tree is untouched."""
    return _write_impl(tree, leaf_idx, jnp.zeros(leaf_idx.shape, tree.dtype), active, depth)


def _descend(tree, u, depth):
    """Vectorized root-to-leaf descent shared by the single-device sampler
    and the per-shard bodies of the sharded one: ``u`` in [0, total mass)
    -> (leaf index, leaf mass)."""
    p = 1 << depth
    node = jnp.ones(u.shape, jnp.int32)
    for _ in range(depth):
        left = tree[2 * node]
        go_right = u >= left
        u = jnp.where(go_right, u - left, u)
        node = 2 * node + go_right.astype(jnp.int32)
    return node - p, tree[node]


def _tree_zeroed_local(tree, leaf_idx, depth):
    """Raw (un-jitted) functional zeroing for use INSIDE shard_map bodies:
    same semantics as :func:`_tree_zeroed` on a shard-local sub-tree."""
    leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
    return _write_impl(
        tree, leaf_idx, jnp.zeros(leaf_idx.shape, tree.dtype), jnp.ones(leaf_idx.shape, bool), depth
    )


@functools.partial(jax.jit, static_argnames=("n", "depth"))
def _tree_sample(tree, key, beta, count, *, n, depth):
    """Draw ``n`` leaves proportional to priority + their IS weights.

    ``count`` is the number of live transitions N in the IS correction
    w_i = (N · P(i))^-β, normalized by the batch max (Schaul §3.4) so
    weights only ever scale losses DOWN.
    """
    total = tree[1]
    u = jax.random.uniform(key, (n,)) * total
    leaf, mass = _descend(tree, u, depth)
    # float-rounding guard: a draw can skid into a zero-mass leaf at a
    # subtree boundary; fold it onto the heaviest neighbor direction by
    # clamping the probability floor instead of resampling (probability
    # ~ulp, bias unmeasurable, and the kernel stays branch-free)
    probs = jnp.maximum(mass, jnp.finfo(tree.dtype).tiny) / jnp.maximum(total, jnp.finfo(tree.dtype).tiny)
    w = (jnp.maximum(count.astype(tree.dtype), 1.0) * probs) ** (-beta)
    w = w / jnp.max(w)
    return leaf, w


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("depth",))
def _tree_update(tree, max_p, leaf_idx, priorities, active, *, depth):
    new_max = jnp.maximum(max_p, jnp.max(jnp.where(active, priorities, 0.0)))
    tree = _write_impl(tree, leaf_idx, priorities, active, depth)
    return tree, new_max


class PriorityTree:
    """Handle owning the device sum-tree + the running max priority.

    ``n_leaves`` is the flat transition-cell count (the cache maps
    ``(row, env) -> row * n_envs + env``).  ``max_priority`` stays a
    device scalar: seeding appends and folding in TD updates never sync
    to the host.
    """

    def __init__(
        self,
        n_leaves: int,
        *,
        alpha: float = 0.6,
        eps: float = 1e-6,
        device=None,
        initial_priority: float = 1.0,
        kernel: str = "lax",
    ):
        if n_leaves <= 0:
            raise ValueError(f"n_leaves must be positive, got {n_leaves}")
        self.n_leaves = int(n_leaves)
        self.alpha = float(alpha)
        self.eps = float(eps)
        self.kernel = resolve_per_kernel(kernel)
        self.depth = max(int(self.n_leaves - 1).bit_length(), 1)
        self._device = device
        with jax.default_device(device) if device is not None else _null():
            self.tree = jnp.zeros(2 << self.depth, dtype=jnp.float32)
            self.max_priority = jnp.asarray(float(initial_priority), dtype=jnp.float32)

    # ------------------------------------------------------------- write
    def _write_tree(self, leaf_idx, values, active):
        """Route one scatter-update through the configured kernel (same
        semantics either way; pallas fuses scatter + rebuild into one
        ops/pallas_per.py program)."""
        if self.kernel == "pallas":
            from sheeprl_tpu.ops.pallas_per import sum_tree_write

            return sum_tree_write(self.tree, leaf_idx, values, active, depth=self.depth)
        return _tree_write(self.tree, leaf_idx, values, active, depth=self.depth)

    def seed_max(self, leaf_idx, active) -> None:
        """Priority-seeded insert: new cells enter at the running max
        priority so every transition is trained on at least once before
        its priority can decay (Schaul §3.3 'new transitions arrive at
        maximal priority')."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        vals = jnp.broadcast_to(self.max_priority, leaf_idx.shape)
        self.tree = self._write_tree(leaf_idx, vals, jnp.asarray(active))

    def update(self, leaf_idx, td_abs, active=None) -> None:
        """TD-error feedback from the train step: p = (|δ| + ε)^α."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        if active is None:
            active = jnp.ones(leaf_idx.shape, bool)
        pri = priority_from_td(jnp.asarray(td_abs, jnp.float32).reshape(leaf_idx.shape), self.alpha, self.eps)
        if self.kernel == "pallas":
            from sheeprl_tpu.ops.pallas_per import sum_tree_update

            self.tree, self.max_priority = sum_tree_update(
                self.tree, self.max_priority, leaf_idx, pri, jnp.asarray(active), depth=self.depth
            )
            return
        self.tree, self.max_priority = _tree_update(
            self.tree, self.max_priority, leaf_idx, pri, jnp.asarray(active), depth=self.depth
        )

    def scale(self, leaf_idx, factor: float) -> None:
        """Multiply the priorities at ``leaf_idx`` by ``factor`` (duplicate
        indices scale once — gather-then-write).  Used for decay-on-sample
        recency bias when no TD signal drives the priorities."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32).reshape(-1)
        vals = self.priorities(leaf_idx) * jnp.float32(factor)
        self.tree = self._write_tree(leaf_idx, vals, jnp.ones(leaf_idx.shape, bool))

    def set_priorities(self, leaf_idx, priorities, active=None) -> None:
        """Raw priority write (restore path / tests)."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        if active is None:
            active = jnp.ones(leaf_idx.shape, bool)
        self.tree = self._write_tree(
            leaf_idx, jnp.asarray(priorities, jnp.float32), jnp.asarray(active)
        )

    # ------------------------------------------------------------- read
    def sample(
        self, key, n: int, *, beta: float, count, exclude_idx=None, exclude_active=None
    ) -> Tuple[jax.Array, jax.Array]:
        """Proportional draw of ``n`` leaves (+ β-corrected IS weights).

        ``exclude_idx``/``exclude_active`` zero those cells in a
        functional copy first — the stored priorities survive (used for
        the stale-next-obs head row and invalid sequence starts).  The
        pallas kernel applies the same exclusions as in-descent mass
        corrections instead (no tree copy; excluded indices must be
        distinct where active — true for every data-plane caller)."""
        if self.kernel == "pallas":
            from sheeprl_tpu.ops.pallas_per import sum_tree_sample

            return sum_tree_sample(
                self.tree,
                key,
                jnp.asarray(beta, jnp.float32),
                jnp.asarray(count, jnp.float32),
                n=int(n),
                depth=self.depth,
                exclude_idx=exclude_idx,
                exclude_active=exclude_active,
            )
        tree = self.tree
        if exclude_idx is not None:
            ex = jnp.asarray(exclude_idx, jnp.int32)
            act = (
                jnp.asarray(exclude_active)
                if exclude_active is not None
                else jnp.ones(ex.shape, bool)
            )
            tree = _tree_zeroed(tree, ex, act, depth=self.depth)
        return _tree_sample(
            tree,
            jnp.asarray(key),
            jnp.asarray(beta, jnp.float32),
            jnp.asarray(count, jnp.float32),
            n=int(n),
            depth=self.depth,
        )

    def priorities(self, leaf_idx) -> jax.Array:
        leaf = jnp.asarray(leaf_idx, jnp.int32) + (1 << self.depth)
        return self.tree[leaf]

    @property
    def total(self) -> float:
        return float(self.tree[1])

    # ------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        """Leaf priorities + running max as host numpy (rides the
        CheckpointManager snapshot; internal nodes are derived state)."""
        p = 1 << self.depth
        return {
            "leaves": np.asarray(self.tree[p : p + self.n_leaves]),
            "max_priority": np.asarray(self.max_priority),
            "alpha": self.alpha,
            "eps": self.eps,
        }

    def load_state_dict(self, state: dict) -> None:
        leaves = np.asarray(state["leaves"], np.float32)
        if leaves.shape[0] != self.n_leaves:
            raise ValueError(
                f"priority state has {leaves.shape[0]} leaves, tree expects {self.n_leaves}"
            )
        p = 1 << self.depth
        full = np.zeros(2 << self.depth, np.float32)
        full[p : p + self.n_leaves] = leaves
        # rebuild internal nodes host-side in one pass (resume cadence only)
        for node in range(p - 1, 0, -1):
            full[node] = full[2 * node] + full[2 * node + 1]
        with jax.default_device(self._device) if self._device is not None else _null():
            self.tree = jnp.asarray(full)
            self.max_priority = jnp.asarray(float(state["max_priority"]), jnp.float32)


def _null():
    import contextlib

    return contextlib.nullcontext()


# --------------------------------------------------------------------- sharded
def shard_proportional_draw(
    tree,
    key,
    rank,
    n_shards,
    axes,
    *,
    n,
    depth,
    kernel: str = "lax",
    exclude_idx=None,
    exclude_active=None,
):
    """Globally-proportional draw from per-shard sub-trees, callable ONLY
    inside a ``shard_map`` body (it issues collectives over ``axes``).

    Conceptually the global mass space is the concatenation of every
    shard's sub-tree mass; the single cross-shard reduction is ONE
    ``psum`` assembling the per-shard total masses (the scalar vector all
    shards need to place their interval in the global CDF).  Every shard
    then draws the SAME ``n`` uniforms (the key is deliberately not
    rank-folded), descends its own sub-tree for all of them, and owns
    exactly the draws whose ``u`` falls inside its mass interval — so
    each global draw has exactly one owner and the aggregate marginals
    are IDENTICAL to a single global sum-tree's (the parity property the
    multi-device PER tests pin).

    Returns ``(local_leaf, mass, own, total)``: the shard-local leaf and
    its mass for ALL n draws (garbage where ``own`` is False — mask
    before any cross-shard assembly), the ownership mask, and the global
    total mass (replicated).

    ``kernel="pallas"`` descends each shard's sub-tree through the fused
    ops/pallas_per.py kernel and folds shard-local sampling exclusions
    into the descent as mass corrections (``exclude_idx`` — the lax path
    instead expects the caller to pre-zero a functional sub-tree copy,
    the historical contract, so exclusions are pallas-only here)."""
    if kernel == "pallas":
        from sheeprl_tpu.ops.pallas_per import _excl_args, _excluded_mass, sum_tree_descend

        excl, eact = _excl_args(n, exclude_idx, exclude_active)
        m_local = tree[1] - jnp.sum(_excluded_mass(tree, excl, eact, depth))
    else:
        if exclude_idx is not None:
            raise ValueError("exclude_idx on the lax path: pre-zero the sub-tree instead")
        m_local = tree[1]
    masses = jax.lax.psum(
        jnp.zeros((n_shards,), tree.dtype).at[rank].set(m_local), axes
    )
    prefix = jnp.concatenate([jnp.zeros((1,), tree.dtype), jnp.cumsum(masses)])
    total = prefix[-1]
    # clamp the unit draws below 1: u == total would fall outside every
    # shard's half-open interval (float rounding can push r * total up to
    # total exactly); the 1e-7 relative clamp is ~1 ulp in f32
    r01 = jnp.minimum(jax.random.uniform(key, (n,)), jnp.float32(1.0 - 1e-7))
    u = r01 * total
    lo = prefix[rank]
    hi = prefix[rank + 1]
    own = (u >= lo) & (u < hi)
    # cumsum rounding can make (hi - lo) exceed this shard's own mass by
    # an ulp; keep the local descent strictly inside the sub-tree
    u_loc = jnp.clip(u - lo, 0.0, m_local * (1.0 - 1e-7))
    if kernel == "pallas":
        leaf, mass = sum_tree_descend(
            tree, u_loc, depth=depth, exclude_idx=excl, exclude_active=eact
        )
    else:
        leaf, mass = _descend(tree, u_loc, depth)
    return leaf, mass, own, total


class ShardedPriorityTree:
    """Shard-aware counterpart of :class:`PriorityTree` for the env-sharded
    :class:`~sheeprl_tpu.data.device_buffer.ShardedDeviceReplayCache`.

    Each device owns an independent sub-tree over ITS env columns' cells
    (leaf = row * n_local_envs + env_local); the sub-trees ride stacked as
    one ``(n_shards, 2·P)`` array sharded over the mesh batch axes, so
    every write is a single shard_map dispatch where each device scatters
    only the leaves it owns and sampling needs exactly one psum'd
    total-mass reduction per draw (:func:`shard_proportional_draw`).

    The host-facing API mirrors :class:`PriorityTree` verbatim — GLOBAL
    cell indices in, checkpoint state in global leaf order — so the cache
    and the checkpoint schema cannot tell the two apart (a run may resume
    sharded from a single-device tree state and vice versa).
    """

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        n_shards: int,
        mesh,
        *,
        alpha: float = 0.6,
        eps: float = 1e-6,
        initial_priority: float = 1.0,
        kernel: str = "lax",
    ):
        from sheeprl_tpu.parallel.sharding import BATCH_AXES
        from jax.sharding import NamedSharding, PartitionSpec as P

        if n_envs % n_shards:
            raise ValueError(f"n_envs ({n_envs}) must divide over {n_shards} shards")
        self.capacity = int(capacity)
        self.n_envs = int(n_envs)
        self.n_shards = int(n_shards)
        self.n_local_envs = self.n_envs // self.n_shards
        self.n_leaves = self.capacity * self.n_envs
        self.n_leaves_local = self.capacity * self.n_local_envs
        self.alpha = float(alpha)
        self.eps = float(eps)
        self.kernel = resolve_per_kernel(kernel)
        self.depth = max(int(self.n_leaves_local - 1).bit_length(), 1)
        self._mesh = mesh
        self._axes = BATCH_AXES
        self._tree_sharding = NamedSharding(mesh, P(BATCH_AXES, None))
        self._replicated = NamedSharding(mesh, P())
        # device-native zeros (NOT a numpy temp): the write kernels donate
        # ``trees``, and donating a buffer that zero-copy aliases host
        # memory is the PR-3 heap-corruption class
        self.trees = jax.device_put(
            jnp.zeros((self.n_shards, 2 << self.depth), jnp.float32), self._tree_sharding
        )
        self.max_priority = jax.device_put(jnp.float32(initial_priority), self._replicated)
        self._write_fn = self._build_write()

    # ------------------------------------------------------------- mapping
    def _map_leaves(self, leaf_idx):
        """Global cell id -> (owning shard, shard-local leaf).  Works on
        jnp or np arrays (pure arithmetic)."""
        row = leaf_idx // self.n_envs
        env = leaf_idx % self.n_envs
        return env // self.n_local_envs, row * self.n_local_envs + env % self.n_local_envs

    def _build_write(self):
        from sheeprl_tpu.utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        axes, n_shards, depth = self._axes, self.n_shards, self.depth
        fsdp = int(self._mesh.shape[self._axes[1]])
        kernel = self.kernel

        def body(trees, max_p, shard_ids, local_leaf, values, active, track_max):
            r = jax.lax.axis_index(axes[0]) * fsdp + jax.lax.axis_index(axes[1])
            act = active & (shard_ids == r)
            if kernel == "pallas":
                from sheeprl_tpu.ops.pallas_per import sum_tree_scatter

                t = sum_tree_scatter(trees[0], local_leaf, values, act, depth=depth)
            else:
                t = _write_impl(trees[0], local_leaf, values, act, depth)
            # running max across every shard's accepted writes: pmax keeps
            # it replicated without a host sync (track_max=False for raw
            # set/scale writes, matching PriorityTree semantics)
            cand = jnp.max(jnp.where(act, values, 0.0))
            new_max = jnp.maximum(max_p, jax.lax.pmax(cand, axes))
            new_max = jnp.where(track_max, new_max, max_p)
            return t[None], new_max

        mapped = shard_map(
            body,
            mesh=self._mesh,
            in_specs=(P(axes, None), P(), P(), P(), P(), P(), P()),
            out_specs=(P(axes, None), P()),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0,))

    def _write(self, leaf_idx, values, active, track_max: bool) -> None:
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32).reshape(-1)
        values = jnp.asarray(values, jnp.float32).reshape(leaf_idx.shape)
        active = jnp.asarray(active).reshape(leaf_idx.shape)
        shard_ids, local_leaf = self._map_leaves(leaf_idx)
        self.trees, self.max_priority = self._write_fn(
            self.trees,
            self.max_priority,
            shard_ids.astype(jnp.int32),
            local_leaf.astype(jnp.int32),
            values,
            active,
            jnp.asarray(track_max),
        )

    # ------------------------------------------------------------- write API
    def seed_max(self, leaf_idx, active) -> None:
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        vals = jnp.broadcast_to(self.max_priority, leaf_idx.shape)
        self._write(leaf_idx, vals, jnp.asarray(active), track_max=False)

    def update(self, leaf_idx, td_abs, active=None) -> None:
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        if active is None:
            active = jnp.ones(leaf_idx.shape, bool)
        pri = priority_from_td(
            jnp.asarray(td_abs, jnp.float32).reshape(leaf_idx.shape), self.alpha, self.eps
        )
        self._write(leaf_idx, pri, jnp.asarray(active), track_max=True)

    def scale(self, leaf_idx, factor: float) -> None:
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32).reshape(-1)
        vals = self.priorities(leaf_idx) * jnp.float32(factor)
        self._write(leaf_idx, vals, jnp.ones(leaf_idx.shape, bool), track_max=False)

    def set_priorities(self, leaf_idx, priorities, active=None) -> None:
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        if active is None:
            active = jnp.ones(leaf_idx.shape, bool)
        self._write(leaf_idx, jnp.asarray(priorities, jnp.float32), jnp.asarray(active), track_max=False)

    # ------------------------------------------------------------- read
    def priorities(self, leaf_idx) -> jax.Array:
        """Per-cell priorities for GLOBAL cell ids (replicated result —
        each shard contributes its own leaves via one masked psum)."""
        from sheeprl_tpu.utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        leaf_idx = jnp.asarray(leaf_idx, jnp.int32).reshape(-1)
        shard_ids, local_leaf = self._map_leaves(leaf_idx)
        axes, depth = self._axes, self.depth
        fsdp = int(self._mesh.shape[self._axes[1]])

        def body(trees, shard_ids, local_leaf):
            r = jax.lax.axis_index(axes[0]) * fsdp + jax.lax.axis_index(axes[1])
            vals = trees[0][local_leaf + (1 << depth)]
            return jax.lax.psum(jnp.where(shard_ids == r, vals, 0.0), axes)

        fn = shard_map(
            body,
            mesh=self._mesh,
            in_specs=(P(axes, None), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(fn)(self.trees, shard_ids.astype(jnp.int32), local_leaf.astype(jnp.int32))

    @property
    def total(self) -> float:
        return float(jnp.sum(self.trees[:, 1]))

    # ------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        """Same schema as :class:`PriorityTree` — leaves in GLOBAL cell
        order, so sharded and single-device runs can resume each other."""
        p = 1 << self.depth
        trees_np = np.asarray(self.trees)  # gathers the shards
        local = trees_np[:, p : p + self.n_leaves_local]
        # (shard, row * n_local + e) -> global order (row, shard, e)
        leaves = (
            local.reshape(self.n_shards, self.capacity, self.n_local_envs)
            .transpose(1, 0, 2)
            .reshape(-1)
        )
        return {
            "leaves": leaves,
            "max_priority": np.asarray(self.max_priority),
            "alpha": self.alpha,
            "eps": self.eps,
        }

    def load_state_dict(self, state: dict) -> None:
        leaves = np.asarray(state["leaves"], np.float32)
        if leaves.shape[0] != self.n_leaves:
            raise ValueError(
                f"priority state has {leaves.shape[0]} leaves, tree expects {self.n_leaves}"
            )
        p = 1 << self.depth
        local = (
            leaves.reshape(self.capacity, self.n_shards, self.n_local_envs)
            .transpose(1, 0, 2)
            .reshape(self.n_shards, self.n_leaves_local)
        )
        full = np.zeros((self.n_shards, 2 << self.depth), np.float32)
        full[:, p : p + self.n_leaves_local] = local
        # rebuild internal nodes host-side per shard (resume cadence only)
        for node in range(p - 1, 0, -1):
            full[:, node] = full[:, 2 * node] + full[:, 2 * node + 1]
        # jnp.array (copy) before placement: the restored trees are donated
        # by the next write, which must never alias the host staging buffer
        self.trees = jax.device_put(jnp.array(full), self._tree_sharding)
        self.max_priority = jax.device_put(
            jnp.float32(float(state["max_priority"])), self._replicated
        )
