"""Device-resident binary sum-tree for proportional prioritized replay.

Prioritized Experience Replay (Schaul et al., 2016) samples transition i
with probability p_i^α / Σ p^α and corrects the induced bias with
importance-sampling weights w_i = (N · P(i))^-β.  The classical host
implementation is a mutable array-backed segment tree; here the tree is a
single flat ``jax.Array`` living on the training device next to the
``DeviceReplayCache`` rings, so sampling stays inside the jitted sample
step — an O(log n) vectorized descent, no host round-trips — exactly the
property that makes the device cache pay on remote-link TPU setups.

Layout: 1-based heap in a ``(2·P,)`` float32 array where ``P`` is the
leaf count padded to a power of two; index 0 is unused, the root (total
mass) sits at 1, leaves at ``[P, 2·P)``.  All kernels take the depth
``log2(P)`` statically, so the per-level loops unroll into a fixed
gather/scatter chain XLA fuses well.

Batched updates with duplicate leaf indices are safe: the leaf scatter
picks one writer per duplicate (callers that can produce duplicates —
``update_priorities`` with a batch that sampled the same transition
twice — pass equal values per duplicate within one call), and parents
are rebuilt bottom-up from the final child values, so the tree is always
internally consistent.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PriorityTree", "per_beta_schedule", "priority_from_td"]


def priority_from_td(td_abs, alpha: float, eps: float):
    """Schaul proportional priority: (|δ| + ε)^α (works on jnp or np)."""
    return (abs(td_abs) + eps) ** alpha


def per_beta_schedule(beta0: float, beta_end: float, total_steps: int):
    """Linear β annealing (Schaul §3.4: anneal the IS correction toward 1
    as training converges).  Returns ``step -> β`` on host floats."""
    beta0 = float(beta0)
    beta_end = float(beta_end)
    span = max(int(total_steps), 1)

    def beta(step: int) -> float:
        frac = min(max(float(step) / span, 0.0), 1.0)
        return beta0 + (beta_end - beta0) * frac

    return beta


def _write_impl(tree, leaf_idx, values, active, depth):
    """Set ``leaf_idx`` to ``values`` where ``active``, keep the rest, and
    rebuild the touched ancestor paths bottom-up."""
    p = 1 << depth
    node = leaf_idx.astype(jnp.int32) + p
    cur = tree[node]
    tree = tree.at[node].set(jnp.where(active, values.astype(tree.dtype), cur))
    for _ in range(depth):
        node = node >> 1
        tree = tree.at[node].set(tree[2 * node] + tree[2 * node + 1])
    return tree


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("depth",))
def _tree_write(tree, leaf_idx, values, active, *, depth):
    return _write_impl(tree, leaf_idx, values, active, depth)


@functools.partial(jax.jit, static_argnames=("depth",))
def _tree_zeroed(tree, leaf_idx, active, *, depth):
    """Functional copy with ``leaf_idx`` zeroed where ``active`` — the
    sampling-time exclusion mask (write-head rows whose next-obs is stale,
    ring cells too close to the head to start a full sequence).  The
    stored tree is untouched."""
    return _write_impl(tree, leaf_idx, jnp.zeros(leaf_idx.shape, tree.dtype), active, depth)


@functools.partial(jax.jit, static_argnames=("n", "depth"))
def _tree_sample(tree, key, beta, count, *, n, depth):
    """Draw ``n`` leaves proportional to priority + their IS weights.

    ``count`` is the number of live transitions N in the IS correction
    w_i = (N · P(i))^-β, normalized by the batch max (Schaul §3.4) so
    weights only ever scale losses DOWN.
    """
    p = 1 << depth
    total = tree[1]
    u = jax.random.uniform(key, (n,)) * total
    node = jnp.ones((n,), jnp.int32)
    for _ in range(depth):
        left = tree[2 * node]
        go_right = u >= left
        u = jnp.where(go_right, u - left, u)
        node = 2 * node + go_right.astype(jnp.int32)
    leaf = node - p
    mass = tree[node]
    # float-rounding guard: a draw can skid into a zero-mass leaf at a
    # subtree boundary; fold it onto the heaviest neighbor direction by
    # clamping the probability floor instead of resampling (probability
    # ~ulp, bias unmeasurable, and the kernel stays branch-free)
    probs = jnp.maximum(mass, jnp.finfo(tree.dtype).tiny) / jnp.maximum(total, jnp.finfo(tree.dtype).tiny)
    w = (jnp.maximum(count.astype(tree.dtype), 1.0) * probs) ** (-beta)
    w = w / jnp.max(w)
    return leaf, w


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("depth",))
def _tree_update(tree, max_p, leaf_idx, priorities, active, *, depth):
    new_max = jnp.maximum(max_p, jnp.max(jnp.where(active, priorities, 0.0)))
    tree = _write_impl(tree, leaf_idx, priorities, active, depth)
    return tree, new_max


class PriorityTree:
    """Handle owning the device sum-tree + the running max priority.

    ``n_leaves`` is the flat transition-cell count (the cache maps
    ``(row, env) -> row * n_envs + env``).  ``max_priority`` stays a
    device scalar: seeding appends and folding in TD updates never sync
    to the host.
    """

    def __init__(
        self,
        n_leaves: int,
        *,
        alpha: float = 0.6,
        eps: float = 1e-6,
        device=None,
        initial_priority: float = 1.0,
    ):
        if n_leaves <= 0:
            raise ValueError(f"n_leaves must be positive, got {n_leaves}")
        self.n_leaves = int(n_leaves)
        self.alpha = float(alpha)
        self.eps = float(eps)
        self.depth = max(int(self.n_leaves - 1).bit_length(), 1)
        self._device = device
        with jax.default_device(device) if device is not None else _null():
            self.tree = jnp.zeros(2 << self.depth, dtype=jnp.float32)
            self.max_priority = jnp.asarray(float(initial_priority), dtype=jnp.float32)

    # ------------------------------------------------------------- write
    def seed_max(self, leaf_idx, active) -> None:
        """Priority-seeded insert: new cells enter at the running max
        priority so every transition is trained on at least once before
        its priority can decay (Schaul §3.3 'new transitions arrive at
        maximal priority')."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        vals = jnp.broadcast_to(self.max_priority, leaf_idx.shape)
        self.tree = _tree_write(self.tree, leaf_idx, vals, jnp.asarray(active), depth=self.depth)

    def update(self, leaf_idx, td_abs, active=None) -> None:
        """TD-error feedback from the train step: p = (|δ| + ε)^α."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        if active is None:
            active = jnp.ones(leaf_idx.shape, bool)
        pri = priority_from_td(jnp.asarray(td_abs, jnp.float32).reshape(leaf_idx.shape), self.alpha, self.eps)
        self.tree, self.max_priority = _tree_update(
            self.tree, self.max_priority, leaf_idx, pri, jnp.asarray(active), depth=self.depth
        )

    def scale(self, leaf_idx, factor: float) -> None:
        """Multiply the priorities at ``leaf_idx`` by ``factor`` (duplicate
        indices scale once — gather-then-write).  Used for decay-on-sample
        recency bias when no TD signal drives the priorities."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32).reshape(-1)
        vals = self.priorities(leaf_idx) * jnp.float32(factor)
        self.tree = _tree_write(
            self.tree, leaf_idx, vals, jnp.ones(leaf_idx.shape, bool), depth=self.depth
        )

    def set_priorities(self, leaf_idx, priorities, active=None) -> None:
        """Raw priority write (restore path / tests)."""
        leaf_idx = jnp.asarray(leaf_idx, jnp.int32)
        if active is None:
            active = jnp.ones(leaf_idx.shape, bool)
        self.tree = _tree_write(
            self.tree, leaf_idx, jnp.asarray(priorities, jnp.float32), jnp.asarray(active), depth=self.depth
        )

    # ------------------------------------------------------------- read
    def sample(
        self, key, n: int, *, beta: float, count, exclude_idx=None, exclude_active=None
    ) -> Tuple[jax.Array, jax.Array]:
        """Proportional draw of ``n`` leaves (+ β-corrected IS weights).

        ``exclude_idx``/``exclude_active`` zero those cells in a
        functional copy first — the stored priorities survive (used for
        the stale-next-obs head row and invalid sequence starts)."""
        tree = self.tree
        if exclude_idx is not None:
            ex = jnp.asarray(exclude_idx, jnp.int32)
            act = (
                jnp.asarray(exclude_active)
                if exclude_active is not None
                else jnp.ones(ex.shape, bool)
            )
            tree = _tree_zeroed(tree, ex, act, depth=self.depth)
        return _tree_sample(
            tree,
            jnp.asarray(key),
            jnp.asarray(beta, jnp.float32),
            jnp.asarray(count, jnp.float32),
            n=int(n),
            depth=self.depth,
        )

    def priorities(self, leaf_idx) -> jax.Array:
        leaf = jnp.asarray(leaf_idx, jnp.int32) + (1 << self.depth)
        return self.tree[leaf]

    @property
    def total(self) -> float:
        return float(self.tree[1])

    # ------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        """Leaf priorities + running max as host numpy (rides the
        CheckpointManager snapshot; internal nodes are derived state)."""
        p = 1 << self.depth
        return {
            "leaves": np.asarray(self.tree[p : p + self.n_leaves]),
            "max_priority": np.asarray(self.max_priority),
            "alpha": self.alpha,
            "eps": self.eps,
        }

    def load_state_dict(self, state: dict) -> None:
        leaves = np.asarray(state["leaves"], np.float32)
        if leaves.shape[0] != self.n_leaves:
            raise ValueError(
                f"priority state has {leaves.shape[0]} leaves, tree expects {self.n_leaves}"
            )
        p = 1 << self.depth
        full = np.zeros(2 << self.depth, np.float32)
        full[p : p + self.n_leaves] = leaves
        # rebuild internal nodes host-side in one pass (resume cadence only)
        for node in range(p - 1, 0, -1):
            full[node] = full[2 * node] + full[2 * node + 1]
        with jax.default_device(self._device) if self._device is not None else _null():
            self.tree = jnp.asarray(full)
            self.max_priority = jnp.asarray(float(state["max_priority"]), jnp.float32)


def _null():
    import contextlib

    return contextlib.nullcontext()
