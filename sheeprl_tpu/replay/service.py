"""Remote replay service for the decoupled N-player topology.

In the PR-4 decoupled SAC, each player owns a shard of the replay buffer
and ships SAMPLED BATCHES to the trainer — the experience path is
whatever the rollout transport does, the trainer has no say in what it
trains on, and prioritization is impossible (no process sees the whole
buffer).  Reverb's architecture (Cassirer et al., 2021) inverts this:
the buffer lives WITH the learner, actors stream raw experience into it,
and the learner samples under its own policy.  This module is that
inversion over the existing ``queue|shm|tcp`` transports:

- :class:`ReplayWriter` — the player-side endpoint: ships each env
  step's ``(T, n_envs, *)`` block as one ``rb_insert`` frame and blocks
  on INSERT CREDITS granted by the trainer (the rate limiter's reach
  across the transport: a trainer that falls behind simply stops
  granting, and the player's stall shows up in telemetry);
- :class:`ReplayServer` — the trainer-side endpoint: drains insert
  frames from all N players into a trainer-resident
  ``EnvIndependentReplayBuffer`` (+ the prioritized ``DeviceReplayCache``
  when ``buffer.prioritized``), routes each player's columns to its env
  shard, seeds priorities on write (max-priority insert), feeds the
  limiter, and grants credits while the SPI budget allows.

The experience path becomes player → replay-writer → prioritized-sampler
instead of player-side uniform sampling.  Everything runs on the trainer
MAIN thread (``pump`` is a bounded drain, not a daemon), so the buffer
needs no locks and the ``replay_server_exit`` fault site can model a
crash of the whole service between two pumps.
"""

from __future__ import annotations

import os
import queue as queue_mod
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.obs import flight
from sheeprl_tpu.resilience.integrity import FrameCorruptError
from sheeprl_tpu.resilience.peer import PeerDiedError

# wire tags of the replay service (the transport treats tags opaquely;
# transport.py re-exports these so the frame vocabulary is documented in
# one place next to data/params/stop)
RB_INSERT_TAG = "rb_insert"
RB_CREDIT_TAG = "rb_credit"

__all__ = [
    "RB_CREDIT_TAG",
    "RB_INSERT_TAG",
    "ReplayServer",
    "ReplayWriter",
    "remote_replay_setting",
]


def remote_replay_setting(cfg) -> bool:
    """Resolve ``buffer.remote_replay`` (env override
    ``SHEEPRL_REMOTE_REPLAY``) to a bool."""
    val = cfg.buffer.get("remote_replay", False)
    env = os.environ.get("SHEEPRL_REMOTE_REPLAY")
    if env is not None:
        val = env
    return str(val).lower() in ("1", "true", "on", "yes")


class ReplayWriter:
    """Player-side insert endpoint over one transport :class:`Channel`.

    ``append`` consumes one insert credit per frame and blocks (pumping
    the channel) when the trainer has stopped granting — that block IS
    the samples-per-insert limiter acting on this player.  Non-credit
    frames drained while pumping (params broadcasts, checkpoint replies)
    land in :attr:`frames` for the caller.
    """

    def __init__(self, channel, n_envs: int, *, initial_credits: int = 2):
        self._chan = channel
        self.n_envs = int(n_envs)
        self.credits = int(initial_credits)
        self.seq = 0
        self.inserts = 0  # transitions shipped
        self.stalls = 0
        self.stall_s = 0.0
        self.frames: deque = deque()  # non-credit frames for the caller

    def pump(self, timeout: float = 0.01) -> None:
        """Drain whatever the channel has within ``timeout``: credits are
        applied, everything else queues for the caller."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = max(deadline - time.monotonic(), 0.01)
            try:
                frame = self._chan.recv(timeout=remaining)
            except queue_mod.Empty:
                return
            if frame.tag == RB_CREDIT_TAG:
                self.credits += int(frame.extra[0]) if frame.extra else 1
                frame.release()
            else:
                self.frames.append(frame)
            if time.monotonic() > deadline:
                return

    def append(
        self,
        step_data: Dict[str, np.ndarray],
        timeout: float = 600.0,
        summary: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Ship one ``(T, n_envs, *)`` block as an ``rb_insert`` frame;
        blocks while no credit is available (limiter throttle).
        ``summary`` (ISSUE 15) piggybacks this player's compact
        live-metrics dict on the frame's extra — the server folds it into
        its fleet view."""
        t_len = next(iter(step_data.values())).shape[0]
        if self.credits <= 0:
            self.stalls += 1
            t0 = time.monotonic()
            deadline = t0 + timeout
            try:
                while self.credits <= 0:
                    if time.monotonic() > deadline:
                        raise queue_mod.Full(
                            f"replay writer starved of insert credits for {timeout:.0f}s "
                            "(trainer stalled or rate limiter budget misconfigured)"
                        )
                    self.pump(0.2)  # PeerDiedError propagates from the channel
            finally:
                self.stall_s += time.monotonic() - t0
        self.credits -= 1
        self.seq += 1
        self._chan.send(
            RB_INSERT_TAG,
            arrays=[(k, v) for k, v in step_data.items()],
            extra=(t_len * self.n_envs,) + ((summary,) if summary is not None else ()),
            seq=self.seq,
            timeout=timeout,
        )
        self.inserts += t_len * self.n_envs

    def stats(self) -> Dict[str, Any]:
        return {
            "inserts": self.inserts,
            "credits": self.credits,
            "insert_stalls": self.stalls,
            "insert_stall_s": round(self.stall_s, 3),
        }


class ReplayServer:
    """Trainer-side replay service: buffer + sampler + credit granting.

    ``channels`` / ``env_shards`` come from ``spawn_players``; the server
    routes player ``p``'s columns into env indices
    ``[offset_p, offset_p + count_p)`` of one trainer-resident
    ``EnvIndependentReplayBuffer`` (per-env rings tolerate players
    inserting at different speeds).  With ``prioritized`` a
    :class:`~sheeprl_tpu.data.device_buffer.DeviceReplayCache` mirrors the
    buffer on the training device and sampling goes through its sum-tree;
    otherwise sampling is the host buffer's uniform path.
    """

    def __init__(
        self,
        buffer_size: int,
        env_shards: Sequence[Tuple[int, int]],
        channels: Dict[int, Any],
        *,
        obs_keys: Sequence[str] = ("observations",),
        limiter=None,
        prioritized: bool = False,
        per_alpha: float = 0.6,
        per_eps: float = 1e-6,
        device=None,
        memmap: bool = False,
        memmap_dir: Optional[str] = None,
        credit_window: int = 2,
        integrity: str = "off",
        ingest_max_abs: float = 1e6,
        per_kernel: str = "lax",
    ):
        from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer
        from sheeprl_tpu.data.device_buffer import DeviceReplayCache

        self.env_shards = list(env_shards)
        total_envs = sum(count for _, count in self.env_shards)
        self.total_envs = total_envs
        self.buffer_size = int(buffer_size)
        self.rb = EnvIndependentReplayBuffer(
            self.buffer_size,
            total_envs,
            obs_keys=tuple(obs_keys),
            memmap=memmap,
            memmap_dir=memmap_dir,
        )
        self.prioritized = bool(prioritized)
        self.cache: Optional[DeviceReplayCache] = (
            DeviceReplayCache(
                self.buffer_size,
                total_envs,
                device=device,
                prioritized=True,
                per_alpha=per_alpha,
                per_eps=per_eps,
                kernel=per_kernel,
            )
            if self.prioritized
            else None
        )
        self.limiter = limiter
        self.channels = dict(channels)
        self.credit_window = max(1, int(credit_window))
        # credits in flight per player (granted, not yet consumed by an
        # ingested frame) — the writer starts with the same initial window
        self._outstanding = {pid: self.credit_window for pid in self.channels}
        self.stopped: set = set()
        self.dead: Dict[int, str] = {}
        # elastic-pool bookkeeping (the supervisor's expected surface —
        # remote players are stateless writers, so ``joining`` is
        # transient: a revived pid is fully live the moment it reconnects)
        self.joining: Dict[int, float] = {}
        self.last_seen: Dict[int, float] = {}
        self._awaiting_first_frame: set = set()
        self.rejoins = 0
        self.events: List[Dict[str, Any]] = []
        self.total_inserts = 0  # transitions (the trainer's policy-step clock)
        self.inserts_by_player = {pid: 0 for pid in self.channels}
        # per-player live-metrics summaries piggybacked on rb_insert
        # frames (ISSUE 15); rides stats() to the lead's /status
        self.fleet: Dict[int, Dict[str, Any]] = {}
        self.credit_stall_players = 0  # grant attempts refused by the limiter
        # insert -> first-sample freshness (ISSUE 16): arrival times of
        # inserts no sample() has run since; the next sample() reads the
        # oldest as the first_sample_age_s SLO gauge and drains the list
        self._unsampled_insert_ts: deque = deque(maxlen=1024)
        self.first_sample_age_s: Optional[float] = None
        # training-sentinel quarantine bookkeeping: ring rows written per
        # env since the last verdict-clean horizon (mark_health_horizon)
        self._rows_since_mark = np.zeros(total_envs, dtype=np.int64)
        self.quarantines = 0
        self.quarantined_rows = 0
        # ingest validation (algo.transport_integrity != off): schema +
        # bounds + finiteness checks on every rb_insert BEFORE it can
        # reach the learner (resilience/integrity.py) — the boundary
        # where the rb_corrupt fault class is *detected* instead of
        # silently absorbed
        self._ingest_guard = None
        if str(integrity) != "off":
            from sheeprl_tpu.resilience.integrity import IngestGuard

            self._ingest_guard = IngestGuard(max_abs=ingest_max_abs)
        self.inserts_quarantined = 0

    # ------------------------------------------------------------ liveness
    @property
    def live(self) -> List[int]:
        return sorted(p for p in self.channels if p not in self.dead and p not in self.stopped)

    @property
    def all_stopped(self) -> bool:
        return not self.live

    def _mark_dead(self, pid: int, reason: str) -> None:
        if pid in self.dead or pid in self.stopped:
            return
        ch = self.channels.get(pid)
        detail = ""
        if ch is not None and getattr(ch, "detail_fn", None) is not None:
            try:
                detail = ch.detail_fn() or ""
            except Exception:
                detail = ""
        # a clean exit means the player finished; its stop frame may have
        # been destroyed by a TCP reset (see FanIn.mark_dead)
        self._awaiting_first_frame.discard(pid)
        if "exitcode=0" in detail.replace(" ", ""):
            self.stopped.add(pid)
            return
        self.dead[pid] = reason
        self.events.append(
            {"event": "player_dead", "player": pid, "reason": reason, "live": len(self.live)}
        )
        if not self.live and not self.stopped and not self.joining:
            raise PeerDiedError(
                "player", "; ".join(f"player[{p}]: {r}" for p, r in self.dead.items())
            )

    # the supervisor calls the public name (FanIn parity)
    def mark_dead(self, pid: int, reason: str) -> None:
        self._mark_dead(pid, reason)

    def begin_join(self, pid: int, channel=None, steps_per_frame: Optional[int] = None) -> None:
        """Re-admit a restarted player (the supervisor's revival hook).

        The stale credit window died with the old process: a fresh
        :class:`ReplayWriter` comes up believing it holds the full initial
        window, so ``_outstanding`` is RESET to match — without this the
        server would under-grant forever (it thinks credits are still in
        flight) and a rejoined player would deadlock on its first stall."""
        if channel is not None:
            self.channels[pid] = channel
        self.dead.pop(pid, None)
        self.stopped.discard(pid)
        self._outstanding[pid] = self.credit_window
        self.inserts_by_player.setdefault(pid, 0)
        # until its first frame lands, sends to a tcp joiner would stall
        # on a socket it has not dialed yet — broadcasts skip it
        self._awaiting_first_frame.add(pid)
        self.rejoins += 1
        self.events.append({"event": "player_rejoin", "player": pid, "live": len(self.live)})

    # ---------------------------------------------------------------- pump
    def pump(self, budget_s: float = 0.05, on_control: Optional[Callable] = None) -> int:
        """Drain available ``rb_insert`` frames from every live player and
        re-grant credits; returns transitions ingested.  Control frames
        (``ckpt_req`` etc.) go to ``on_control``; runs on the caller's
        thread — bounded by ``budget_s``, never blocks on an idle player."""
        got = 0
        t_pump = time.time()
        deadline = time.monotonic() + budget_s
        while True:
            any_frame = False
            for pid in list(self.live):
                ch = self.channels[pid]
                try:
                    frame = ch.recv(timeout=0.01)
                except queue_mod.Empty:
                    continue
                except PeerDiedError as e:
                    self._mark_dead(pid, str(e))
                    continue
                except FrameCorruptError as e:
                    # unrecoverable frame corruption (integrity layer
                    # give-up): the frame is lost, the channel and the
                    # service keep running — FanIn.gather parity
                    self.events.append(
                        {"event": "frame_corrupt_dropped", "player": pid, "detail": str(e)}
                    )
                    continue
                any_frame = True
                self.last_seen[pid] = time.monotonic()
                self._awaiting_first_frame.discard(pid)
                if frame.tag == "stop":
                    self.stopped.add(pid)
                    frame.release()
                elif frame.tag == RB_INSERT_TAG:
                    got += self._ingest(pid, frame)
                elif on_control is not None:
                    on_control(pid, frame)
                else:
                    frame.release()
            self.grant_credits()
            if not any_frame or time.monotonic() > deadline:
                break
        if got:
            rec = flight.get_recorder()
            if rec is not None:
                rec.span_done("replay_pump", t_pump, time.time(), {"transitions": got})
                rec.sampled_event("replay_insert", "rb_insert", total=self.total_inserts)
        return got

    def _ingest(self, pid: int, frame) -> int:
        offset, count = self.env_shards[pid]
        extra = getattr(frame, "extra", ()) or ()
        if len(extra) > 1 and isinstance(extra[1], dict):
            # the player's piggybacked live-metrics summary (ISSUE 15)
            self.fleet[pid] = dict(extra[1])
        arrays = frame.arrays_copy()  # transport buffers go back on release
        frame.release()
        t_len = next(iter(arrays.values())).shape[0]
        # fault site (resilience/faults.py): a poisoned replay batch
        # entering the service — scribble this insert frame's payload
        from sheeprl_tpu.resilience.faults import fault_arg, fault_point

        if fault_point("rb_corrupt"):
            scale = fault_arg("rb_corrupt") or 1e8
            arrays = {
                k: (
                    np.random.default_rng(0).standard_normal(v.shape).astype(v.dtype)
                    * v.dtype.type(scale)
                    if v.dtype.kind == "f"
                    else v
                )
                for k, v in arrays.items()
            }
        # ingest validation AFTER the fault site, so rb_corrupt (and real
        # SDC that slipped past the wire checksum) is DETECTED here:
        # schema violations cannot be stored at all; value violations
        # (non-finite / absurd magnitude) are quarantined — on the
        # prioritized path they are written but immediately floored to
        # the epsilon priority (the sampler effectively never draws
        # them; the ring overwrites them in time), on the uniform path
        # (no per-row mask) they are dropped outright
        reason = None
        if self._ingest_guard is not None:
            from sheeprl_tpu.resilience.integrity import integrity_stats

            st = integrity_stats()
            st.inserts_checked += 1
            reason = self._ingest_guard.check(arrays)
            if reason is not None:
                st.inserts_quarantined += 1
                self.inserts_quarantined += 1
                self._outstanding[pid] = max(0, self._outstanding[pid] - 1)
                self.events.append(
                    {"event": "insert_quarantined", "player": pid, "reason": reason}
                )
                flight.fleet_event("insert_quarantined", player=pid, reason=reason)
                if self.cache is None or "schema" in reason or "dtype" in reason or "shape" in reason or "key set" in reason:
                    return 0  # unstorable / uniform path: drop the frame
        indices = list(range(offset, offset + count))
        self.rb.add(arrays, indices=indices)
        if self.cache is not None:
            self.cache.add(arrays, indices=indices)
        n = t_len * count
        if reason is not None and self.cache is not None:
            # epsilon-priority-floor quarantine (same mechanism as
            # quarantine_recent): the rows were written to keep the ring
            # clocks consistent, but their priorities drop to the floor
            import jax.numpy as jnp

            cap = self.cache.capacity
            n_envs = self.total_envs
            idx_list = []
            for env in range(offset, offset + count):
                pos = int(self.cache._pos[env])
                recent = (pos - 1 - np.arange(min(t_len, cap))) % cap
                idx_list.append(recent * n_envs + env)
            idx = np.concatenate(idx_list)
            self.cache.update_priorities(jnp.asarray(idx), jnp.zeros(len(idx), jnp.float32))
            self.quarantined_rows += t_len * count
        self.total_inserts += n
        self.inserts_by_player[pid] += n
        self._unsampled_insert_ts.append(time.time())
        self._rows_since_mark[offset : offset + count] += t_len
        if self.limiter is not None:
            self.limiter.insert(n)
        self._outstanding[pid] = max(0, self._outstanding[pid] - 1)
        return n

    def grant_credits(self) -> None:
        """Top every live player back up to ``credit_window`` outstanding
        frames — but only while the limiter's insert budget (including
        credits already in flight) allows.  Withholding here is what makes
        a stalled trainer throttle its players."""
        for pid in list(self.live):
            if pid in self._awaiting_first_frame:
                continue  # revived player still dialing back in
            offset, count = self.env_shards[pid]
            while self._outstanding[pid] < self.credit_window:
                if self.limiter is not None:
                    pending = sum(
                        self._outstanding[p] * self.env_shards[p][1] for p in self.live
                    )
                    if not self.limiter.can_insert(pending + count):
                        self.credit_stall_players += 1
                        return
                try:
                    self.channels[pid].send(RB_CREDIT_TAG, extra=(1,), timeout=10.0)
                except (PeerDiedError, queue_mod.Full, OSError) as e:
                    self._mark_dead(pid, f"credit grant failed: {e}")
                    break
                self._outstanding[pid] += 1

    # -------------------------------------------------------------- sample
    def data_ready(self, need_per_env: int = 1) -> bool:
        """True once every env ring holds ``need_per_env`` rows (a lagging
        player delays readiness — by design: the batch must cover the
        whole env population, same as the coupled loop's prefill)."""
        for sub in self.rb.buffer:
            stored = sub.buffer_size if sub.full else sub._pos
            if stored < need_per_env:
                return False
        return True

    def sample(
        self,
        g: int,
        batch_size: int,
        key,
        beta: float,
        sample_next_obs: bool = False,
        obs_keys: Sequence[str] = ("observations",),
    ):
        """Draw ``g`` gradient-step batches; returns ``(data, idx)`` where
        ``data`` is the (g, batch, *) float32 pytree (plus ``is_weights``
        when prioritized) and ``idx`` feeds :meth:`update_priorities`
        (None on the uniform path)."""
        import jax.numpy as jnp

        idx = None
        if self.cache is not None and self.cache.can_sample_transitions(sample_next_obs):
            sampled, idx = self.cache.sample_transitions_per(
                g, batch_size, key, beta, sample_next_obs=sample_next_obs, obs_keys=obs_keys
            )
            data = {k: v.astype(jnp.float32) for k, v in sampled.items()}
        else:
            sample = self.rb.sample(batch_size=g * batch_size, sample_next_obs=sample_next_obs)
            data = {
                k: np.asarray(v, np.float32).reshape(g, batch_size, *v.shape[2:])
                for k, v in sample.items()
            }
            if self.prioritized:
                # cache not ready/disabled: unweighted uniform fallback
                data["is_weights"] = np.ones((g, batch_size, 1), np.float32)
        if self.limiter is not None:
            self.limiter.sample(g * batch_size)
        if self._unsampled_insert_ts:
            # freshness gauge: how stale was the OLDEST insert this is
            # the first sample to cover (the replay_age SLO input)
            self.first_sample_age_s = round(time.time() - self._unsampled_insert_ts[0], 4)
            self._unsampled_insert_ts.clear()
        flight.sampled_event("replay_sample", "replay_sample", total=self.total_inserts)
        return data, idx

    def update_priorities(self, idx, td_abs) -> None:
        if self.cache is not None and idx is not None:
            self.cache.update_priorities(idx, td_abs)

    # ------------------------------------------------------- health hooks
    def mark_health_horizon(self) -> None:
        """Sentinel hook: the latest update dispatched on this buffer was
        verdict-clean, so everything written up to now is trusted — resets
        the quarantine window."""
        self._rows_since_mark[:] = 0

    def quarantine_recent(self) -> int:
        """Rollback hook: the inserts newer than the last verdict-clean
        horizon are suspect (they fed — or were concurrent with — the
        anomalous updates).  On the prioritized path their sum-tree
        priorities drop to the epsilon floor, so the sampler effectively
        never draws them again (the ring overwrites them in time).  The
        uniform path has no per-row mask — the event is still recorded so
        the telemetry shows the exposure.  Returns rows quarantined."""
        rows = 0
        if self.cache is not None and getattr(self.cache, "_tree", None) is not None:
            import jax.numpy as jnp

            n_envs = self.total_envs
            cap = self.cache.capacity
            idx_list = []
            for env in range(n_envs):
                r = int(min(self._rows_since_mark[env], cap))
                if r <= 0:
                    continue
                pos = int(self.cache._pos[env])
                recent = (pos - 1 - np.arange(r)) % cap
                idx_list.append(recent * n_envs + env)
                rows += r
            if idx_list:
                idx = np.concatenate(idx_list)
                # |TD| = 0 -> priority (0 + eps)^alpha: the floor
                self.cache.update_priorities(jnp.asarray(idx), jnp.zeros(len(idx), jnp.float32))
        else:
            rows = int(self._rows_since_mark.sum())
        self.quarantines += 1
        self.quarantined_rows += rows
        self._rows_since_mark[:] = 0
        self.events.append(
            {"event": "replay_quarantine", "rows": rows, "prioritized": self.prioritized}
        )
        return rows

    # --------------------------------------------------------- checkpoint
    def state_dict(self) -> Dict[str, Any]:
        """Tree + limiter + clock (plain numpy/dicts).  The buffer itself
        is NOT nested here: the checkpoint snapshot machinery only
        materializes a buffer at the TOP-LEVEL ``rb`` key, so the caller
        ships ``self.rb`` separately (see sac_decoupled's remote ckpt)."""
        state: Dict[str, Any] = {"total_inserts": self.total_inserts}
        if self.cache is not None:
            state["replay_priority"] = self.cache.priority_state()
        if self.limiter is not None:
            state["rate_limiter"] = self.limiter.state_dict()
        return state

    def load_state_dict(self, state: Dict[str, Any], rb_state=None) -> None:
        from sheeprl_tpu.utils.callback import restore_buffer

        if rb_state is not None:
            restored = restore_buffer(rb_state, memmap=False)
            if restored.n_envs != self.total_envs or restored.buffer_size != self.buffer_size:
                raise RuntimeError(
                    f"restored replay service buffer ({restored.n_envs} envs x "
                    f"{restored.buffer_size}) does not match this topology "
                    f"({self.total_envs} x {self.buffer_size})"
                )
            self.rb = restored
            if self.cache is not None:
                self.cache.load_from(self.rb)
        if self.cache is not None:
            self.cache.load_priority_state(state.get("replay_priority"))
        if self.limiter is not None and state.get("rate_limiter"):
            self.limiter.load_state_dict(state["rate_limiter"])
        self.total_inserts = int(state.get("total_inserts", 0))

    # ---------------------------------------------------------- telemetry
    def stats(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "remote": True,
            "prioritized": self.prioritized,
            "inserts": self.total_inserts,
            "players": {
                str(p): {
                    "inserts": self.inserts_by_player.get(p, 0),
                    "credits_outstanding": self._outstanding.get(p, 0),
                    "alive": p in self.live,
                }
                for p in self.channels
            },
            "live": len(self.live),
            "deaths": len(self.dead),
            "rejoins": self.rejoins,
            "credit_grant_stalls": self.credit_stall_players,
            "first_sample_age_s": self.first_sample_age_s,
            "quarantines": self.quarantines,
            "quarantined_rows": self.quarantined_rows,
            "inserts_quarantined": self.inserts_quarantined,
        }
        if self.limiter is not None:
            rec["limiter"] = self.limiter.stats()
        if self.fleet:
            rec["fleet"] = {str(pid): dict(s) for pid, s in sorted(self.fleet.items())}
        return rec

    @property
    def broadcast_targets(self):
        """Live players safe to push params at (a revived tcp player that
        has not dialed back yet is excluded — a send would stall on its
        dead socket until the reconnect)."""
        return [p for p in self.live if p not in self._awaiting_first_frame]
