"""Fused Pallas kernels for the prioritized-replay sum-tree data plane.

The lax path (replay/priority_tree.py) runs a proportional draw as a
chain of per-level gathers over the heap array and — when sampling-time
exclusions apply (stale next-obs head rows, invalid sequence starts) —
pays a FUNCTIONAL COPY of the whole tree first (``_tree_zeroed``: an
O(tree) scatter + ancestor rebuild per draw batch, 8 MB at the 1e6-leaf
rung).  These kernels fuse the whole draw into ONE program:

- :func:`sum_tree_sample`: all ``n`` draws descend the tree in one
  kernel, with exclusions applied as ON-THE-FLY CORRECTIONS instead of a
  tree copy — at each level the excluded mass under the left child is
  subtracted from the stored prefix sum (an excluded leaf's ancestor at
  level L is just ``(leaf + P) >> (depth - L)``, so the correction is a
  tiny (n, E) compare-and-sum against the E excluded leaves).  The
  no-exclusion descent is op-for-op identical to the lax ``_descend`` and
  therefore bit-exact on the same key; with exclusions the arithmetic is
  ``stored_sum - excluded_mass`` instead of the rebuilt zeroed sums, so
  parity is exact arithmetic (integer-valued f32 priorities: bit-exact)
  and otherwise within float rounding of a subtree boundary — a draw can
  flip leaf only when it lands within ~1 ulp of a boundary.
- :func:`sum_tree_write` / :func:`sum_tree_update`: the fused
  scatter-update for ``_tree_write``/``_tree_update`` — leaf scatter +
  bottom-up ancestor rebuild (+ running-max fold for updates) in one
  kernel, same one-writer-per-duplicate semantics (inactive lanes parked
  at heap slot 0), bit-exact with the lax path.
- :func:`sum_tree_descend`: the raw (un-jitted) corrected descent for
  use INSIDE ``shard_map`` bodies — the per-shard counterpart that
  composes with ``shard_proportional_draw`` (each shard descends its own
  sub-tree for all n draws; exclusions stay shard-local).

Exclusion contract: excluded leaf indices must be DISTINCT where active
(a duplicate would subtract its mass twice).  Every data-plane caller
satisfies this by construction — head rows are one leaf per env, and the
L-1 pre-head sequence starts are distinct rows modulo a capacity that
``can_sample`` already bounds below by the sequence length.

Kernels are SINGLE-PROGRAM pallas_calls (no grid): tree, draws and
outputs live in one VMEM residency, which bounds the compiled-mode tree
at roughly VMEM size (2M leaves ≈ 8 MB f32 — above that a compiled
kernel needs an HBM tree + per-level DMA, not written yet because this
container cannot compile TPU kernels).  ``interpret=True`` (the default
off-TPU) runs them anywhere; interpret mode executes the body as plain
traced jax ops, so the fused-exclusion path is ALSO the fast path on
CPU — measured 8.5x over the lax zeroed-copy sample at the 1e6 rung
(see benchmarks/results/replay_sampling_r14.json).  Large interpret
grids are pathological (~1 ms per grid step): keep these kernels
gridless.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = [
    "resolve_interpret",
    "sum_tree_descend",
    "sum_tree_sample",
    "sum_tree_scatter",
    "sum_tree_update",
    "sum_tree_write",
]


def resolve_interpret(interpret: Optional[bool]) -> bool:
    """``None`` -> interpreter mode everywhere but a real TPU backend."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


# --------------------------------------------------------------- descent
def _corrected_descent(tree, u, excl, eact, emass, depth):
    """Root-to-leaf descent with excluded-mass corrections (see module
    docstring).  ``tree`` is the heap VALUES array; with ``eact`` all
    False this is op-for-op the lax ``_descend``."""
    p = 1 << depth
    node = jnp.ones(u.shape, jnp.int32)
    enode = excl.astype(jnp.int32) + p
    for lvl in range(depth):
        child = 2 * node
        left = jnp.take(tree, child)
        if excl.shape[0]:  # static — compiled away when no exclusions ride
            anc = enode >> (depth - 1 - lvl)
            corr = jnp.sum(
                jnp.where(anc[None, :] == child[:, None], emass[None, :], 0.0), axis=1
            )
            left = left - corr
        go_right = u >= left
        u = jnp.where(go_right, u - left, u)
        node = child + go_right.astype(jnp.int32)
    return node - p, jnp.take(tree, node)


def _excluded_mass(tree, excl, eact, depth):
    p = 1 << depth
    return jnp.where(eact, jnp.take(tree, excl.astype(jnp.int32) + p), 0.0)


def _sample_kernel(tree_ref, r01_ref, beta_ref, count_ref, excl_ref, eact_ref, leaf_ref, w_ref, *, depth):
    tree = tree_ref[:]
    emass = _excluded_mass(tree, excl_ref[:], eact_ref[:], depth)
    total = tree[1] - jnp.sum(emass)
    u = r01_ref[:] * total
    leaf, mass = _corrected_descent(tree, u, excl_ref[:], eact_ref[:], emass, depth)
    # identical IS-weight formulas to the lax _tree_sample (same rounding
    # guard: a draw that skids into a zero-mass leaf keeps a tiny floor)
    tiny = jnp.finfo(tree.dtype).tiny
    probs = jnp.maximum(mass, tiny) / jnp.maximum(total, tiny)
    w = (jnp.maximum(count_ref[0], 1.0) * probs) ** (-beta_ref[0])
    leaf_ref[:] = leaf
    w_ref[:] = w / jnp.max(w)


def _descend_kernel(tree_ref, u_ref, excl_ref, eact_ref, leaf_ref, mass_ref, *, depth):
    tree = tree_ref[:]
    emass = _excluded_mass(tree, excl_ref[:], eact_ref[:], depth)
    leaf, mass = _corrected_descent(tree, u_ref[:], excl_ref[:], eact_ref[:], emass, depth)
    leaf_ref[:] = leaf
    mass_ref[:] = mass


def _write_body(tree, leaf_idx, values, active, depth):
    """Scatter + bottom-up ancestor rebuild — the exact ``_write_impl``
    arithmetic (one writer per duplicate, inactive lanes parked at the
    unused heap slot 0) so lax and pallas trees stay bit-identical."""
    p = 1 << depth
    node = jnp.where(active, leaf_idx.astype(jnp.int32) + p, 0)
    tree = tree.at[node].set(jnp.where(active, values.astype(tree.dtype), tree[0]))
    for _ in range(depth):
        node = node >> 1
        tree = tree.at[node].set(tree[2 * node] + tree[2 * node + 1])
    return tree


def _write_kernel(tree_ref, leaf_ref, val_ref, act_ref, out_ref, *, depth):
    out_ref[:] = _write_body(tree_ref[:], leaf_ref[:], val_ref[:], act_ref[:], depth)


def _update_kernel(tree_ref, maxp_ref, leaf_ref, pri_ref, act_ref, out_ref, newmax_ref, *, depth):
    act = act_ref[:]
    pri = pri_ref[:]
    newmax_ref[0] = jnp.maximum(maxp_ref[0], jnp.max(jnp.where(act, pri, 0.0)))
    out_ref[:] = _write_body(tree_ref[:], leaf_ref[:], pri, act, depth)


# ----------------------------------------------------------- public API
def _excl_args(n, exclude_idx, exclude_active):
    """Normalize the (possibly absent) exclusion pair to static-shape
    device args: no exclusions ride as one inactive dummy lane."""
    if exclude_idx is None:
        return jnp.zeros((1,), jnp.int32), jnp.zeros((1,), bool)
    excl = jnp.asarray(exclude_idx, jnp.int32).reshape(-1)
    if exclude_active is None:
        eact = jnp.ones(excl.shape, bool)
    else:
        eact = jnp.asarray(exclude_active).reshape(excl.shape)
    return excl, eact


@functools.partial(jax.jit, static_argnames=("n", "depth", "interpret"))
def _sample_jit(tree, key, beta, count, excl, eact, *, n, depth, interpret):
    # the uniforms consume the key exactly like the lax _tree_sample
    # (u = uniform(key, (n,)) * total — total is applied inside the kernel)
    r01 = jax.random.uniform(key, (n,))
    return pl.pallas_call(
        functools.partial(_sample_kernel, depth=depth),
        out_shape=(
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((n,), tree.dtype),
        ),
        interpret=interpret,
    )(tree, r01, beta.reshape(1), count.reshape(1), excl, eact)


def sum_tree_sample(
    tree,
    key,
    beta,
    count,
    *,
    n: int,
    depth: int,
    exclude_idx=None,
    exclude_active=None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused proportional draw: ``n`` leaves + batch-max-normalized IS
    weights in ONE kernel, exclusions folded into the descent (no tree
    copy).  Same key consumption and weight formulas as the lax
    ``_tree_zeroed`` + ``_tree_sample`` pair."""
    excl, eact = _excl_args(n, exclude_idx, exclude_active)
    return _sample_jit(
        tree,
        jnp.asarray(key),
        jnp.asarray(beta, tree.dtype),
        jnp.asarray(count, tree.dtype),
        excl,
        eact,
        n=n,
        depth=depth,
        interpret=resolve_interpret(interpret),
    )


def sum_tree_descend(
    tree,
    u,
    *,
    depth: int,
    exclude_idx=None,
    exclude_active=None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Raw fused descent ``u in [0, total) -> (leaf, mass)`` — un-jitted,
    for use inside ``shard_map`` bodies (the caller owns the collective
    that placed ``u`` in this shard's interval)."""
    excl, eact = _excl_args(u.shape[0], exclude_idx, exclude_active)
    return pl.pallas_call(
        functools.partial(_descend_kernel, depth=depth),
        out_shape=(
            jax.ShapeDtypeStruct(u.shape, jnp.int32),
            jax.ShapeDtypeStruct(u.shape, tree.dtype),
        ),
        interpret=resolve_interpret(interpret),
    )(tree, u, excl, eact)


def sum_tree_scatter(tree, leaf_idx, values, active, *, depth: int, interpret: Optional[bool] = None):
    """Raw (un-jitted) fused scatter-update for use INSIDE ``shard_map``
    bodies — the per-shard counterpart of :func:`sum_tree_write` (the
    outer jit owns donation there, so no aliasing is declared)."""
    return pl.pallas_call(
        functools.partial(_write_kernel, depth=depth),
        out_shape=jax.ShapeDtypeStruct(tree.shape, tree.dtype),
        interpret=resolve_interpret(interpret),
    )(
        tree,
        jnp.asarray(leaf_idx, jnp.int32),
        jnp.asarray(values, tree.dtype),
        jnp.asarray(active),
    )


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("depth", "interpret"))
def _write_jit(tree, leaf_idx, values, active, *, depth, interpret):
    return pl.pallas_call(
        functools.partial(_write_kernel, depth=depth),
        out_shape=jax.ShapeDtypeStruct(tree.shape, tree.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(tree, leaf_idx, values, active)


def sum_tree_write(tree, leaf_idx, values, active, *, depth: int, interpret: Optional[bool] = None):
    """Fused scatter-update (set leaves + rebuild touched ancestors) in
    one donated kernel — bit-exact with the lax ``_tree_write``."""
    return _write_jit(
        tree,
        jnp.asarray(leaf_idx, jnp.int32),
        jnp.asarray(values, tree.dtype),
        jnp.asarray(active),
        depth=depth,
        interpret=resolve_interpret(interpret),
    )


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("depth", "interpret"))
def _update_jit(tree, max_p, leaf_idx, priorities, active, *, depth, interpret):
    tree, new_max = pl.pallas_call(
        functools.partial(_update_kernel, depth=depth),
        out_shape=(
            jax.ShapeDtypeStruct(tree.shape, tree.dtype),
            jax.ShapeDtypeStruct((1,), tree.dtype),
        ),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(tree, max_p.reshape(1), leaf_idx, priorities, active)
    return tree, new_max[0]


def sum_tree_update(
    tree, max_p, leaf_idx, priorities, active, *, depth: int, interpret: Optional[bool] = None
):
    """Fused priority update: scatter + rebuild + running-max fold in one
    donated kernel — bit-exact with the lax ``_tree_update``."""
    return _update_jit(
        tree,
        jnp.asarray(max_p, tree.dtype),
        jnp.asarray(leaf_idx, jnp.int32),
        jnp.asarray(priorities, tree.dtype),
        jnp.asarray(active),
        depth=depth,
        interpret=resolve_interpret(interpret),
    )
