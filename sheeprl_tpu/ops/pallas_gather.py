"""Fused Pallas gather kernels for the replay-cache data plane.

The lax samplers (data/device_buffer.py) assemble a draw as per-key
XLA gathers over a ``seq_len``-strided index fan: ``_gather_windows``
builds a (flat, L) ring-index matrix and issues one advanced-indexing
gather PER BUFFER KEY, ``_gather_transitions`` likewise plus a second
fan for the ``next_*`` rows.  These kernels fuse one whole draw into a
SINGLE ``pallas_call``: every buffer key rides as one input ref and one
output ref of the same kernel, the ring/window index arithmetic is
computed ONCE, and each key's gather happens in the same program — a
prioritized sequence draw becomes one kernel launch instead of a
per-key gather chain.

The gathers move bytes untouched, so outputs are BIT-IDENTICAL to the
lax path's for the same indices — ``per_kernel=pallas`` changes the
execution shape, never the sampled data.

Like ops/pallas_per.py these are gridless single-program kernels
(interpret mode executes them as fused jax ops on any backend; a large
interpret grid costs ~1 ms PER STEP, so a (flat × L)-grid DMA design —
the natural compiled-TPU evolution via ``PrefetchScalarGridSpec``, one
(1, 1, F) block copy per window row with the ring offset computed in
the index map — is documented in howto/performance.md but not the
default).  VMEM residency bounds compiled-mode use to rings that fit
on-chip; interpret mode has no such bound.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from sheeprl_tpu.ops.pallas_per import resolve_interpret

__all__ = [
    "gather_transitions_fused",
    "gather_windows_fused",
]


def _flat2(buf):
    """(cap, n_envs, *feat) -> (cap * n_envs, F) view (F >= 1)."""
    cap, n_envs = buf.shape[:2]
    feat = int(np.prod(buf.shape[2:], dtype=np.int64) or 1)
    return buf.reshape(cap * n_envs, feat)


def _windows_kernel(*refs, n_keys, seq_len, cap, n_envs):
    starts_ref, envs_ref = refs[0], refs[1]
    buf_refs = refs[2 : 2 + n_keys]
    out_refs = refs[2 + n_keys :]
    starts = starts_ref[:]
    envs = envs_ref[:]
    # one index fan for every key: (flat, L) ring rows -> flat cell ids
    t_idx = (starts[:, None] + jnp.arange(seq_len)[None, :]) % cap
    cell = (t_idx * n_envs + envs[:, None]).reshape(-1)
    for b_ref, o_ref in zip(buf_refs, out_refs):
        flat, feat = o_ref.shape[0], o_ref.shape[-1]
        o_ref[:] = jnp.take(b_ref[:], cell, axis=0).reshape(flat, seq_len, feat)


def gather_windows_fused(
    bufs: Dict[str, jax.Array],
    starts,
    envs,
    *,
    seq_len: int,
    interpret: Optional[bool] = None,
) -> Dict[str, jax.Array]:
    """All keys' (flat, L, *feat) sequence windows in ONE kernel.

    ``bufs[k]`` is (cap, n_envs, *feat); ``starts``/``envs`` are (flat,)
    ring starts and env columns; windows wrap modulo the capacity."""
    keys = list(bufs)
    first = bufs[keys[0]]
    cap, n_envs = first.shape[:2]
    flat = starts.shape[0]
    flats = [_flat2(bufs[k]) for k in keys]
    out = pl.pallas_call(
        functools.partial(
            _windows_kernel, n_keys=len(keys), seq_len=int(seq_len), cap=cap, n_envs=n_envs
        ),
        out_shape=tuple(
            jax.ShapeDtypeStruct((flat, int(seq_len), f.shape[1]), f.dtype) for f in flats
        ),
        interpret=resolve_interpret(interpret),
    )(jnp.asarray(starts, jnp.int32), jnp.asarray(envs, jnp.int32), *flats)
    return {
        k: o.reshape((flat, int(seq_len)) + bufs[k].shape[2:]) for k, o in zip(keys, out)
    }


def _transitions_kernel(*refs, n_keys, n_next, cap, n_envs):
    rows_ref, envs_ref = refs[0], refs[1]
    buf_refs = refs[2 : 2 + n_keys + n_next]
    out_refs = refs[2 + n_keys + n_next :]
    rows = rows_ref[:]
    envs = envs_ref[:]
    cell = rows * n_envs + envs
    ncell = ((rows + 1) % cap) * n_envs + envs
    for i, (b_ref, o_ref) in enumerate(zip(buf_refs, out_refs)):
        o_ref[:] = jnp.take(b_ref[:], cell if i < n_keys else ncell, axis=0)


def gather_transitions_fused(
    bufs: Dict[str, jax.Array],
    rows,
    envs,
    *,
    next_keys: Sequence[str] = (),
    interpret: Optional[bool] = None,
) -> Dict[str, jax.Array]:
    """All keys' flat-transition rows (+ ``next_<k>`` successor rows for
    ``next_keys``) in ONE kernel.  Successor row = (row + 1) % cap, same
    contract as the lax ``_gather_transitions``."""
    keys = list(bufs)
    nxt = list(next_keys)
    first = bufs[keys[0]]
    cap, n_envs = first.shape[:2]
    flat = rows.shape[0]
    flats = [_flat2(bufs[k]) for k in keys] + [_flat2(bufs[k]) for k in nxt]
    out = pl.pallas_call(
        functools.partial(
            _transitions_kernel, n_keys=len(keys), n_next=len(nxt), cap=cap, n_envs=n_envs
        ),
        out_shape=tuple(
            jax.ShapeDtypeStruct((flat, f.shape[1]), f.dtype) for f in flats
        ),
        interpret=resolve_interpret(interpret),
    )(jnp.asarray(rows, jnp.int32), jnp.asarray(envs, jnp.int32), *flats)
    res = {}
    for k, o in zip(keys, out[: len(keys)]):
        res[k] = o.reshape((flat,) + bufs[k].shape[2:])
    for k, o in zip(nxt, out[len(keys) :]):
        res[f"next_{k}"] = o.reshape((flat,) + bufs[k].shape[2:])
    return res
