"""Efficient-BPTT custom VJP for the Dreamer dynamic scans (DV3 + DV2).

The discrete-latent dynamic recurrence (this repo's
``RSSM.dynamic_posterior``; reference sheeprl dreamer_v3.py:113-146 +
RSSM.dynamic agent.py:396, dreamer_v2 agent.py RSSM.dynamic:336) interleaves
posterior sampling with the GRU:

    feat   = act(LN_p?([z_{t-1}, a_t] @ Wp + bp))     # input projection
    h_t    = LayerNormGRU(h_{t-1}, feat)              # Hafner GRU (+bias in V2)
    logits = head(act(LN_r?(h_t @ k_h + emb_proj_t))) # representation model
    z_t    = ST-sample(unimix?(logits) + gumbel)      # posterior

Autodiff-through-``lax.scan`` puts every weight-gradient accumulator
(Wp, Wg, k_h, head — ~4.5 MB f32 at DV3-S) into the backward while-loop's
carry: every reverse iteration reads and writes them all (~9 MB of HBM
round-trip per step) on top of the serial matmuls.  A Pallas
whole-sequence forward kernel does NOT help here — measured on the v5e,
one-kernel grid=(T,) recurrences are launch-overhead-bound and lose to
XLA's while loop (benchmarks/results/seq_gru_tpu_r4.json: 4.10 ms vs
3.85 ms fwd at T=64/B=16/H=512) — but the backward is fixable in pure JAX:

* the forward stays an XLA ``lax.scan`` (already latency-optimal), saving
  only the carried states (hs, zs) — no per-step residual stacking;
* the backward recomputes every activation, LayerNorm statistic and gate
  from the saved states in batched (T*B) matmuls, then runs a reverse
  ``lax.scan`` whose carry is ONLY (dh, dz): four small matmuls per step
  (head/rep/GRU/projection transposes) and elementwise chain rules;
* every weight gradient is a single batched contraction over stacked
  reverse-scan outputs, OUTSIDE the sequential loop.

Chip A/B at DV3-S: 16.2-16.3 → 15.7 ms per train step.

Generality knobs (static): activation (``silu`` for V3 / ``elu`` for V2),
optional LayerNorms on the projection and representation trunks (with
their epsilons: V3 configures 1e-3, V2 uses flax's 1e-6 default), Dense
biases on the projection and GRU (always-present zero arrays when the
module variant has none — the adds are free next to the matmuls), and
``unimix`` (V3's 1% log-mix; 0 means the logits pass through raw, V2).
The is_first reset state is an input pair (init_rec/init_post): V3 passes
its learned initial state, V2 passes zeros.

Numerics: matmuls run in the caller's compute dtype with f32 LayerNorms,
mirroring ``linear_ln_act_apply``/``gru_cell_apply``/``DenseActLn``; all
backward cotangent arithmetic is f32 (autodiff would carry bf16
cotangents through bf16 segments — the f32 choice is strictly more
precise; grads match autodiff exactly in f32 and to bf16 tolerance under
bf16-mixed, pinned by ``tests/test_parallel/test_dyn_bptt.py``).
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "DynParams",
    "V1DynParams",
    "dyn_bptt_setting",
    "dyn_rssm_sequence",
    "dyn_rssm_sequence_v1",
    "extract_dyn_params",
    "extract_dyn_params_v1",
    "extract_dyn_params_v2",
    "rssm_dyn_bptt_eligible",
]


def dyn_bptt_setting(cfg) -> bool:
    """The ``algo.world_model.dyn_bptt`` config knob with its
    ``SHEEPRL_DYN_BPTT`` env override (shared by every Dreamer-family
    train fn; callers AND their own structural eligibility check, e.g.
    :func:`rssm_dyn_bptt_eligible` or a supported-activation test)."""
    enabled = bool(cfg.algo.world_model.get("dyn_bptt", False))
    if os.environ.get("SHEEPRL_DYN_BPTT") is not None:
        enabled = os.environ["SHEEPRL_DYN_BPTT"].lower() not in ("0", "false")
    return enabled


class DynParams(NamedTuple):
    """Raw weight leaves of the fused dynamic step (flax param layout).

    w_proj (S+A, P) / b_proj (P,)   recurrent model input projection
    lnp_*  (P,)        its LayerNorm (when proj_ln)
    w_gru  (H+P, 3H) / b_gru (3H,)  LayerNormGRUCell dense
    lng_*  (3H,)       its LayerNorm (eps 1e-6, always on)
    k_h    (H, R)      representation trunk, h-side rows of the first Dense
                       (the embed-side rows and the Dense bias live in the
                       precomputed ``emb_proj``)
    lnr_*  (R,)        representation trunk LayerNorm (when rep_ln)
    head_k (R, S) / head_b (S,)     logits head (f32 matmul)

    Bias/LN arrays are always present; pass zeros/ones when the module
    variant has none (their gradients are then simply discarded).
    """

    w_proj: jax.Array
    b_proj: jax.Array
    lnp_scale: jax.Array
    lnp_bias: jax.Array
    w_gru: jax.Array
    b_gru: jax.Array
    lng_scale: jax.Array
    lng_bias: jax.Array
    k_h: jax.Array
    lnr_scale: jax.Array
    lnr_bias: jax.Array
    head_k: jax.Array
    head_b: jax.Array


def _ln_fwd(x32, scale, bias, eps):
    """flax fast-variance LayerNorm in f32; returns (out, xhat, inv)."""
    mu = x32.mean(-1, keepdims=True)
    var = jnp.maximum((x32 * x32).mean(-1, keepdims=True) - mu * mu, 0.0)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x32 - mu) * inv
    return xhat * scale + bias, xhat, inv


def _ln_bwd(dy, scale, xhat, inv):
    """Cotangent of the LN input given d(out); scale/bias grads batch outside."""
    dxhat = dy * scale
    return inv * (
        dxhat
        - dxhat.mean(-1, keepdims=True)
        - xhat * (dxhat * xhat).mean(-1, keepdims=True)
    )


def _act_fwd(v, act: str):
    if act == "silu":
        return jax.nn.silu(v)
    if act == "elu":
        return jax.nn.elu(v)
    raise ValueError(f"unsupported activation for dyn_bptt: {act}")


def _act_grad(v, act: str):
    """d act(v) / dv evaluated at the saved pre-activation value."""
    if act == "silu":
        s = jax.nn.sigmoid(v)
        return s * (1.0 + v * (1.0 - s))
    if act == "elu":
        return jnp.where(v > 0, 1.0, jnp.exp(jnp.minimum(v, 0.0)))
    raise ValueError(f"unsupported activation for dyn_bptt: {act}")


def _group_softmax(x, groups, classes):
    return jax.nn.softmax(x.reshape(*x.shape[:-1], groups, classes), -1)


@functools.lru_cache(maxsize=16)
def _get_op(
    eps_p: float,
    eps_r: float,
    unimix: float,
    discrete: int,
    dt_name: str,
    unroll: int,
    act: str,
    proj_ln: bool,
    rep_ln: bool,
):
    dt = jnp.dtype(dt_name)
    f32 = jnp.float32

    def _step_fwd(params: DynParams, init_rec, init_post, carry, inp):
        """One dynamic step, numerics-identical to RSSM.dynamic_posterior
        (V3) / RSSM.dynamic_posterior_from_proj (V2)."""
        z, h = carry
        a, emb, f, n = inp
        keep = 1.0 - f
        a_eff = keep * a
        hg = keep * h + f * init_rec
        zg = keep * z + f * init_post

        fpre = (
            jnp.concatenate([zg, a_eff], -1).astype(dt) @ params.w_proj.astype(dt)
            + params.b_proj.astype(dt)
        )
        if proj_ln:
            lnp, _, _ = _ln_fwd(fpre.astype(f32), params.lnp_scale, params.lnp_bias, eps_p)
            feat = _act_fwd(lnp.astype(dt), act)
        else:
            feat = _act_fwd(fpre, act)

        gpre = (
            jnp.concatenate([hg.astype(dt), feat], -1) @ params.w_gru.astype(dt)
            + params.b_gru.astype(dt)
        )
        parts, _, _ = _ln_fwd(gpre.astype(f32), params.lng_scale, params.lng_bias, 1e-6)
        hidden = h.shape[-1]
        reset = jax.nn.sigmoid(parts[..., :hidden])
        cand = jnp.tanh(reset * parts[..., hidden : 2 * hidden])
        update = jax.nn.sigmoid(parts[..., 2 * hidden :] - 1.0)
        h_new = update * cand + (1.0 - update) * hg

        xpre = h_new.astype(dt) @ params.k_h.astype(dt) + emb
        if rep_ln:
            lnr, _, _ = _ln_fwd(xpre.astype(f32), params.lnr_scale, params.lnr_bias, eps_r)
            x = _act_fwd(lnr.astype(dt), act)
        else:
            x = _act_fwd(xpre, act)
        logits = x.astype(f32) @ params.head_k + params.head_b

        groups = logits.shape[-1] // discrete
        if unimix > 0.0:
            pr = _group_softmax(logits, groups, discrete)
            pm = (1.0 - unimix) * pr + unimix / discrete
            mixed = jnp.log(pm)
        else:
            mixed = logits.reshape(*logits.shape[:-1], groups, discrete)
        hard = jax.nn.one_hot(
            jnp.argmax(mixed + n.reshape(mixed.shape), -1), discrete, dtype=f32
        )
        z_new = hard.reshape(z.shape)
        return (z_new, h_new), (h_new, z_new, mixed.reshape(z.shape))

    def _fwd_scan(z0, h0, actions, emb_proj, is_first, noise, init_rec, init_post, params):
        step = functools.partial(_step_fwd, params, init_rec, init_post)
        _, (hs, zs, mixed) = jax.lax.scan(
            step, (z0, h0), (actions, emb_proj, is_first, noise), unroll=unroll
        )
        return hs, zs, mixed

    @jax.custom_vjp
    def op(z0, h0, actions, emb_proj, is_first, noise, init_rec, init_post, params):
        return _fwd_scan(z0, h0, actions, emb_proj, is_first, noise, init_rec, init_post, params)

    def op_fwd(z0, h0, actions, emb_proj, is_first, noise, init_rec, init_post, params):
        hs, zs, mixed = _fwd_scan(
            z0, h0, actions, emb_proj, is_first, noise, init_rec, init_post, params
        )
        return (hs, zs, mixed), (
            z0,
            h0,
            actions,
            emb_proj,
            is_first,
            noise,
            init_rec,
            init_post,
            params,
            hs,
            zs,
        )

    def op_bwd(res, cots):
        z0, h0, actions, emb_proj, is_first, noise, init_rec, init_post, params, hs, zs = res
        d_hs, d_zs, d_mixed = cots
        T, b = hs.shape[:2]
        hidden = h0.shape[-1]
        stoch = z0.shape[-1]
        groups = stoch // discrete

        # ---- batched recompute of every step's activations from the saved
        # states (one (T*B) matmul per layer, nothing sequential)
        f = is_first.astype(f32)
        keep = 1.0 - f
        z_prev = jnp.concatenate([z0[None], zs[:-1]], 0)
        h_prev = jnp.concatenate([h0[None], hs[:-1]], 0)
        a_eff = keep * actions
        hg = keep * h_prev + f * init_rec
        zg = keep * z_prev + f * init_post

        inp_p32 = jnp.concatenate([zg, a_eff], -1)
        fpre_dt = (
            inp_p32.astype(dt) @ params.w_proj.astype(dt) + params.b_proj.astype(dt)
        )
        fpre = fpre_dt.astype(f32)
        if proj_ln:
            lnp, xhat_p, inv_p = _ln_fwd(fpre, params.lnp_scale, params.lnp_bias, eps_p)
            actin_p = lnp.astype(dt)  # activation input (saved pre-act value)
        else:
            xhat_p = inv_p = jnp.zeros_like(fpre[..., :1])
            actin_p = fpre_dt
        feat = _act_fwd(actin_p, act)

        g_in32 = jnp.concatenate([hg, feat.astype(f32)], -1)
        gpre = (
            g_in32.astype(dt) @ params.w_gru.astype(dt) + params.b_gru.astype(dt)
        ).astype(f32)
        parts, xhat_g, inv_g = _ln_fwd(gpre, params.lng_scale, params.lng_bias, 1e-6)
        reset = jax.nn.sigmoid(parts[..., :hidden])
        p2 = parts[..., hidden : 2 * hidden]
        cand = jnp.tanh(reset * p2)
        update = jax.nn.sigmoid(parts[..., 2 * hidden :] - 1.0)

        xpre_dt = hs.astype(dt) @ params.k_h.astype(dt) + emb_proj
        xpre = xpre_dt.astype(f32)
        if rep_ln:
            lnr, xhat_r, inv_r = _ln_fwd(xpre, params.lnr_scale, params.lnr_bias, eps_r)
            actin_r = lnr.astype(dt)
        else:
            xhat_r = inv_r = jnp.zeros_like(xpre[..., :1])
            actin_r = xpre_dt
        x32 = _act_fwd(actin_r, act).astype(f32)
        logits = x32 @ params.head_k + params.head_b
        l3 = logits.reshape(T, b, groups, discrete)
        if unimix > 0.0:
            pr = jax.nn.softmax(l3, -1)
            pm = (1.0 - unimix) * pr + unimix / discrete
            p_st = jax.nn.softmax(jnp.log(pm), -1)  # fp-faithful to the fwd
        else:
            pr = pm = jnp.zeros_like(l3[..., :1])  # unused
            p_st = jax.nn.softmax(l3, -1)

        w_gru_h = params.w_gru[:hidden].astype(f32)
        w_gru_x = params.w_gru[hidden:].astype(f32)
        w_proj_z = params.w_proj[:stoch].astype(f32)
        k_h32 = params.k_h.astype(f32)
        head_k32 = params.head_k.astype(f32)

        def back_step(carry, inp_t):
            dh_c, dz_c = carry
            (
                d_hs_t,
                d_zs_t,
                d_mixed_t,
                f_t,
                p_st_t,
                pm_t,
                pr_t,
                actin_r_t,
                xhat_r_t,
                inv_r_t,
                hg_t,
                cand_t,
                update_t,
                reset_t,
                p2_t,
                xhat_g_t,
                inv_g_t,
                actin_p_t,
                xhat_p_t,
                inv_p_t,
            ) = inp_t
            keep_t = 1.0 - f_t

            # straight-through (+ unimix) backward into the logits
            dz3 = (d_zs_t + dz_c).reshape(-1, groups, discrete)
            dmx = p_st_t * (dz3 - (dz3 * p_st_t).sum(-1, keepdims=True))
            dmx = dmx + d_mixed_t.reshape(dmx.shape)
            if unimix > 0.0:
                dpm = dmx / pm_t
                dpr = (1.0 - unimix) * dpm
                dlogits = (pr_t * (dpr - (dpr * pr_t).sum(-1, keepdims=True))).reshape(
                    -1, groups * discrete
                )
            else:
                dlogits = dmx.reshape(-1, groups * discrete)

            # representation head + trunk backward
            dx32 = dlogits @ head_k32.T
            dl = dx32 * _act_grad(actin_r_t.astype(f32), act)
            if rep_ln:
                dxpre = _ln_bwd(dl, params.lnr_scale, xhat_r_t, inv_r_t)
            else:
                dxpre = dl
            dh_rep = dxpre @ k_h32.T

            # GRU backward (gated carry hg)
            dh_tot = d_hs_t + dh_c + dh_rep
            du = (cand_t - hg_t) * dh_tot
            dcand = update_t * dh_tot
            dhg = (1.0 - update_t) * dh_tot
            dp3 = du * update_t * (1.0 - update_t)
            dtanh = dcand * (1.0 - cand_t * cand_t)
            dp2 = dtanh * reset_t
            dreset = dtanh * p2_t
            dp1 = dreset * reset_t * (1.0 - reset_t)
            dparts = jnp.concatenate([dp1, dp2, dp3], -1)
            dgpre = _ln_bwd(dparts, params.lng_scale, xhat_g_t, inv_g_t)
            dhg = dhg + dgpre @ w_gru_h.T
            dfeat = dgpre @ w_gru_x.T

            # input projection backward
            dl_p = dfeat * _act_grad(actin_p_t.astype(f32), act)
            if proj_ln:
                dfpre = _ln_bwd(dl_p, params.lnp_scale, xhat_p_t, inv_p_t)
            else:
                dfpre = dl_p
            dzg = dfpre @ w_proj_z.T

            dh_prev = keep_t * dhg
            dz_prev = keep_t * dzg
            return (dh_prev, dz_prev), (dlogits, dxpre, dparts, dgpre, dfpre, dhg, dzg)

        seq = (
            d_hs.astype(f32),
            d_zs.astype(f32).reshape(T, b, stoch),
            d_mixed.astype(f32),
            f,
            p_st,
            pm,
            pr,
            actin_r,
            xhat_r,
            inv_r,
            hg,
            cand,
            update,
            reset,
            p2,
            xhat_g,
            inv_g,
            actin_p,
            xhat_p,
            inv_p,
        )
        (dh0, dz0), (dlogits, dxpre, dparts, dgpre, dfpre, dhgs, dzgs) = jax.lax.scan(
            back_step,
            (jnp.zeros_like(h0, f32), jnp.zeros_like(z0, f32)),
            seq,
            reverse=True,
            unroll=unroll,
        )

        # ---- weight gradients: one batched contraction each
        n_r = params.k_h.shape[-1]
        x32f = x32.reshape(T * b, n_r)
        dlogf = dlogits.reshape(T * b, stoch)
        dxpref = dxpre.reshape(T * b, n_r)
        # LN scale/bias grads need the pre-LN-input cotangents dlnr/dlnp
        dlnr_full = (dlogits @ head_k32.T) * _act_grad(actin_r.astype(f32), act)
        dlnp_full = (dgpre @ w_gru_x.T) * _act_grad(actin_p.astype(f32), act)

        grads = DynParams(
            w_proj=(inp_p32.reshape(T * b, -1).T @ dfpre.reshape(T * b, -1)).astype(
                params.w_proj.dtype
            ),
            b_proj=dfpre.sum((0, 1)).astype(params.b_proj.dtype),
            lnp_scale=(dlnp_full * xhat_p).sum((0, 1)) if proj_ln else jnp.zeros_like(params.lnp_scale),
            lnp_bias=dlnp_full.sum((0, 1)) if proj_ln else jnp.zeros_like(params.lnp_bias),
            w_gru=(g_in32.reshape(T * b, -1).T @ dgpre.reshape(T * b, -1)).astype(
                params.w_gru.dtype
            ),
            b_gru=dgpre.sum((0, 1)).astype(params.b_gru.dtype),
            lng_scale=(dparts * xhat_g).sum((0, 1)),
            lng_bias=dparts.sum((0, 1)),
            k_h=(hs.reshape(T * b, hidden).T @ dxpref).astype(params.k_h.dtype),
            lnr_scale=(dlnr_full * xhat_r).sum((0, 1)) if rep_ln else jnp.zeros_like(params.lnr_scale),
            lnr_bias=dlnr_full.sum((0, 1)) if rep_ln else jnp.zeros_like(params.lnr_bias),
            head_k=(x32f.T @ dlogf).astype(params.head_k.dtype),
            head_b=dlogf.sum(0).astype(params.head_b.dtype),
        )
        d_actions = (keep * (dfpre @ params.w_proj[stoch:].astype(f32).T)).astype(actions.dtype)
        d_emb = dxpre.astype(emb_proj.dtype)
        d_init_rec = (f * dhgs).sum(0).astype(init_rec.dtype)
        d_init_post = (f * dzgs).sum(0).astype(init_post.dtype)
        return (
            dz0.astype(z0.dtype),
            dh0.astype(h0.dtype),
            d_actions,
            d_emb,
            jnp.zeros_like(is_first),
            jnp.zeros_like(noise),
            d_init_rec,
            d_init_post,
            grads,
        )

    op.defvjp(op_fwd, op_bwd)
    return op


class V1DynParams(NamedTuple):
    """Raw weight leaves of the DV1 (Gaussian-latent) dynamic step.

    w_proj (S+A, P) / b_proj (P,)  recurrent model input projection
                                   (``RecurrentModel.Dense_0`` — bias present)
    w_i    (P, 3H) / b_i (3H,)     flax GRUCell input kernels [ir|iz|in]
    w_h    (H, 3H) / b_hn (H,)     flax GRUCell hidden kernels [hr|hz|hn]
                                   (only ``hn`` has a bias)
    k_h    (H, R)                  representation trunk, h-side rows of the
                                   first Dense (embed-side rows + bias live
                                   in the precomputed ``emb_proj``)
    head_k (R, 2S) / head_b (2S,)  (mean, std) head (f32 matmul)
    """

    w_proj: jax.Array
    b_proj: jax.Array
    w_i: jax.Array
    b_i: jax.Array
    w_h: jax.Array
    b_hn: jax.Array
    k_h: jax.Array
    head_k: jax.Array
    head_b: jax.Array


@functools.lru_cache(maxsize=16)
def _get_op_v1(min_std: float, dt_name: str, unroll: int, act: str):
    """Efficient-BPTT op for the DV1 continuous-latent dynamic recurrence.

    The DV1 chain (``dreamer_v1.agent.RSSM.dynamic_posterior_from_proj``;
    reference sheeprl dreamer_v1/agent.py RSSM.dynamic:97 +
    dreamer_v1/utils.py:80) is simpler than V3's: reparameterized Gaussian
    sampling instead of straight-through/unimix, a plain flax GRUCell
    instead of the Hafner LayerNorm GRU, no LayerNorms anywhere, and no
    is_first resets.  The efficient-BPTT design is identical: forward is
    the plain XLA ``lax.scan`` saving only (hs, zs); backward recomputes
    all activations in batched (T*B) matmuls and runs a reverse scan whose
    carry is only (dh, dz), with every weight gradient one batched
    contraction outside the loop.
    """
    dt = jnp.dtype(dt_name)
    f32 = jnp.float32

    def _gru_fwd(params: V1DynParams, h, feat32):
        """flax nn.GRUCell numerics: r/z gates, reset applied to the
        hidden-side candidate product, new_h = (1-z)*n + z*h."""
        hidden = h.shape[-1]
        gi = feat32 @ params.w_i.astype(f32) + params.b_i.astype(f32)
        gh = h @ params.w_h.astype(f32)
        r = jax.nn.sigmoid(gi[..., :hidden] + gh[..., :hidden])
        u = jax.nn.sigmoid(gi[..., hidden : 2 * hidden] + gh[..., hidden : 2 * hidden])
        ghn = gh[..., 2 * hidden :] + params.b_hn.astype(f32)
        n = jnp.tanh(gi[..., 2 * hidden :] + r * ghn)
        return (1.0 - u) * n + u * h, (r, u, n, ghn)

    def _step_fwd(params: V1DynParams, carry, inp):
        z, h = carry
        a, emb, n_t = inp
        fpre = (
            jnp.concatenate([z, a], -1).astype(dt) @ params.w_proj.astype(dt)
            + params.b_proj.astype(dt)
        )
        feat32 = _act_fwd(fpre, act).astype(f32)
        h_new, _ = _gru_fwd(params, h, feat32)
        xpre = h_new.astype(dt) @ params.k_h.astype(dt) + emb
        x = _act_fwd(xpre, act)
        ms = x.astype(f32) @ params.head_k + params.head_b
        mean, stdraw = jnp.split(ms, 2, -1)
        std = jax.nn.softplus(stdraw) + min_std
        z_new = mean + std * n_t
        return (z_new, h_new), (h_new, z_new, mean, std)

    def _fwd_scan(z0, h0, actions, emb_proj, noise, params):
        step = functools.partial(_step_fwd, params)
        _, (hs, zs, means, stds) = jax.lax.scan(
            step, (z0, h0), (actions, emb_proj, noise), unroll=unroll
        )
        return hs, zs, means, stds

    @jax.custom_vjp
    def op(z0, h0, actions, emb_proj, noise, params):
        return _fwd_scan(z0, h0, actions, emb_proj, noise, params)

    def op_fwd(z0, h0, actions, emb_proj, noise, params):
        hs, zs, means, stds = _fwd_scan(z0, h0, actions, emb_proj, noise, params)
        return (hs, zs, means, stds), (z0, h0, actions, emb_proj, noise, params, hs, zs)

    def op_bwd(res, cots):
        z0, h0, actions, emb_proj, noise, params, hs, zs = res
        d_hs, d_zs, d_means, d_stds = cots
        T, b = hs.shape[:2]
        hidden = h0.shape[-1]
        stoch = z0.shape[-1]

        # ---- batched recompute of every step's activations from the saved
        # states (one (T*B) matmul per layer, nothing sequential)
        z_prev = jnp.concatenate([z0[None], zs[:-1]], 0)
        h_prev = jnp.concatenate([h0[None], hs[:-1]], 0)
        inp_p32 = jnp.concatenate([z_prev, actions.astype(f32)], -1)
        fpre_dt = (
            inp_p32.astype(dt) @ params.w_proj.astype(dt) + params.b_proj.astype(dt)
        )
        feat32 = _act_fwd(fpre_dt, act).astype(f32)
        gi = feat32 @ params.w_i.astype(f32) + params.b_i.astype(f32)
        gh = h_prev @ params.w_h.astype(f32)
        r = jax.nn.sigmoid(gi[..., :hidden] + gh[..., :hidden])
        u = jax.nn.sigmoid(gi[..., hidden : 2 * hidden] + gh[..., hidden : 2 * hidden])
        ghn = gh[..., 2 * hidden :] + params.b_hn.astype(f32)
        n_cand = jnp.tanh(gi[..., 2 * hidden :] + r * ghn)
        xpre_dt = hs.astype(dt) @ params.k_h.astype(dt) + emb_proj
        x32 = _act_fwd(xpre_dt, act).astype(f32)
        ms = x32 @ params.head_k + params.head_b
        stdraw = ms[..., stoch:]
        sig_std = jax.nn.sigmoid(stdraw)  # d softplus

        w_i32 = params.w_i.astype(f32)
        w_h32 = params.w_h.astype(f32)
        w_proj_z32 = params.w_proj[:stoch].astype(f32)
        k_h32 = params.k_h.astype(f32)
        head_k32 = params.head_k.astype(f32)

        def back_step(carry, inp_t):
            dh_c, dz_c = carry
            (
                d_hs_t,
                d_zs_t,
                d_mean_t,
                d_std_t,
                noise_t,
                sig_t,
                actin_r_t,
                h_prev_t,
                r_t,
                u_t,
                n_t,
                ghn_t,
                actin_p_t,
            ) = inp_t

            # reparameterized-sample backward into the (mean, std) head
            dz_tot = d_zs_t + dz_c
            dmean = dz_tot + d_mean_t
            dstd = dz_tot * noise_t + d_std_t
            dms = jnp.concatenate([dmean, dstd * sig_t], -1)

            # representation trunk backward
            dx32 = dms @ head_k32.T
            dxpre = dx32 * _act_grad(actin_r_t.astype(f32), act)
            dh_rep = dxpre @ k_h32.T

            # flax-GRUCell backward
            dh_tot = d_hs_t + dh_c + dh_rep
            du = (h_prev_t - n_t) * dh_tot
            dn = (1.0 - u_t) * dh_tot
            dh_direct = u_t * dh_tot
            dtanh = dn * (1.0 - n_t * n_t)
            dr = dtanh * ghn_t
            dghn = dtanh * r_t
            du_pre = du * u_t * (1.0 - u_t)
            dr_pre = dr * r_t * (1.0 - r_t)
            dgi = jnp.concatenate([dr_pre, du_pre, dtanh], -1)
            dgh = jnp.concatenate([dr_pre, du_pre, dghn], -1)
            dh_prev = dh_direct + dgh @ w_h32.T
            dfeat = dgi @ w_i32.T

            # input projection backward
            dfpre = dfeat * _act_grad(actin_p_t.astype(f32), act)
            dz_prev = dfpre @ w_proj_z32.T
            return (dh_prev, dz_prev), (dms, dxpre, dgi, dgh, dfpre)

        seq = (
            d_hs.astype(f32),
            d_zs.astype(f32),
            d_means.astype(f32),
            d_stds.astype(f32),
            noise,
            sig_std,
            xpre_dt,
            h_prev,
            r,
            u,
            n_cand,
            ghn,
            fpre_dt,
        )
        (dh0, dz0), (dms_s, dxpre_s, dgi_s, dgh_s, dfpre_s) = jax.lax.scan(
            back_step,
            (jnp.zeros_like(h0, f32), jnp.zeros_like(z0, f32)),
            seq,
            reverse=True,
            unroll=unroll,
        )

        # ---- weight gradients: one batched contraction each
        tb = T * b
        grads = V1DynParams(
            w_proj=(inp_p32.reshape(tb, -1).T @ dfpre_s.reshape(tb, -1)).astype(
                params.w_proj.dtype
            ),
            b_proj=dfpre_s.sum((0, 1)).astype(params.b_proj.dtype),
            w_i=(feat32.reshape(tb, -1).T @ dgi_s.reshape(tb, -1)).astype(params.w_i.dtype),
            b_i=dgi_s.sum((0, 1)).astype(params.b_i.dtype),
            w_h=(h_prev.reshape(tb, -1).T @ dgh_s.reshape(tb, -1)).astype(params.w_h.dtype),
            b_hn=dgh_s[..., 2 * hidden :].sum((0, 1)).astype(params.b_hn.dtype),
            k_h=(hs.reshape(tb, hidden).T @ dxpre_s.reshape(tb, -1)).astype(
                params.k_h.dtype
            ),
            head_k=(x32.reshape(tb, -1).T @ dms_s.reshape(tb, -1)).astype(
                params.head_k.dtype
            ),
            head_b=dms_s.sum((0, 1)).astype(params.head_b.dtype),
        )
        d_actions = (dfpre_s @ params.w_proj[stoch:].astype(f32).T).astype(actions.dtype)
        d_emb = dxpre_s.astype(emb_proj.dtype)
        return (
            dz0.astype(z0.dtype),
            dh0.astype(h0.dtype),
            d_actions,
            d_emb,
            jnp.zeros_like(noise),
            grads,
        )

    op.defvjp(op_fwd, op_bwd)
    return op


def extract_dyn_params_v1(rssm_variables, hidden: int) -> V1DynParams:
    """Pull the DV1 op's raw weight leaves out of a bound DV1 RSSM param
    tree (``wm_params["rssm"]``).  Plain dict indexing/slicing so autodiff
    routes the op's weight cotangents back into the original tree; the
    embed-side rows of the representation Dense get their gradient through
    the ``representation_embed_proj`` path."""
    p = rssm_variables["params"]
    lin = p["recurrent_model"]["Dense_0"]
    gru = p["recurrent_model"]["GRUCell_0"]
    rep_lin = p["representation_model"]["DenseActLn_0"]["Dense_0"]
    head = p["representation_model"]["Dense_0"]
    return V1DynParams(
        w_proj=lin["kernel"],
        b_proj=lin["bias"],
        w_i=jnp.concatenate(
            [gru["ir"]["kernel"], gru["iz"]["kernel"], gru["in"]["kernel"]], -1
        ),
        b_i=jnp.concatenate([gru["ir"]["bias"], gru["iz"]["bias"], gru["in"]["bias"]], -1),
        w_h=jnp.concatenate(
            [gru["hr"]["kernel"], gru["hz"]["kernel"], gru["hn"]["kernel"]], -1
        ),
        b_hn=gru["hn"]["bias"],
        k_h=rep_lin["kernel"][:hidden],
        head_k=head["kernel"],
        head_b=head["bias"],
    )


def dyn_rssm_sequence_v1(
    z0,
    h0,
    actions,
    emb_proj,
    noise,
    params: V1DynParams,
    *,
    min_std: float = 0.1,
    matmul_dtype=jnp.float32,
    unroll: int = 1,
    act: str = "elu",
):
    """Run the DV1 T-step dynamic recurrence with the efficient-BPTT VJP.

    z0 (B, S) f32 Gaussian posterior sample; h0 (B, H); actions (T, B, A);
    emb_proj (T, B, R) in the compute dtype (embed-side projection incl.
    the Dense bias, ``RSSM.representation_embed_proj``); noise (T, B, S)
    pre-drawn standard normal.  No is_first gating — DV1 sequences cross
    episode boundaries (reference dreamer_v1/agent.py dynamic:97).

    Returns (hs (T,B,H) f32, zs (T,B,S) f32, means (T,B,S) f32,
    stds (T,B,S) f32); ``zs`` is the reparameterized sample
    ``mean + std * noise`` so gradients flow through both moments,
    exactly like scanning ``dynamic_posterior_from_proj``.
    """
    op = _get_op_v1(float(min_std), jnp.dtype(matmul_dtype).name, int(unroll), str(act))
    return op(z0, h0, actions, emb_proj, noise, params)


def rssm_dyn_bptt_eligible(rssm) -> bool:
    """Does this DV3 RSSM's configuration match the op's closed-form
    backward?  Requires the non-decoupled posterior, LayerNorm blocks,
    a supported activation, unimix > 0, and the plain (non-Pallas) GRU
    cell so the fwd numerics are the reference scan's."""
    return (
        not rssm.decoupled
        and rssm.layer_norm
        and rssm.unimix > 0.0
        and rssm.act in ("silu", "elu")
        and not rssm.fused_gru
    )


def extract_dyn_params(rssm_variables, hidden: int) -> DynParams:
    """Pull the op's raw weight leaves out of a bound DV3 RSSM param tree
    (``wm_params["rssm"]``). Plain dict indexing/slicing, so autodiff
    routes the op's weight cotangents back into the original tree
    (including the h-side rows of the representation model's first Dense —
    the embed-side rows get their gradient through the
    ``representation_embed_proj`` path)."""
    p = rssm_variables["params"]
    lin = p["recurrent_model"]["LinearLnAct_0"]
    gru = p["recurrent_model"]["LayerNormGRUCell_0"]
    rep_lin = p["representation_model"]["LinearLnAct_0"]
    head = p["representation_model"]["Dense_0"]
    w_proj = lin["Dense_0"]["kernel"]
    w_gru = gru["Dense_0"]["kernel"]
    return DynParams(
        w_proj=w_proj,
        b_proj=jnp.zeros((w_proj.shape[-1],), w_proj.dtype),
        lnp_scale=lin["LayerNorm_0"]["scale"],
        lnp_bias=lin["LayerNorm_0"]["bias"],
        w_gru=w_gru,
        b_gru=jnp.zeros((w_gru.shape[-1],), w_gru.dtype),
        lng_scale=gru["LayerNorm_0"]["scale"],
        lng_bias=gru["LayerNorm_0"]["bias"],
        k_h=rep_lin["Dense_0"]["kernel"][:hidden],
        lnr_scale=rep_lin["LayerNorm_0"]["scale"],
        lnr_bias=rep_lin["LayerNorm_0"]["bias"],
        head_k=head["kernel"],
        head_b=head["bias"],
    )


def extract_dyn_params_v2(rssm_variables, hidden: int) -> DynParams:
    """Same extraction for the DV2 RSSM (DenseActLn blocks: Dense WITH
    bias; GRU with bias; rep-trunk LayerNorm optional — absent leaves are
    filled with identity LN params, gated off by the ``rep_ln``/
    ``proj_ln`` statics)."""
    p = rssm_variables["params"]
    lin = p["recurrent_model"]["DenseActLn_0"]
    gru = p["recurrent_model"]["LayerNormGRUCell_0"]
    rep_lin = p["representation_model"]["DenseActLn_0"]
    head = p["representation_model"]["Dense_0"]
    w_proj = lin["Dense_0"]["kernel"]
    w_gru = gru["Dense_0"]["kernel"]
    proj_units = w_proj.shape[-1]
    rep_units = rep_lin["Dense_0"]["kernel"].shape[-1]

    def _ln_or_identity(block, n):
        if "LayerNorm_0" in block:
            return block["LayerNorm_0"]["scale"], block["LayerNorm_0"]["bias"]
        return jnp.ones((n,), w_proj.dtype), jnp.zeros((n,), w_proj.dtype)

    lnp_scale, lnp_bias = _ln_or_identity(lin, proj_units)
    lnr_scale, lnr_bias = _ln_or_identity(rep_lin, rep_units)
    return DynParams(
        w_proj=w_proj,
        b_proj=lin["Dense_0"]["bias"],
        lnp_scale=lnp_scale,
        lnp_bias=lnp_bias,
        w_gru=w_gru,
        b_gru=gru["Dense_0"]["bias"],
        lng_scale=gru["LayerNorm_0"]["scale"],
        lng_bias=gru["LayerNorm_0"]["bias"],
        k_h=rep_lin["Dense_0"]["kernel"][:hidden],
        lnr_scale=lnr_scale,
        lnr_bias=lnr_bias,
        head_k=head["kernel"],
        head_b=head["bias"],
    )


def dyn_rssm_sequence(
    z0,
    h0,
    actions,
    emb_proj,
    is_first,
    noise,
    init_rec,
    init_post,
    params: DynParams,
    *,
    eps_proj: float = 1e-3,
    eps_rep: float = 1e-3,
    unimix: float = 0.01,
    discrete: int = 32,
    matmul_dtype=jnp.float32,
    unroll: int = 1,
    act: str = "silu",
    proj_ln: bool = True,
    rep_ln: bool = True,
):
    """Run the full T-step dynamic recurrence with the efficient-BPTT VJP.

    z0 (B, S) f32 flat posterior; h0 (B, H); actions (T, B, A) f32
    (UNgated — the is_first gating happens inside); emb_proj (T, B, R) in
    the compute dtype (embed-side projection incl. any Dense bias,
    ``RSSM.representation_embed_proj``); is_first (T, B, 1); noise
    (T, B, groups, discrete) pre-drawn gumbel; init_rec (B, H) /
    init_post (B, S) reset states (DV3: the learned initial state; DV2:
    zeros).

    Returns (hs (T,B,H) f32, z_st (T,B,S) f32, logits (T,B,S) f32 — the
    unimix-mixed logits for V3, the raw logits for V2); ``z_st``'s forward
    value is the hard one-hot sample and its gradient is the
    straight-through estimator, exactly like scanning the corresponding
    ``dynamic_posterior`` method.
    """
    op = _get_op(
        float(eps_proj),
        float(eps_rep),
        float(unimix),
        int(discrete),
        jnp.dtype(matmul_dtype).name,
        int(unroll),
        str(act),
        bool(proj_ln),
        bool(rep_ln),
    )
    noise = noise.reshape(*noise.shape[:2], -1)
    return op(z0, h0, actions, emb_proj, is_first, noise, init_rec, init_post, params)
