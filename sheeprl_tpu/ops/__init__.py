from sheeprl_tpu.ops.ring_attention import (  # noqa: F401
    blockwise_attention,
    make_ring_attention,
    ring_attention,
)
from sheeprl_tpu.ops.pallas_gru import fused_gru_cell, reference_gru_cell  # noqa: F401
