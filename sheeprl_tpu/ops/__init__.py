from sheeprl_tpu.ops.ring_attention import (  # noqa: F401
    blockwise_attention,
    make_ring_attention,
    ring_attention,
)
from sheeprl_tpu.ops.pallas_gru import fused_gru_cell, reference_gru_cell  # noqa: F401
from sheeprl_tpu.ops.pallas_per import (  # noqa: F401
    sum_tree_descend,
    sum_tree_sample,
    sum_tree_update,
    sum_tree_write,
)
from sheeprl_tpu.ops.pallas_gather import (  # noqa: F401
    gather_transitions_fused,
    gather_windows_fused,
)
