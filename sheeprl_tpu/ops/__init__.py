from sheeprl_tpu.ops.ring_attention import (  # noqa: F401
    blockwise_attention,
    make_ring_attention,
    ring_attention,
)
