"""Fused Pallas kernel for the Hafner LayerNorm-GRU cell.

The RSSM's sequential scan calls the GRU cell once per time step — the
hottest small op in every Dreamer train step. Unfused, each step costs a
matmul plus several elementwise HBM round trips (LayerNorm, three gates,
the convex update). This kernel keeps the (B, 3H) pre-activations in VMEM
and applies LayerNorm + gates + state update in one pass: one HBM read of
the operands, one HBM write of the new state per step.

The contraction dimension is blocked over the grid (weights stream through
VMEM in (block_k, 3H) tiles with a VMEM accumulator), so the kernel works
for hidden sizes whose full weight matrix exceeds VMEM.

Semantics match ``sheeprl_tpu.models.models.LayerNormGRUCell`` exactly:

    parts = LN(concat([h, x]) @ W)          # no bias, LN over 3H
    reset, cand, update = split(parts, 3)
    cand = tanh(sigmoid(reset) * cand)
    update = sigmoid(update - 1)
    h' = update * cand + (1 - update) * h

Status: integrated. ``models.LayerNormGRUCell(fused=True)`` routes through
``gru_cell`` (Pallas forward + analytic custom-VJP backward), enabled from
configs via ``algo.world_model.recurrent_model.fused``. Validated against
the flax cell bit-for-bit-ish (interpret mode everywhere, compiled on a
real chip: max err ~2e-6). Shapes should be lane-aligned (hidden/feature
dims % 128 == 0) on real TPUs; ``interpret=True`` runs anywhere for
testing.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gru_kernel(h_ref, inp_ref, w_ref, gamma_ref, beta_ref, out_ref, acc_ref, *, nk: int, eps: float, use_ln: bool):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # the contraction runs in ``matmul_dtype`` (bf16 under mixed precision,
    # MXU fast path) with an f32 accumulator; gates/LN/state update stay f32.
    # inp/w are pre-cast by the caller so their tiles stream through VMEM at
    # the matmul dtype's width (half the HBM traffic under bf16).
    acc_ref[:] += jnp.dot(
        inp_ref[:],
        w_ref[:],
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == nk - 1)
    def _finish():
        parts = acc_ref[:]
        if use_ln:  # jaxlint: disable=retrace-branch — static kernel config (python bool)
            mean = parts.mean(axis=-1, keepdims=True)
            var = ((parts - mean) ** 2).mean(axis=-1, keepdims=True)
            parts = (parts - mean) * jax.lax.rsqrt(var + eps)
            parts = parts * gamma_ref[:] + beta_ref[:]
        hidden = h_ref.shape[-1]
        reset = jax.nn.sigmoid(parts[:, :hidden])
        cand = jnp.tanh(reset * parts[:, hidden : 2 * hidden])
        update = jax.nn.sigmoid(parts[:, 2 * hidden :] - 1.0)
        h = h_ref[:].astype(jnp.float32)
        out_ref[:] = (update * cand + (1.0 - update) * h).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("eps", "use_ln", "block_b", "block_k", "interpret", "matmul_dtype"),
)
def fused_gru_cell(
    h: jax.Array,
    x: jax.Array,
    w: jax.Array,
    gamma: Optional[jax.Array] = None,
    beta: Optional[jax.Array] = None,
    *,
    eps: float = 1e-6,
    use_ln: bool = True,
    block_b: int = 8,
    block_k: int = 512,
    interpret: bool = False,
    matmul_dtype=jnp.float32,
) -> jax.Array:
    """One fused LayerNorm-GRU step.

    h: (B, H), x: (B, X), w: (H + X, 3H), gamma/beta: (3H,).
    Returns the new hidden state (B, H)."""
    b, hidden = h.shape
    inp = jnp.concatenate([h, x], axis=-1)
    kdim = inp.shape[-1]
    if use_ln and (gamma is None or beta is None):  # jaxlint: disable=retrace-branch — static kernel config
        raise ValueError("use_ln=True requires gamma and beta")
    if gamma is None:
        gamma = jnp.ones((3 * hidden,), jnp.float32)
        beta = jnp.zeros((3 * hidden,), jnp.float32)

    # stream inp/w at the matmul dtype's width (MXU-native bf16 under mixed
    # precision: half the HBM traffic and half the VMEM per tile)
    inp = inp.astype(matmul_dtype)
    w = w.astype(matmul_dtype)
    block_b = min(block_b, b)
    block_k = min(block_k, kdim)
    # VMEM budget: the (block_k, 3H) weight tile is double-buffered by the
    # pipeline, and the f32 accumulator + h/inp/out blocks live alongside it.
    # Shrink block_k until 2 weight tiles + accumulator fit in ~10 MB (of the
    # 16 MB scoped VMEM), otherwise L/XL hidden sizes (3H >= 9216) OOM at
    # compile time ("ran out of memory in memory space vmem").
    itemsize = jnp.dtype(matmul_dtype).itemsize
    vmem_budget = 10 * 2**20 - 4 * block_b * 3 * hidden  # minus f32 accumulator
    # static tile-size search over python ints (runs at trace time, once)
    while block_k > 128 and 2 * block_k * 3 * hidden * itemsize > vmem_budget:  # jaxlint: disable=retrace-branch
        block_k //= 2
    nb = -(-b // block_b)
    nk = -(-kdim // block_k)
    # pad so the grid tiles exactly (zero rows/cols contribute nothing to
    # the matmul; padded batch rows are dropped at the end)
    pb, pk = nb * block_b - b, nk * block_k - kdim
    if pb:
        h = jnp.pad(h, ((0, pb), (0, 0)))
        inp = jnp.pad(inp, ((0, pb), (0, 0)))
    if pk:
        inp = jnp.pad(inp, ((0, 0), (0, pk)))
        w = jnp.pad(w, ((0, pk), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_gru_kernel, nk=nk, eps=eps, use_ln=use_ln),
        grid=(nb, nk),
        in_specs=[
            pl.BlockSpec((block_b, hidden), lambda i, k: (i, 0)),  # h
            pl.BlockSpec((block_b, block_k), lambda i, k: (i, k)),  # inp
            pl.BlockSpec((block_k, 3 * hidden), lambda i, k: (k, 0)),  # w
            pl.BlockSpec((3 * hidden,), lambda i, k: (0,)),  # gamma
            pl.BlockSpec((3 * hidden,), lambda i, k: (0,)),  # beta
        ],
        out_specs=pl.BlockSpec((block_b, hidden), lambda i, k: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb * block_b, hidden), h.dtype),
        scratch_shapes=[pltpu.VMEM((block_b, 3 * hidden), jnp.float32)],
        interpret=interpret,
    )(h, inp, w, jnp.asarray(gamma, jnp.float32), jnp.asarray(beta, jnp.float32))
    return out[:b]


def reference_gru_cell(h, x, w, gamma=None, beta=None, *, eps: float = 1e-6, use_ln: bool = True):
    """Pure-jax reference with identical semantics (the flax cell's math)."""
    parts = jnp.concatenate([h, x], axis=-1) @ w
    if use_ln:
        mean = parts.mean(-1, keepdims=True)
        var = ((parts - mean) ** 2).mean(-1, keepdims=True)
        parts = (parts - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    hidden = h.shape[-1]
    reset = jax.nn.sigmoid(parts[..., :hidden])
    cand = jnp.tanh(reset * parts[..., hidden : 2 * hidden])
    update = jax.nn.sigmoid(parts[..., 2 * hidden :] - 1.0)
    return update * cand + (1.0 - update) * h


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def gru_cell(
    h, x, w, gamma, beta,
    eps: float = 1e-6, use_ln: bool = True, block_b: int = 8, block_k: int = 512,
    interpret: bool = False, matmul_dtype=jnp.float32,
):
    """Training-safe fused GRU step: Pallas forward, analytic XLA backward.

    The backward recomputes the (cheap) gate activations from the saved
    residuals and differentiates the reference formulas — the memory win of
    the fused forward is kept, and the op is usable inside the RSSM train
    scan. ``interpret=True`` runs the kernel in interpreter mode so the op
    works on non-TPU backends (tests, CPU dry runs)."""
    return fused_gru_cell(
        h, x, w, gamma, beta,
        eps=eps, use_ln=use_ln, block_b=block_b, block_k=block_k, interpret=interpret,
        matmul_dtype=matmul_dtype,
    )


def _gru_fwd(h, x, w, gamma, beta, eps, use_ln, block_b, block_k, interpret, matmul_dtype):
    out = fused_gru_cell(
        h, x, w, gamma, beta,
        eps=eps, use_ln=use_ln, block_b=block_b, block_k=block_k, interpret=interpret,
        matmul_dtype=matmul_dtype,
    )
    return out, (h, x, w, gamma, beta)


def _gru_bwd(eps, use_ln, block_b, block_k, interpret, matmul_dtype, res, g):
    h, x, w, gamma, beta = res
    # rematerialize through the reference formulas and use XLA's VJP; the
    # activations are tiny next to the weight gradient matmuls
    _, vjp = jax.vjp(
        lambda h_, x_, w_, ga_, be_: reference_gru_cell(
            h_, x_, w_, ga_, be_, eps=eps, use_ln=use_ln
        ),
        h, x, w, gamma, beta,
    )
    return vjp(g)


gru_cell.defvjp(_gru_fwd, _gru_bwd)
