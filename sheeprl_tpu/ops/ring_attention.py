"""Sequence/context parallelism primitives: blockwise + ring attention.

The reference framework has no attention anywhere (SURVEY.md §5.7) — its
long-sequence handling is truncated BPTT through the RSSM. These ops make
long-context sequence parallelism a first-class capability of the TPU
runtime for attention-based models: the sequence axis is sharded over a
mesh axis, every device computes attention for its query shard, and K/V
shards rotate around the ring over ICI (`jax.lax.ppermute`) while an
online-softmax accumulator folds in one block per hop — memory per device
stays O(seq/n_devices), and the K/V transfer overlaps with the block
matmuls (Ring Attention, arXiv:2310.01889; blockwise parallel transformers,
arXiv:2305.19370).

Layouts: `q, k, v` are `(..., S, H, D)` (sequence, heads, head_dim) —
batch dims lead. All math runs in float32 accumulators regardless of input
dtype (bf16-safe).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """(…, Sq, H, D) x (…, Sk, H, D) -> (…, H, Sq, Sk) scaled scores."""
    d = q.shape[-1]
    return jnp.einsum("...qhd,...khd->...hqk", q, k).astype(jnp.float32) / jnp.sqrt(
        jnp.float32(d)
    )


def _online_update(carry, scores: jax.Array, v: jax.Array, mask: Optional[jax.Array]):
    """Fold one KV block into the online-softmax state.

    carry: (acc (…, H, Sq, D), row_sum (…, H, Sq, 1), row_max (…, H, Sq, 1))
    """
    acc, row_sum, row_max = carry
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    block_max = scores.max(-1, keepdims=True)
    new_max = jnp.maximum(row_max, block_max)
    # -inf rows (fully masked so far) must not produce NaNs
    safe_new_max = jnp.where(jnp.isneginf(new_max), 0.0, new_max)
    correction = jnp.exp(row_max - safe_new_max)
    p = jnp.exp(scores - safe_new_max)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    acc = acc * correction + jnp.einsum("...hqk,...khd->...hqd", p, v.astype(jnp.float32))
    row_sum = row_sum * correction + p.sum(-1, keepdims=True)
    return acc, row_sum, new_max


def _finalize(acc: jax.Array, row_sum: jax.Array, dtype) -> jax.Array:
    out = acc / jnp.maximum(row_sum, 1e-30)
    # (…, H, Sq, D) -> (…, Sq, H, D)
    return jnp.swapaxes(out, -3, -2).astype(dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_size: int = 512,
    causal: bool = False,
) -> jax.Array:
    """Single-device flash-style attention: `lax.scan` over KV blocks with
    an online softmax — O(S * block) memory instead of O(S^2).

    q, k, v: (..., S, H, D). Returns (..., Sq, H, D)."""
    s_k = k.shape[-3]
    block_size = min(block_size, s_k)
    n_blocks = -(-s_k // block_size)
    pad = n_blocks * block_size - s_k
    if pad:
        pad_widths = [(0, 0)] * (k.ndim - 3) + [(0, pad), (0, 0), (0, 0)]
        k = jnp.pad(k, pad_widths)
        v = jnp.pad(v, pad_widths)

    s_q = q.shape[-3]
    h = q.shape[-2]
    batch_shape = q.shape[:-3]
    q_pos = jnp.arange(s_q)

    # (n_blocks, …, block, H, D) scan layout
    def to_blocks(x):
        x = x.reshape(*batch_shape, n_blocks, block_size, h, x.shape[-1])
        return jnp.moveaxis(x, len(batch_shape), 0)

    kb, vb = to_blocks(k), to_blocks(v)

    acc = jnp.zeros((*batch_shape, h, s_q, q.shape[-1]), jnp.float32)
    row_sum = jnp.zeros((*batch_shape, h, s_q, 1), jnp.float32)
    row_max = jnp.full((*batch_shape, h, s_q, 1), -jnp.inf, jnp.float32)

    def step(carry, inp):
        i, (k_i, v_i) = inp
        scores = _block_scores(q, k_i)
        k_pos = i * block_size + jnp.arange(block_size)
        mask = k_pos[None, :] < s_k  # padding mask, (1, block)
        if causal:
            mask = mask & (k_pos[None, :] <= q_pos[:, None])
        mask = jnp.broadcast_to(mask, scores.shape[-2:])
        return _online_update(carry, scores, v_i, mask), None

    # remat the block fold: autodiff would otherwise SAVE every block's
    # (H, Sq, block) scores/probabilities for the backward pass, making the
    # "O(S * block)" claim quietly O(S^2) once gradients flow (caught by
    # benchmarks/bench_ring_attention.py's compiled-memory sweep).
    # Recomputing scores in the backward pass is the flash-attention trade.
    (acc, row_sum, _), _ = jax.lax.scan(
        jax.checkpoint(step), (acc, row_sum, row_max), (jnp.arange(n_blocks), (kb, vb))
    )
    return _finalize(acc, row_sum, q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Ring attention body — call INSIDE `shard_map` with the sequence axis
    sharded over `axis_name`.

    Each device holds `(..., S/n, H, D)` shards. K/V rotate around the ring
    with `ppermute`; after n hops every query shard has attended to the
    full sequence. For `causal=True` global positions are reconstructed
    from the device index and the hop count."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[-3]
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = idx * s_local + jnp.arange(s_local)

    acc = jnp.zeros((*q.shape[:-3], q.shape[-2], s_local, q.shape[-1]), jnp.float32)
    row_sum = jnp.zeros((*q.shape[:-3], q.shape[-2], s_local, 1), jnp.float32)
    row_max = jnp.full((*q.shape[:-3], q.shape[-2], s_local, 1), -jnp.inf, jnp.float32)

    # hop loop as lax.scan: an unrolled python loop left EVERY hop's
    # (H, S/n, S/n) score/probability buffers simultaneously live (XLA's
    # buffer assignment would not reuse them across the unrolled hops), so
    # both forward and backward peaked at O(S^2/n) per device — exactly the
    # blowup ring attention exists to avoid.  With a scan only one hop's
    # buffers exist at a time, and the rematted body keeps autodiff from
    # saving per-hop scores (the flash-attention trade: recompute in bwd).
    # Measured by benchmarks/bench_ring_attention.py's compiled-memory sweep.
    # inner blocking: even one hop's FULL (S/n, S/n) score block is the
    # dominant working set at long context; folding the hop's K/V shard in
    # (S/n, block) chunks keeps per-device temp memory ~linear in S/n
    h = q.shape[-2]
    batch_shape = q.shape[:-3]
    block = min(512, s_local)
    n_inner = -(-s_local // block)
    pad = n_inner * block - s_local

    def hop(carry, i):
        acc_state, k_i, v_i = carry
        src = (idx - i) % n  # K/V origin device after i hops

        kp, vp = k_i, v_i
        if pad:
            widths = [(0, 0)] * (k_i.ndim - 3) + [(0, pad), (0, 0), (0, 0)]
            kp, vp = jnp.pad(kp, widths), jnp.pad(vp, widths)

        def to_blocks(x):
            x = x.reshape(*batch_shape, n_inner, block, h, x.shape[-1])
            return jnp.moveaxis(x, len(batch_shape), 0)

        def inner(carry2, inp):
            j, (k_j, v_j) = inp
            scores = _block_scores(q, k_j)
            k_pos = src * s_local + j * block + jnp.arange(block)
            mask = k_pos[None, :] < (src * s_local + s_local)  # pad mask
            if causal:
                mask = mask & (k_pos[None, :] <= q_pos[:, None])
            mask = jnp.broadcast_to(mask, scores.shape[-2:])
            return _online_update(carry2, scores, v_j, mask), None

        acc_state, _ = jax.lax.scan(
            jax.checkpoint(inner), acc_state, (jnp.arange(n_inner), (to_blocks(kp), to_blocks(vp)))
        )
        # rotate K/V one step around the ring (the final rotation returns
        # them to their origin device — semantics-free)
        k_i = jax.lax.ppermute(k_i, axis_name, perm)
        v_i = jax.lax.ppermute(v_i, axis_name, perm)
        return (acc_state, k_i, v_i), None

    # the zeros-initialized accumulators are device-INvariant to shard_map's
    # varying-axes typing while the body's outputs (mixed with sharded q/k/v)
    # are device-varying — mark the carry varying up front so the scan types
    # close (this is what forced the old unrolled-python hop loop)
    if hasattr(jax.lax, "pcast"):
        acc, row_sum, row_max = jax.lax.pcast(
            (acc, row_sum, row_max), axis_name, to="varying"
        )
    else:  # older jax
        acc, row_sum, row_max = jax.lax.pvary((acc, row_sum, row_max), axis_name)
    init = ((acc, row_sum, row_max), k, v)
    # no outer remat: the inner fold already remats the score blocks.
    # NOTE on gradients: the outer scan saves each hop's carried K/V shard
    # as a residual, so backward holds n x (S/n) = O(S) of K/V per device
    # (a few hundred MB at 64K tokens) on top of the O(S/n * block)
    # activations; eliminating it needs a custom VJP that re-materializes
    # K/V by continuing the ring rotation in reverse — future work.
    (acc_state, _, _), _ = jax.lax.scan(hop, init, jnp.arange(n))
    acc, row_sum, _ = acc_state
    return _finalize(acc, row_sum, q.dtype)


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = "data",
    causal: bool = False,
):
    """jitted ring attention over `mesh`: inputs `(..., S, H, D)` with the
    sequence axis sharded over `axis_name` (S divisible by the axis size).

    This is the public entry: it wraps `ring_attention` in `shard_map` with
    the sequence-sharded PartitionSpecs and jits the result. The spec is
    built per input rank so any number of leading batch dims works."""
    fns = {}

    def _build(ndim: int):
        # (..., S, H, D): shard the sequence axis, replicate the rest
        spec = P(*([None] * (ndim - 3)), axis_name, None, None)

        @jax.jit
        @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        def fn(q, k, v):
            return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

        return fn, NamedSharding(mesh, spec)

    def apply(q, k, v):
        if q.ndim < 3:
            raise ValueError(f"ring attention inputs must be (..., S, H, D), got rank {q.ndim}")
        if q.ndim not in fns:
            fns[q.ndim] = _build(q.ndim)
        fn, sharding = fns[q.ndim]
        q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
        return fn(q, k, v)

    return apply
