"""Sequence/context parallelism primitives: blockwise + ring attention.

The reference framework has no attention anywhere (SURVEY.md §5.7) — its
long-sequence handling is truncated BPTT through the RSSM. These ops make
long-context sequence parallelism a first-class capability of the TPU
runtime for attention-based models: the sequence axis is sharded over a
mesh axis, every device computes attention for its query shard, and K/V
shards rotate around the ring over ICI (`jax.lax.ppermute`) while an
online-softmax accumulator folds in one block per hop — memory per device
stays O(seq/n_devices), and the K/V transfer overlaps with the block
matmuls (Ring Attention, arXiv:2310.01889; blockwise parallel transformers,
arXiv:2305.19370).

Layouts: `q, k, v` are `(..., S, H, D)` (sequence, heads, head_dim) —
batch dims lead. All math runs in float32 accumulators regardless of input
dtype (bf16-safe).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from sheeprl_tpu.utils.jax_compat import shard_map


def _block_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """(…, Sq, H, D) x (…, Sk, H, D) -> (…, H, Sq, Sk) scaled scores."""
    d = q.shape[-1]
    return jnp.einsum("...qhd,...khd->...hqk", q, k).astype(jnp.float32) / jnp.sqrt(
        jnp.float32(d)
    )


def _online_update(carry, scores: jax.Array, v: jax.Array, mask: Optional[jax.Array]):
    """Fold one KV block into the online-softmax state.

    carry: (acc (…, H, Sq, D), row_sum (…, H, Sq, 1), row_max (…, H, Sq, 1))
    """
    acc, row_sum, row_max = carry
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    block_max = scores.max(-1, keepdims=True)
    new_max = jnp.maximum(row_max, block_max)
    # -inf rows (fully masked so far) must not produce NaNs
    safe_new_max = jnp.where(jnp.isneginf(new_max), 0.0, new_max)
    correction = jnp.exp(row_max - safe_new_max)
    p = jnp.exp(scores - safe_new_max)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    acc = acc * correction + jnp.einsum("...hqk,...khd->...hqd", p, v.astype(jnp.float32))
    row_sum = row_sum * correction + p.sum(-1, keepdims=True)
    return acc, row_sum, new_max


def _finalize(acc: jax.Array, row_sum: jax.Array, dtype) -> jax.Array:
    out = acc / jnp.maximum(row_sum, 1e-30)
    # (…, H, Sq, D) -> (…, Sq, H, D)
    return jnp.swapaxes(out, -3, -2).astype(dtype)


def _mark_varying(tree, axis_name: str):
    """Zeros-initialized accumulators are device-INvariant to shard_map's
    varying-axes typing while the scan body's outputs (mixed with sharded
    inputs) are device-varying — mark the carry varying up front so the
    scan types close."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(tree, axis_name, to="varying")
    return jax.lax.pvary(tree, axis_name)  # older jax


def _hop_block_mask(src, j, block: int, s_local: int, q_pos, scores_shape, causal: bool):
    """Padding + causal mask for inner block `j` of the K/V shard that
    originated on device `src` — SHARED by the forward fold and the custom
    backward so the recomputed softmax weights can never desynchronize
    from the forward's."""
    k_pos = src * s_local + j * block + jnp.arange(block)
    mask = k_pos[None, :] < (src * s_local + s_local)  # pad mask
    if causal:
        mask = mask & (k_pos[None, :] <= q_pos[:, None])
    return jnp.broadcast_to(mask, scores_shape)


def _pad_blocks(x, batch_shape, n_inner: int, block: int, pad: int):
    """(…, S/n, H, D) -> (n_inner, …, block, H, D) scan layout, padding the
    sequence axis up to a block multiple. Shared by fwd + bwd hops."""
    if pad:
        widths = [(0, 0)] * (x.ndim - 3) + [(0, pad), (0, 0), (0, 0)]
        x = jnp.pad(x, widths)
    h = x.shape[-2]
    x = x.reshape(*batch_shape, n_inner, block, h, x.shape[-1])
    return jnp.moveaxis(x, len(batch_shape), 0)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_size: int = 512,
    causal: bool = False,
) -> jax.Array:
    """Single-device flash-style attention: `lax.scan` over KV blocks with
    an online softmax — O(S * block) memory instead of O(S^2).

    q, k, v: (..., S, H, D). Returns (..., Sq, H, D)."""
    s_k = k.shape[-3]
    block_size = min(block_size, s_k)
    n_blocks = -(-s_k // block_size)
    pad = n_blocks * block_size - s_k

    s_q = q.shape[-3]
    h = q.shape[-2]
    batch_shape = q.shape[:-3]
    q_pos = jnp.arange(s_q)

    # single-device case == one ring hop with src=0 and the whole sequence
    # as the "local shard": reuse the shared blocking + mask helpers so the
    # logic cannot drift from the ring path
    kb = _pad_blocks(k, batch_shape, n_blocks, block_size, pad)
    vb = _pad_blocks(v, batch_shape, n_blocks, block_size, pad)

    acc = jnp.zeros((*batch_shape, h, s_q, q.shape[-1]), jnp.float32)
    row_sum = jnp.zeros((*batch_shape, h, s_q, 1), jnp.float32)
    row_max = jnp.full((*batch_shape, h, s_q, 1), -jnp.inf, jnp.float32)

    def step(carry, inp):
        i, (k_i, v_i) = inp
        scores = _block_scores(q, k_i)
        mask = _hop_block_mask(0, i, block_size, s_k, q_pos, scores.shape[-2:], causal)
        return _online_update(carry, scores, v_i, mask), None

    # remat the block fold: autodiff would otherwise SAVE every block's
    # (H, Sq, block) scores/probabilities for the backward pass, making the
    # "O(S * block)" claim quietly O(S^2) once gradients flow (caught by
    # benchmarks/bench_ring_attention.py's compiled-memory sweep).
    # Recomputing scores in the backward pass is the flash-attention trade.
    (acc, row_sum, _), _ = jax.lax.scan(
        jax.checkpoint(step), (acc, row_sum, row_max), (jnp.arange(n_blocks), (kb, vb))
    )
    return _finalize(acc, row_sum, q.dtype)


def _ring_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
):
    """Forward ring pass; returns `(out, lse)` where `lse` is the
    per-query log-sum-exp `(…, H, Sq, 1)` the custom backward needs to
    re-normalize recomputed score blocks."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[-3]
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = idx * s_local + jnp.arange(s_local)

    acc = jnp.zeros((*q.shape[:-3], q.shape[-2], s_local, q.shape[-1]), jnp.float32)
    row_sum = jnp.zeros((*q.shape[:-3], q.shape[-2], s_local, 1), jnp.float32)
    row_max = jnp.full((*q.shape[:-3], q.shape[-2], s_local, 1), -jnp.inf, jnp.float32)

    # hop loop as lax.scan: an unrolled python loop left EVERY hop's
    # (H, S/n, S/n) score/probability buffers simultaneously live (XLA's
    # buffer assignment would not reuse them across the unrolled hops), so
    # both forward and backward peaked at O(S^2/n) per device — exactly the
    # blowup ring attention exists to avoid.  With a scan only one hop's
    # buffers exist at a time, and the rematted body keeps autodiff from
    # saving per-hop scores (the flash-attention trade: recompute in bwd).
    # Measured by benchmarks/bench_ring_attention.py's compiled-memory sweep.
    # inner blocking: even one hop's FULL (S/n, S/n) score block is the
    # dominant working set at long context; folding the hop's K/V shard in
    # (S/n, block) chunks keeps per-device temp memory ~linear in S/n
    batch_shape = q.shape[:-3]
    block = min(512, s_local)
    n_inner = -(-s_local // block)
    pad = n_inner * block - s_local

    def hop(carry, i):
        acc_state, k_i, v_i = carry
        src = (idx - i) % n  # K/V origin device after i hops

        def inner(carry2, inp):
            j, (k_j, v_j) = inp
            scores = _block_scores(q, k_j)
            mask = _hop_block_mask(src, j, block, s_local, q_pos, scores.shape[-2:], causal)
            return _online_update(carry2, scores, v_j, mask), None

        acc_state, _ = jax.lax.scan(
            jax.checkpoint(inner),
            acc_state,
            (
                jnp.arange(n_inner),
                (
                    _pad_blocks(k_i, batch_shape, n_inner, block, pad),
                    _pad_blocks(v_i, batch_shape, n_inner, block, pad),
                ),
            ),
        )
        # rotate K/V one step around the ring (the final rotation returns
        # them to their origin device — semantics-free)
        k_i = jax.lax.ppermute(k_i, axis_name, perm)
        v_i = jax.lax.ppermute(v_i, axis_name, perm)
        return (acc_state, k_i, v_i), None

    acc, row_sum, row_max = _mark_varying((acc, row_sum, row_max), axis_name)
    init = ((acc, row_sum, row_max), k, v)
    # no outer remat: the inner fold already remats the score blocks, and
    # under the custom VJP below autodiff never traces this scan at all.
    (acc_state, _, _), _ = jax.lax.scan(hop, init, jnp.arange(n))
    acc, row_sum, row_max = acc_state
    lse = jnp.where(
        row_sum > 0.0,
        jnp.where(jnp.isneginf(row_max), 0.0, row_max) + jnp.log(jnp.maximum(row_sum, 1e-30)),
        -jnp.inf,
    )
    return _finalize(acc, row_sum, q.dtype), lse


def _ring_backward(q, k, v, out, lse, g, axis_name: str, causal: bool):
    """Flash-style backward for the ring: rotate K/V (and their gradient
    accumulators) around the ring AGAIN, recomputing each hop's score
    blocks from the saved `lse` instead of storing them — so residuals are
    just the local q/k/v/out/lse shards, O(S/n) per device, not the
    O(S) per-device K/V carry chain a plain `lax.scan` VJP would save.

    Standard flash-attention gradients per block (scores already scaled):
      W  = exp(scores - lse)            (softmax weights, recomputed)
      dV = Wᵀ · dO
      dP = dO · Vᵀ
      dS = W ⊙ (dP - Δ) / sqrt(D),  Δ = rowsum(dO ⊙ O)
      dQ += dS · K,   dK += dSᵀ · Q
    Each device keeps its query-shard quantities (q, dO, Δ, lse, dQ)
    resident; (K, V, dK, dV) travel together — after n hops dK/dV have
    accumulated every device's contribution and are home again."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[-3]
    h = q.shape[-2]
    d = q.shape[-1]
    batch_shape = q.shape[:-3]
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_pos = idx * s_local + jnp.arange(s_local)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))

    # head-major f32 copies of the query-resident tensors
    qt = jnp.swapaxes(q, -3, -2).astype(jnp.float32)  # (…, H, Sq, D)
    gt = jnp.swapaxes(g, -3, -2).astype(jnp.float32)
    ot = jnp.swapaxes(out, -3, -2).astype(jnp.float32)
    delta = (gt * ot).sum(-1, keepdims=True)  # (…, H, Sq, 1)
    dead = jnp.isneginf(lse)  # fully-masked query rows contribute nothing
    safe_lse = jnp.where(dead, 0.0, lse)

    block = min(512, s_local)
    n_inner = -(-s_local // block)
    pad = n_inner * block - s_local

    def from_blocks(x):
        x = jnp.moveaxis(x, 0, len(batch_shape))
        x = x.reshape(*batch_shape, n_inner * block, h, x.shape[-1])
        return x[..., :s_local, :, :]

    def hop(carry, i):
        dq, k_i, v_i, dk_i, dv_i = carry
        src = (idx - i) % n  # K/V origin device after i hops (as in fwd)

        def inner(dq2, inp):
            j, (k_j, v_j) = inp
            scores = _block_scores(q, k_j)  # (…, H, Sq, block) f32
            mask = _hop_block_mask(src, j, block, s_local, q_pos, scores.shape[-2:], causal)
            w = jnp.where(mask & ~dead, jnp.exp(scores - safe_lse), 0.0)
            kt_j = jnp.swapaxes(k_j, -3, -2).astype(jnp.float32)  # (…, H, block, D)
            vt_j = jnp.swapaxes(v_j, -3, -2).astype(jnp.float32)
            dp = jnp.einsum("...hqd,...hkd->...hqk", gt, vt_j)
            ds = w * (dp - delta) * scale
            dq_c = jnp.einsum("...hqk,...hkd->...hqd", ds, kt_j)
            dk_j = jnp.einsum("...hqk,...hqd->...khd", ds, qt)
            dv_j = jnp.einsum("...hqk,...hqd->...khd", w, gt)
            return dq2 + dq_c, (dk_j, dv_j)

        dq, (dk_blocks, dv_blocks) = jax.lax.scan(
            inner,
            dq,
            (
                jnp.arange(n_inner),
                (
                    _pad_blocks(k_i, batch_shape, n_inner, block, pad),
                    _pad_blocks(v_i, batch_shape, n_inner, block, pad),
                ),
            ),
        )
        dk_i = dk_i + from_blocks(dk_blocks)
        dv_i = dv_i + from_blocks(dv_blocks)
        # rotate the shard AND its gradient accumulator together; after n
        # hops both are back on the shard's origin device
        k_i, v_i, dk_i, dv_i = (
            jax.lax.ppermute(x, axis_name, perm) for x in (k_i, v_i, dk_i, dv_i)
        )
        return (dq, k_i, v_i, dk_i, dv_i), None

    dq = jnp.zeros(qt.shape, jnp.float32)
    dk = jnp.zeros((*batch_shape, s_local, h, d), jnp.float32)
    dv = jnp.zeros(dk.shape, jnp.float32)
    dq, dk, dv = _mark_varying((dq, dk, dv), axis_name)
    (dq, _, _, dk, dv), _ = jax.lax.scan(hop, (dq, k, v, dk, dv), jnp.arange(n))
    dq = jnp.swapaxes(dq, -3, -2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@lru_cache(maxsize=None)
def _ring_attention_vjp(axis_name: str, causal: bool):
    @jax.custom_vjp
    def attn(q, k, v):
        return _ring_forward(q, k, v, axis_name, causal)[0]

    def fwd(q, k, v):
        out, lse = _ring_forward(q, k, v, axis_name, causal)
        return out, (q, k, v, out, lse)

    def bwd(res, g):
        return _ring_backward(*res, g, axis_name, causal)

    attn.defvjp(fwd, bwd)
    return attn


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Ring attention body — call INSIDE `shard_map` with the sequence axis
    sharded over `axis_name`.

    Each device holds `(..., S/n, H, D)` shards. K/V rotate around the ring
    with `ppermute`; after n hops every query shard has attended to the
    full sequence. For `causal=True` global positions are reconstructed
    from the device index and the hop count.

    Differentiation goes through a custom VJP (`_ring_backward`) that
    re-rotates K/V around the ring instead of saving the forward scan's
    per-hop K/V carries — per-device memory stays O(S/n) under gradients
    (measured by benchmarks/bench_ring_attention.py). Trade-off of
    `jax.custom_vjp`: only reverse-mode differentiation is supported —
    `jax.jvp` / `jax.jacfwd` / `jax.linearize` through this op raise."""
    return _ring_attention_vjp(axis_name, bool(causal))(q, k, v)


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = "data",
    causal: bool = False,
):
    """jitted ring attention over `mesh`: inputs `(..., S, H, D)` with the
    sequence axis sharded over `axis_name` (S divisible by the axis size).

    This is the public entry: it wraps `ring_attention` in `shard_map` with
    the sequence-sharded PartitionSpecs and jits the result. The spec is
    built per input rank so any number of leading batch dims works."""
    fns = {}

    def _build(ndim: int):
        # (..., S, H, D): shard the sequence axis, replicate the rest
        spec = P(*([None] * (ndim - 3)), axis_name, None, None)

        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        def fn(q, k, v):
            return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

        return fn, NamedSharding(mesh, spec)

    def apply(q, k, v):
        if q.ndim < 3:
            raise ValueError(f"ring attention inputs must be (..., S, H, D), got rank {q.ndim}")
        if q.ndim not in fns:
            fns[q.ndim] = _build(q.ndim)
        fn, sharding = fns[q.ndim]
        q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
        return fn(q, k, v)

    return apply
