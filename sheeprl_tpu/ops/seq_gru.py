"""Sequence-level fused LayerNorm-GRU: T steps in ONE Pallas kernel.

The per-step fused cell (``ops/pallas_gru.py``) removes the elementwise HBM
round trips inside one GRU step, but a ``lax.scan`` over it still pays, per
time step, a kernel launch plus a re-read of the (H+X, 3H) weight matrix.
For the latency-bound RSSM train scans that launch/stream overhead is most
of the remaining while-loop time (benchmarks/results/dv3_profile_r4.json).

This op runs the WHOLE T-step recurrence inside one ``pallas_call``:

* grid = (T,) — TPU grid steps execute sequentially, so the hidden state
  lives in a VMEM scratch carried across iterations;
* the weight matrix's BlockSpec index map is constant, so Mosaic keeps it
  resident in VMEM for the whole sequence (fetched from HBM once);
* the per-step math is the Hafner LayerNorm-GRU of
  ``models.LayerNormGRUCell`` with the Dreamer ``is_first`` reset gate
  folded in (state swaps to ``init_rec`` where ``is_first`` is set), i.e.
  exactly ``RSSM.gru_step_gated`` (reference sheeprl LayerNormGRUCell:331 +
  RSSM.dynamic:390 reset logic).

Training uses a custom VJP whose backward is the *efficient BPTT* form:
everything that can batch over time does — the pre-LN activations are
recomputed from the SAVED hidden states in one (T*B, H+X) @ (H+X, 3H)
matmul, and the weight/input/LN-parameter gradients are single batched
contractions — so the reverse ``lax.scan`` carries only ``dh`` (B, H) and
does one small (B, 3H) @ (3H, H) matmul per step. Compared with
autodiff-through-scan this removes the (H+X, 3H) weight-gradient
accumulator from the backward loop carry and all per-step residual stacking
except the hidden states themselves.

Weights must fit in VMEM (f32: (H+X)*3H*4 bytes; S/M Dreamer sizes do, L/XL
do not) — ``fits_vmem`` gates eligibility and callers fall back to the
per-step path. Lane alignment (H, X, B multiples of 128/8) is padded for.

Status: numerics (forward + gradients) pinned against the pure-scan
reference in ``tests/test_parallel/test_seq_gru.py`` (interpret mode);
wall-clock on a real chip is measured by ``benchmarks/bench_seq_gru.py``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["gru_sequence", "gru_sequence_reference", "fits_vmem"]


def fits_vmem(hidden: int, in_dim: int, matmul_dtype=jnp.float32, budget_mb: float = 10.0) -> bool:
    """Can the (H+X, 3H) weight matrix stay VMEM-resident (plus working set)?"""
    itemsize = jnp.dtype(matmul_dtype).itemsize
    return (hidden + in_dim) * 3 * hidden * itemsize <= budget_mb * 2**20


def _gate_math(parts: jax.Array, hg: jax.Array, hidden: int) -> jax.Array:
    reset = jax.nn.sigmoid(parts[..., :hidden])
    cand = jnp.tanh(reset * parts[..., hidden : 2 * hidden])
    update = jax.nn.sigmoid(parts[..., 2 * hidden :] - 1.0)
    return update * cand + (1.0 - update) * hg


def _ln(z: jax.Array, gamma: jax.Array, beta: jax.Array, eps: float) -> jax.Array:
    mu = z.mean(-1, keepdims=True)
    var = jnp.maximum((z * z).mean(-1, keepdims=True) - mu * mu, 0.0)
    return (z - mu) * jax.lax.rsqrt(var + eps) * gamma + beta


def gru_sequence_reference(h0, xs, w, gamma, beta, is_first=None, init_rec=None, *, eps=1e-6, matmul_dtype=jnp.float32):
    """Pure lax.scan reference with identical semantics (autodiff-friendly)."""
    hidden = h0.shape[-1]
    if is_first is None:
        is_first = jnp.zeros((*xs.shape[:2], 1), jnp.float32)
    if init_rec is None:
        init_rec = jnp.zeros_like(h0)

    def step(h, inp):
        x, first = inp
        hg = (1.0 - first) * h + first * init_rec.astype(jnp.float32)
        z = jnp.concatenate([hg.astype(matmul_dtype), x.astype(matmul_dtype)], -1) @ w.astype(matmul_dtype)
        parts = _ln(z.astype(jnp.float32), gamma, beta, eps)
        h_new = _gate_math(parts, hg, hidden)
        return h_new, h_new

    _, hs = jax.lax.scan(step, h0.astype(jnp.float32), (xs, is_first.astype(jnp.float32)))
    return hs


def _seq_kernel(x_ref, first_ref, init_ref, h0_ref, w_ref, gamma_ref, beta_ref, out_ref, h_ref, *, eps: float, hidden: int):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_ref[:] = h0_ref[:]

    first = first_ref[0]  # block (1, B, 1) -> (B, 1) f32
    hg = (1.0 - first) * h_ref[:] + first * init_ref[:]
    inp = jnp.concatenate([hg.astype(x_ref.dtype), x_ref[0]], -1)
    z = jnp.dot(inp, w_ref[:], preferred_element_type=jnp.float32)
    parts = _ln(z, gamma_ref[:], beta_ref[:], eps)
    h_new = _gate_math(parts, hg, hidden)
    h_ref[:] = h_new
    out_ref[0] = h_new


@functools.partial(jax.jit, static_argnames=("eps", "interpret", "matmul_dtype"))
def _gru_sequence_fwd_pallas(h0, xs, w, gamma, beta, is_first, init_rec, *, eps, interpret, matmul_dtype):
    T, b, xdim = xs.shape
    hidden = h0.shape[-1]
    kdim = hidden + xdim

    xs = xs.astype(matmul_dtype)
    w = w.astype(matmul_dtype)
    # pad batch to a sublane multiple; padded rows run harmless math on zeros
    pb = (-b) % 8
    if pb:
        h0 = jnp.pad(h0, ((0, pb), (0, 0)))
        xs = jnp.pad(xs, ((0, 0), (0, pb), (0, 0)))
        is_first = jnp.pad(is_first, ((0, 0), (0, pb), (0, 0)))
        init_rec = jnp.pad(init_rec, ((0, pb), (0, 0)))
    bp = b + pb

    hs = pl.pallas_call(
        functools.partial(_seq_kernel, eps=eps, hidden=hidden),
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, bp, xdim), lambda t: (t, 0, 0)),  # xs
            pl.BlockSpec((1, bp, 1), lambda t: (t, 0, 0)),  # is_first
            pl.BlockSpec((bp, hidden), lambda t: (0, 0)),  # init_rec (resident)
            pl.BlockSpec((bp, hidden), lambda t: (0, 0)),  # h0 (resident)
            pl.BlockSpec((kdim, 3 * hidden), lambda t: (0, 0)),  # w (resident)
            pl.BlockSpec((3 * hidden,), lambda t: (0,)),
            pl.BlockSpec((3 * hidden,), lambda t: (0,)),
        ],
        out_specs=pl.BlockSpec((1, bp, hidden), lambda t: (t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((T, bp, hidden), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bp, hidden), jnp.float32)],
        interpret=interpret,
    )(
        xs.reshape(T, bp, xdim),
        is_first.astype(jnp.float32),
        init_rec.astype(jnp.float32),
        h0.astype(jnp.float32),
        w,
        jnp.asarray(gamma, jnp.float32),
        jnp.asarray(beta, jnp.float32),
    )
    return hs[:, :b]


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9))
def gru_sequence(h0, xs, w, gamma, beta, is_first, init_rec, eps: float = 1e-6, interpret: bool = False, matmul_dtype=jnp.float32):
    """T-step LayerNorm-GRU with is_first reset gating, one Pallas kernel.

    h0: (B, H) f32 initial carry; xs: (T, B, X) projected inputs;
    w: (H+X, 3H); gamma/beta: (3H,); is_first: (T, B, 1);
    init_rec: (B, H) learned reset state. Returns hs (T, B, H) f32.
    """
    return _gru_sequence_fwd_pallas(
        h0, xs, w, gamma, beta, is_first, init_rec,
        eps=eps, interpret=interpret, matmul_dtype=matmul_dtype,
    )


def _fwd(h0, xs, w, gamma, beta, is_first, init_rec, eps, interpret, matmul_dtype):
    hs = _gru_sequence_fwd_pallas(
        h0, xs, w, gamma, beta, is_first, init_rec,
        eps=eps, interpret=interpret, matmul_dtype=matmul_dtype,
    )
    return hs, (h0, xs, w, gamma, beta, is_first, init_rec, hs)


def _bwd(eps, interpret, matmul_dtype, res, g):
    """Efficient BPTT: batched recompute from saved states; the reverse scan
    carries only dh and does one (B, 3H) @ (3H, H) matmul per step."""
    h0, xs, w, gamma, beta, is_first, init_rec, hs = res
    T, b, xdim = xs.shape
    hidden = h0.shape[-1]
    f32 = jnp.float32

    h_prev = jnp.concatenate([h0[None].astype(f32), hs[:-1]], 0)  # (T, B, H)
    hg = (1.0 - is_first) * h_prev + is_first * init_rec.astype(f32)

    # ---- batched recompute of every step's pre-LN activations and gates
    inp = jnp.concatenate([hg.astype(matmul_dtype), xs.astype(matmul_dtype)], -1)
    z = (inp @ w.astype(matmul_dtype)).astype(f32)  # (T, B, 3H)
    mu = z.mean(-1, keepdims=True)
    var = jnp.maximum((z * z).mean(-1, keepdims=True) - mu * mu, 0.0)
    inv = jax.lax.rsqrt(var + eps)
    zhat = (z - mu) * inv
    parts = zhat * gamma + beta
    p1, p2, p3 = jnp.split(parts, 3, -1)
    reset = jax.nn.sigmoid(p1)
    cand = jnp.tanh(reset * p2)
    update = jax.nn.sigmoid(p3 - 1.0)

    n3 = 3 * hidden
    w_h = w[:hidden].astype(f32)  # (H, 3H)

    def back_step(dh, inp_t):
        g_t, hg_t, cand_t, update_t, reset_t, p2_t, zhat_t, inv_t, first_t = inp_t
        dh_tot = dh + g_t
        du = (cand_t - hg_t) * dh_tot
        dcand = update_t * dh_tot
        dhg = (1.0 - update_t) * dh_tot
        dp3 = du * update_t * (1.0 - update_t)
        dtanh = dcand * (1.0 - cand_t * cand_t)
        dp2 = dtanh * reset_t
        dreset = dtanh * p2_t
        dp1 = dreset * reset_t * (1.0 - reset_t)
        dparts = jnp.concatenate([dp1, dp2, dp3], -1)  # (B, 3H)
        # LayerNorm backward (per row over 3H; stats are saved, not carried)
        dzhat = dparts * gamma
        dz = inv_t * (
            dzhat
            - dzhat.mean(-1, keepdims=True)
            - zhat_t * (dzhat * zhat_t).mean(-1, keepdims=True)
        )
        # into the carry: through the matmul's h-side AND the convex update
        dhg = dhg + dz @ w_h.T
        dh_prev = (1.0 - first_t) * dhg
        return dh_prev, (dz, dparts, dhg)

    seq = (g.astype(f32), hg, cand, update, reset, p2, zhat, inv, is_first.astype(f32))
    dh0, (dzs, dpartss, dhgs) = jax.lax.scan(
        back_step, jnp.zeros_like(h0, f32), seq, reverse=True
    )

    # ---- everything else batches over (T*B): ONE contraction each
    inp2 = jnp.concatenate([hg, xs.astype(f32)], -1).reshape(T * b, hidden + xdim)
    dz2 = dzs.reshape(T * b, n3)
    dw = (inp2.T @ dz2).astype(w.dtype)  # (H+X, 3H)
    dxs = (dz2 @ w[hidden:].astype(f32).T).reshape(T, b, xdim).astype(xs.dtype)
    dgamma = (dpartss.reshape(T * b, n3) * zhat.reshape(T * b, n3)).sum(0)
    dbeta = dpartss.reshape(T * b, n3).sum(0)
    dinit = (is_first * dhgs).sum(0).astype(init_rec.dtype)  # (B, H)
    dfirst = ((init_rec.astype(f32) - h_prev) * dhgs).sum(-1, keepdims=True)
    return (
        dh0.astype(h0.dtype),
        dxs,
        dw,
        dgamma.astype(gamma.dtype),
        dbeta.astype(beta.dtype),
        dfirst.astype(is_first.dtype),
        dinit,
    )


gru_sequence.defvjp(_fwd, _bwd)
