"""A2C losses in jax (reference sheeprl/algos/a2c/loss.py:1-40)."""

from __future__ import annotations

import jax

from sheeprl_tpu.algos.ppo.loss import _reduce


def policy_loss(logprobs: jax.Array, advantages: jax.Array, reduction: str = "mean") -> jax.Array:
    """Vanilla policy-gradient objective (no ratio clipping)."""
    return _reduce(-(logprobs * advantages), reduction)


def value_loss(values: jax.Array, returns: jax.Array, reduction: str = "mean") -> jax.Array:
    return _reduce((values - returns) ** 2, reduction)
