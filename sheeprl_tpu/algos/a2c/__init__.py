from sheeprl_tpu.algos.a2c import a2c, evaluate  # noqa: F401  (registry side-effect)
