"""A2C evaluation entrypoint (reference sheeprl/algos/a2c/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.ppo.evaluate import evaluate_ppo
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="a2c")
def evaluate_a2c(runtime, cfg: Dict[str, Any], state: Dict[str, Any]):
    evaluate_ppo(runtime, cfg, state)
