"""A2C reuses the PPO agent (reference sheeprl/algos/a2c/utils.py:10 —
a2c/agent.py is empty and imports from sheeprl.algos.ppo.agent)."""

from sheeprl_tpu.algos.ppo.agent import (  # noqa: F401
    PPOAgentModule,
    PPOPlayer,
    build_agent,
    evaluate_actions,
    get_values,
    sample_actions,
)
