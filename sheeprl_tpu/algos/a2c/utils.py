"""A2C helpers (reference sheeprl/algos/a2c/utils.py)."""

from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {"Rewards/rew_avg", "Game/ep_len_avg", "Loss/value_loss", "Loss/policy_loss"}
MODELS_TO_REGISTER = {"agent"}
