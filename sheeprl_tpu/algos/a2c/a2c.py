"""A2C — TPU-native main loop (reference sheeprl/algos/a2c/a2c.py:26,118).

Same rollout scaffold as PPO; the update differs: a single optimizer step
per iteration with gradients accumulated over minibatches (the reference's
``no_backward_sync`` + deferred ``optimizer.step``). In jax that's a
``lax.scan`` summing grads over minibatch chunks, then one ``tx.update`` —
the whole thing one jitted function."""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions, get_values, PPOPlayer
from sheeprl_tpu.algos.ppo.ppo import _set_lr, build_ppo_optimizer
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import flight, setup_observability, trace_scope
from sheeprl_tpu.parallel.pipeline import OnPolicyCollector, PipelinedCollector, detach_copy, resolve_overlap_setting
from sheeprl_tpu.resilience import CheckpointManager
from sheeprl_tpu.resilience.sentinel import guard_update, restore_like
from sheeprl_tpu.utils.callback import load_checkpoint
from sheeprl_tpu.utils.env import make_train_envs, resolve_env_backend
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import (
    MetricFetchGate,
    device_get_metrics,
    gae,
    normalize_tensor,
    polynomial_decay,
    save_configs,
)
from sheeprl_tpu.optim import restore_opt_states
from sheeprl_tpu.utils.jax_compat import shard_map


def make_update_fn(runtime, module, tx, cfg: Dict[str, Any], obs_keys: Sequence[str]):
    mb_size = int(cfg.algo.per_rank_batch_size) * runtime.world_size
    gamma = float(cfg.algo.gamma)
    gae_lambda = float(cfg.algo.gae_lambda)
    vf_coef = float(cfg.algo.vf_coef)
    reduction = str(cfg.algo.loss_reduction)
    normalize_adv = bool(cfg.algo.get("normalize_advantages", False))
    ent_coef = float(cfg.algo.ent_coef)

    world_size = int(runtime.world_size)

    def _core(params, opt_state, data, next_obs, key, local_mb, pmean_axis):
        """GAE + shuffled minibatch gradient ACCUMULATION + one update.

        Runs either on the whole rollout (single device) or, under
        shard_map, on a rank's env columns with ``local_mb`` rows per
        minibatch and a ``pmean`` over ``pmean_axis`` before the single
        optimizer step — the accumulate-then-step structure means the
        rank-local decomposition is EXACTLY the global computation
        (sum over minibatches of per-minibatch means)."""
        next_values = get_values(
            module, params, normalize_obs({k: next_obs[k].astype(jnp.float32) for k in obs_keys}, (), obs_keys)
        )
        returns, advantages = gae(
            data["rewards"], data["values"], data["dones"], next_values, gamma, gae_lambda
        )
        data = {**data, "returns": returns, "advantages": advantages}
        n_total = data["rewards"].shape[0] * data["rewards"].shape[1]
        flat = {k: v.reshape(n_total, *v.shape[2:]) for k, v in data.items()}
        num_minibatches = max(1, -(-n_total // local_mb))
        n_used = num_minibatches * local_mb

        def loss_fn(p, mb):
            obs = normalize_obs({k: mb[k].astype(jnp.float32) for k in obs_keys}, (), obs_keys)
            logprobs, entropy, new_values = evaluate_actions(module, p, obs, mb["actions"])
            adv = normalize_tensor(mb["advantages"]) if normalize_adv else mb["advantages"]
            pg = policy_loss(logprobs, adv, reduction)
            vl = value_loss(new_values, mb["returns"], reduction)
            total = pg + vf_coef * vl - ent_coef * entropy.mean()
            return total, jnp.stack([pg, vl])

        grad_fn = jax.grad(loss_fn, has_aux=True)

        perm = jax.random.permutation(key, n_total)
        if n_used > n_total:  # pad by wrapping as many times as needed
            perm = jnp.tile(perm, -(-n_used // n_total))[:n_used]
        shuffled = jax.tree_util.tree_map(
            lambda x: x[perm].reshape(num_minibatches, local_mb, *x.shape[1:]), flat
        )

        def mb_step(acc, mb):
            grads, losses = grad_fn(params, mb)
            acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            return acc, losses

        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        grads, losses = jax.lax.scan(mb_step, zero_grads, shuffled)
        if pmean_axis is not None:
            grads = jax.lax.pmean(grads, pmean_axis)
            losses = jax.lax.pmean(losses, pmean_axis)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        mean_losses = losses.mean(0)
        return params, opt_state, {
            "Loss/policy_loss": mean_losses[0],
            "Loss/value_loss": mean_losses[1],
            # accumulated-gradient global norm: telemetry + the training
            # sentinel's z-score monitor
            "Grads/agent": optax.global_norm(grads),
        }

    def update(params, opt_state, data, next_obs, key, lr):
        opt_state = _set_lr(opt_state, lr)
        if runtime.ddp_gate(data["rewards"].shape[1], "A2C"):
            # rank-local DDP core: the epoch-shuffle gather cannot stay
            # sharded under GSPMD (it would replicate the whole update on
            # every device — see ppo.py's _update_shard_map).  Specs and
            # the gradient pmean cover BOTH mesh axes (parallel/sharding):
            # every device is a batch shard regardless of the (d, f) split,
            # and the reduction lowers to explicit jax.lax collectives.
            from jax.sharding import PartitionSpec as SMP

            from sheeprl_tpu.parallel.sharding import BATCH_AXES

            data_specs = jax.tree_util.tree_map(lambda _: SMP(None, BATCH_AXES), data)
            obs_specs = jax.tree_util.tree_map(lambda _: SMP(BATCH_AXES), next_obs)

            def body(params, opt_state, data, next_obs, key):
                rank_key = jax.random.fold_in(key, runtime.layout.flat_rank())
                return _core(
                    params, opt_state, data, next_obs, rank_key,
                    mb_size // world_size, BATCH_AXES,
                )

            return shard_map(
                body,
                mesh=runtime.mesh,
                in_specs=(SMP(), SMP(), data_specs, obs_specs, SMP()),
                out_specs=(SMP(), SMP(), SMP()),
                check_vma=False,
            )(params, opt_state, data, next_obs, key)
        return _core(params, opt_state, data, next_obs, key, mb_size, None)

    # training health sentinel hook (resilience/sentinel.py)
    return guard_update(runtime, update, cfg, n_state=2, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    if len(cfg.algo.cnn_keys.encoder) > 0:
        raise ValueError("A2C supports only vector observations (mlp keys)")

    world_size = runtime.world_size
    runtime.seed_everything(cfg.seed)

    state = load_checkpoint(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    runtime.print(f"Log dir: {log_dir}")
    if logger:
        logger.log_hyperparams(cfg)

    import gymnasium as gym

    total_envs = cfg.env.num_envs * world_size
    # env backend dispatch (howto/jax-envs.md): host = the gymnasium
    # vector stack (bit-exact pre-backend behavior), jax = device-resident
    # envs + the fused collect path below
    env_backend = resolve_env_backend(cfg)
    envs = make_train_envs(cfg, runtime, log_dir)
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    obs_keys = list(cfg.algo.mlp_keys.encoder)

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    module, params = build_agent(
        runtime, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    params = runtime.replicate(runtime.to_param_dtype(params))
    tx = build_ppo_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm, runtime.precision)
    opt_state = (
        runtime.replicate(tx.init(params))
        if state is None
        else restore_opt_states(state["optimizer"], params, runtime.precision)
    )
    player = PPOPlayer(
        module,
        params,
        lambda obs: prepare_obs(obs, num_envs=total_envs),
        device=runtime.player_device(params),
    )

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(dict(cfg.metric.aggregator))
    observability = setup_observability(runtime, cfg, log_dir, logger=logger)

    rb = ReplayBuffer(
        cfg.buffer.size,
        total_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        obs_keys=obs_keys,
    )

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(cfg.env.num_envs * cfg.algo.rollout_steps * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size

    ckpt_mgr = CheckpointManager(
        runtime, cfg, log_dir, observability=observability, last_checkpoint=last_checkpoint
    )
    update_fn = make_update_fn(runtime, module, tx, cfg, obs_keys)
    health = update_fn.health.bind(ckpt_mgr=ckpt_mgr, select=("agent", "optimizer"))
    if health.enabled:
        observability.health_stats = health.stats
    lr0 = float(cfg.algo.optimizer.get("learning_rate", 1e-3))
    current_lr = lr0

    # collect/train pipeline: overlap_collect=True steps iteration t+1's
    # envs on a background thread while iteration t trains (params
    # staleness <= 1); False keeps the serial pre-pipeline order bit-exact;
    # "auto" turns it on only where a spare host core exists for the
    # collector thread (single-core hosts stay serial)
    overlap = resolve_overlap_setting(cfg)  # always off on the jax backend
    if overlap:
        # the player's device_put is a no-op on a same-device tree, so its
        # initial weights alias the buffers update 1 donates — detach them
        # before the collector thread starts acting on them
        player.params = detach_copy(params)
    if env_backend == "jax":
        # fused collect (envs/jax/collect.py): policy + env + append as
        # one lax.scan per rollout; the payload is born on device
        from sheeprl_tpu.envs.jax.collect import FusedOnPolicyCollector

        collector = FusedOnPolicyCollector(
            envs=envs,
            module=module,
            params=params,
            cfg=cfg,
            runtime=runtime,
            obs_keys=obs_keys,
            total_envs=total_envs,
            world_size=world_size,
            aggregator=aggregator,
            policy_step=policy_step,
        )
        observability.jaxenv_stats = collector.stats
        adopt_params_fn = collector.adopt

        def _pack(payload):
            # already device arrays; only the mesh layout is (re)applied
            with trace_scope("host_to_device"):
                payload.data = runtime.shard_batch(dict(payload.data), axis=1)
                payload.next_obs = runtime.shard_batch(dict(payload.next_obs), axis=0)

    else:
        collector = OnPolicyCollector(
            envs=envs,
            player=player,
            rb=rb,
            cfg=cfg,
            runtime=runtime,
            obs_keys=obs_keys,
            total_envs=total_envs,
            world_size=world_size,
            aggregator=aggregator,
            policy_step=policy_step,
        )
        adopt_params_fn = lambda p: setattr(player, "params", p)

        def _pack(payload):
            # env-axis sharding: each mesh device receives only its columns; on
            # the overlapped path this runs on the collector thread, so the
            # host->device upload of rollout t+1 overlaps train step t
            local_data = {k: v.astype(jnp.float32) for k, v in payload.data.items()}
            # np.array (copy), not asarray: SyncVectorEnv mutates its obs
            # buffer in place and CPU device_put zero-copy aliases host memory
            host_next_obs = {k: np.array(payload.next_obs[k]) for k in obs_keys}
            # the upload sources must outlive the update that reads them —
            # device_put's zero-copy alias does not keep them alive itself
            payload.host_refs.append((local_data, host_next_obs))
            with trace_scope("host_to_device"):
                payload.data = runtime.shard_batch(local_data, axis=1)
                payload.next_obs = runtime.shard_batch(host_next_obs, axis=0)

    pipeline = PipelinedCollector(
        runtime,
        collector.collect,
        _pack,
        start_iter=start_iter,
        total_iters=total_iters,
        overlap=overlap,
        seed=cfg.seed,
        adopt_params_fn=adopt_params_fn,
    )
    metric_fetch_gate = MetricFetchGate(cfg.metric.get("fetch_every", 1))

    for iter_num, payload in pipeline:
        observability.on_iteration(policy_step)
        payload.apply_events(aggregator, runtime, cfg.metric.log_level)
        policy_step = payload.policy_step_end

        with timer("Time/train_time", SumMetric, sync_on_compute=cfg.metric.sync_on_compute), flight.span(
            "train_step", round=iter_num
        ):
            params, opt_state, train_metrics = update_fn(
                params, opt_state, payload.data, payload.next_obs, runtime.next_key(), jnp.float32(current_lr)
            )
        pipeline.publish(iter_num, params)
        train_step += world_size

        rolled = health.tick()
        if rolled is not None:
            params = restore_like(params, rolled["agent"])
            opt_state = restore_like(opt_state, rolled["optimizer"])

        if aggregator and not aggregator.disabled and metric_fetch_gate():
            with trace_scope("block_until_ready"):
                fetched_metrics = device_get_metrics(train_metrics)
            for k, v in fetched_metrics.items():
                aggregator.update(k, v)

        if cfg.metric.log_level > 0 and logger:
            logger.log_metrics({"Info/learning_rate": current_lr}, policy_step)
            if policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters:
                observability.on_log(policy_step, train_step)
                if aggregator and not aggregator.disabled:
                    logger.log_metrics(aggregator.compute(), policy_step)
                    aggregator.reset()
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                            policy_step,
                        )
                    if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / timer_metrics["Time/env_interaction_time"]
                            },
                            policy_step,
                        )
                    timer.reset()
                last_log = policy_step
                last_train = train_step

        if cfg.algo.anneal_lr:
            current_lr = polynomial_decay(iter_num, initial=lr0, final=0.0, max_decay_steps=total_iters, power=1.0)

        def _ckpt_state():
            state = {
                "agent": params,
                "optimizer": opt_state,
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": ckpt_mgr.last_checkpoint,
            }
            # opt-in on-policy buffer persistence (buffer.checkpoint_on_policy):
            # the rollout is cheap to regenerate, but the resilience benchmark
            # needs a replay-buffer-bearing state on this loop
            if cfg.buffer.get("checkpoint_on_policy", False):
                state["rb"] = rb
            return state

        ckpt_mgr.maybe_checkpoint(
            policy_step=policy_step, is_last=iter_num == total_iters, state_fn=_ckpt_state
        )
        if ckpt_mgr.preempted:
            runtime.print(f"Preemption signal: emergency checkpoint written, stopping at iter {iter_num}")
            break

    pipeline.close()  # before envs.close(): the collector may be mid-step
    player.params = params  # the test episode runs on the final weights
    ckpt_mgr.close()
    envs.close()
    observability.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_rew = test(player, runtime, cfg, log_dir)
        if logger:
            logger.log_metrics({"Test/cumulative_reward": test_rew}, policy_step)
    if logger:
        logger.finalize()
