from sheeprl_tpu.algos.droq import droq, evaluate  # noqa: F401  (registry side-effect)
