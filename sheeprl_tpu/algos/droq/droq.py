"""DroQ — TPU-native main loop (reference sheeprl/algos/droq/droq.py
train:31, main:141).

Differences from SAC faithfully kept: high replay ratio (20), dropout+
LayerNorm critics, per-minibatch critic updates with EMA after every
critic step, a SEPARATE batch for the single actor/alpha update, and the
actor objective using the ensemble MEAN q-value (droq.py:124) instead of
the min. The G critic minibatches run as one ``lax.scan``."""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.droq.agent import build_agent, droq_ensemble_apply
from sheeprl_tpu.algos.sac.agent import SACPlayer, actor_action_and_log_prob
from sheeprl_tpu.algos.sac.loss import (
    critic_loss,
    critic_loss_weighted,
    entropy_loss,
    policy_loss,
    td_error_abs,
)
from sheeprl_tpu.algos.sac.sac import _make_optimizer
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_buffer import maybe_create_for_transitions
from sheeprl_tpu.obs import setup_observability, trace_scope
from sheeprl_tpu.replay import per_beta_schedule, rate_limiter_from_cfg
from sheeprl_tpu.resilience import CheckpointManager
from sheeprl_tpu.resilience.sentinel import guard_update, restore_like
from sheeprl_tpu.utils.callback import load_checkpoint, restore_buffer
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import MetricFetchGate, device_get_metrics, Ratio, save_configs
from sheeprl_tpu.optim import restore_opt_states


def make_train_fn(
    runtime, actor, critic, txs, cfg: Dict[str, Any], target_entropy: float, prioritized: bool = False
):
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    num_critics = int(cfg.algo.critic.n)
    actor_tx, critic_tx, alpha_tx = txs

    def _core(params, opt_states, critic_data, actor_data, key, dp_axes):
        """``prioritized`` consumes ``critic_data["is_weights"]`` and
        returns per-minibatch |TD| for the priority updates (the actor
        batch stays unweighted — see loss.critic_loss_weighted); the
        False path traces exactly the pre-PER computation.  ``dp_axes``
        is the shard_map DDP core: batch rows are device-local and every
        component gradient carries an explicit ``pmean`` (see sac.py)."""
        if dp_axes is not None:
            # per-shard noise stream (dropout masks, action sampling)
            key = jax.random.fold_in(key, runtime.layout.flat_rank())
        alpha = jnp.exp(params["log_alpha"])

        # ---------------- G critic minibatches (Algorithm 2, lines 5-9)
        def critic_step(carry, inp):
            cparams, ctarget, copt = carry
            batch, k = inp
            k_next, k_drop = jax.random.split(k)
            next_actions, next_logp = actor_action_and_log_prob(
                actor, params["actor"], batch["next_observations"], k_next
            )
            qf_next = droq_ensemble_apply(
                critic, ctarget, batch["next_observations"], next_actions
            )
            min_qf_next = qf_next.min(-1, keepdims=True) - alpha * next_logp
            target = jax.lax.stop_gradient(
                batch["rewards"] + (1 - batch["terminated"]) * gamma * min_qf_next
            )

            if prioritized:

                def qf_loss_fn_w(cp):
                    q = droq_ensemble_apply(critic, cp, batch["observations"], batch["actions"], k_drop)
                    return (
                        critic_loss_weighted(q, target, num_critics, batch["is_weights"]),
                        td_error_abs(q, target),
                    )

                (qf_loss, td_abs), grads = jax.value_and_grad(qf_loss_fn_w, has_aux=True)(cparams)
            else:

                def qf_loss_fn(cp):
                    q = droq_ensemble_apply(critic, cp, batch["observations"], batch["actions"], k_drop)
                    return critic_loss(q, target, num_critics)

                qf_loss, grads = jax.value_and_grad(qf_loss_fn)(cparams)
                td_abs = None
            if dp_axes is not None:
                # explicit DDP gradient all-reduce (NCCL-equivalent psum)
                grads = jax.lax.pmean(grads, dp_axes)
                qf_loss = jax.lax.pmean(qf_loss, dp_axes)
            updates, copt = critic_tx.update(grads, copt, cparams)
            cparams = optax.apply_updates(cparams, updates)
            ctarget = optax.incremental_update(cparams, ctarget, tau)  # EMA per step
            return (cparams, ctarget, copt), ((qf_loss, td_abs) if prioritized else qf_loss)

        g = critic_data["rewards"].shape[0]
        keys = jax.random.split(key, g + 3)
        (new_critic, new_target, new_critic_opt), critic_ys = jax.lax.scan(
            critic_step,
            (params["critic"], params["target_critic"], opt_states["critic"]),
            (critic_data, keys[:g]),
        )
        qf_losses, td_abs = critic_ys if prioritized else (critic_ys, None)

        # ---------------- single actor + alpha update on a separate batch
        def actor_loss_fn(ap):
            actions, logp = actor_action_and_log_prob(actor, ap, actor_data["observations"], keys[g])
            q = droq_ensemble_apply(critic, new_critic, actor_data["observations"], actions, keys[g + 1])
            return policy_loss(alpha, logp, q.mean(-1, keepdims=True)), logp

        (actor_loss, logp), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        if dp_axes is not None:
            actor_grads = jax.lax.pmean(actor_grads, dp_axes)
            actor_loss = jax.lax.pmean(actor_loss, dp_axes)
        updates, new_actor_opt = actor_tx.update(actor_grads, opt_states["actor"], params["actor"])
        new_actor = optax.apply_updates(params["actor"], updates)

        alpha_loss, alpha_grad = jax.value_and_grad(lambda la: entropy_loss(la, logp, target_entropy))(
            params["log_alpha"]
        )
        if dp_axes is not None:
            alpha_grad = jax.lax.pmean(alpha_grad, dp_axes)
            alpha_loss = jax.lax.pmean(alpha_loss, dp_axes)
        updates, new_alpha_opt = alpha_tx.update(alpha_grad, opt_states["alpha"], params["log_alpha"])
        new_log_alpha = optax.apply_updates(params["log_alpha"], updates)

        new_params = {
            "actor": new_actor,
            "critic": new_critic,
            "target_critic": new_target,
            "log_alpha": new_log_alpha,
        }
        new_opts = {"actor": new_actor_opt, "critic": new_critic_opt, "alpha": new_alpha_opt}
        metrics = {
            "Loss/value_loss": qf_losses.mean(),
            "Loss/policy_loss": actor_loss,
            "Loss/alpha_loss": alpha_loss,
            # actor+alpha grad norm (critic grads live inside the scan;
            # its health is covered by the value loss + update norm)
            "Grads/agent": optax.global_norm((actor_grads, alpha_grad)),
        }
        if prioritized:
            # (G, B) |TD| rides back for update_priorities — stays on device
            return new_params, new_opts, metrics, td_abs
        return new_params, new_opts, metrics

    def train(params, opt_states, critic_data, actor_data, key):
        if runtime.ddp_gate(critic_data["rewards"].shape[1], "DroQ"):
            # explicit DDP core over the flattened batch axes (see sac.py)
            from jax.sharding import PartitionSpec as SMP

            from sheeprl_tpu.parallel.sharding import BATCH_AXES
            from sheeprl_tpu.utils.jax_compat import shard_map

            critic_specs = jax.tree_util.tree_map(lambda _: SMP(None, BATCH_AXES), critic_data)
            actor_specs = jax.tree_util.tree_map(lambda _: SMP(BATCH_AXES), actor_data)
            td_spec = (SMP(None, BATCH_AXES),) if prioritized else ()

            def body(params, opt_states, critic_data, actor_data, key):
                return _core(params, opt_states, critic_data, actor_data, key, BATCH_AXES)

            return shard_map(
                body,
                mesh=runtime.mesh,
                in_specs=(SMP(), SMP(), critic_specs, actor_specs, SMP()),
                out_specs=(SMP(), SMP(), SMP()) + td_spec,
                check_vma=False,
            )(params, opt_states, critic_data, actor_data, key)
        return _core(params, opt_states, critic_data, actor_data, key, None)

    # training health sentinel hook (resilience/sentinel.py)
    return guard_update(runtime, train, cfg, n_state=2, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    import gymnasium as gym
    from gymnasium.vector import AsyncVectorEnv, AutoresetMode, SyncVectorEnv

    world_size = runtime.world_size
    runtime.seed_everything(cfg.seed)
    state = load_checkpoint(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    runtime.print(f"Log dir: {log_dir}")
    observability = setup_observability(runtime, cfg, log_dir, logger=logger)
    if logger:
        logger.log_hyperparams(cfg)

    total_envs = cfg.env.num_envs * world_size
    thunks = [
        make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
        for i in range(total_envs)
    ]
    envs = (
        SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
        if cfg.env.sync_env
        else AsyncVectorEnv(thunks, context="spawn", autoreset_mode=AutoresetMode.SAME_STEP)
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the DroQ agent")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    actor, critic, params, target_entropy = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    params = runtime.replicate(
        runtime.to_param_dtype(params, exclude=("target_critic", "log_alpha"))
    )
    actor_tx = _make_optimizer(cfg.algo.actor.optimizer, runtime.precision)
    critic_tx = _make_optimizer(cfg.algo.critic.optimizer, runtime.precision)
    alpha_tx = _make_optimizer(cfg.algo.alpha.optimizer, runtime.precision)
    if state is not None:
        opt_states = restore_opt_states(
            state["opt_states"], params, runtime.precision, key_map={"alpha": "log_alpha"}
        )
    else:
        opt_states = runtime.replicate(
            {
                "actor": actor_tx.init(params["actor"]),
                "critic": critic_tx.init(params["critic"]),
                "alpha": alpha_tx.init(params["log_alpha"]),
            }
        )

    player = SACPlayer(
        actor,
        params["actor"],
        lambda obs: prepare_obs(obs, mlp_keys=mlp_keys, num_envs=total_envs),
        device=runtime.player_device(params["actor"]),
    )

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(dict(cfg.metric.aggregator))

    buffer_size = cfg.buffer.size // total_envs if not cfg.dry_run else 1
    rb = ReplayBuffer(
        max(buffer_size, 1),
        total_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        obs_keys=("observations",),
    )
    if state and cfg.buffer.checkpoint:
        rb = restore_buffer(state["rb"], memmap=cfg.buffer.memmap)
    # HBM-resident replay window + on-device sampling (data/device_buffer.py)
    device_cache = maybe_create_for_transitions(
        cfg, runtime, rb, state if state and cfg.buffer.checkpoint else None
    )
    # prioritized replay + samples-per-insert rate control (see sac.py —
    # DroQ shares the same critic-side PER semantics)
    prioritized = device_cache is not None and device_cache.prioritized
    beta_fn = per_beta_schedule(
        cfg.buffer.get("per_beta", 0.4),
        cfg.buffer.get("per_beta_end", 1.0),
        int(cfg.algo.total_steps),
    )
    limiter = rate_limiter_from_cfg(cfg, default_min_size=max(int(cfg.algo.learning_starts), 1))
    if limiter is not None and state is not None and state.get("rate_limiter"):
        limiter.load_state_dict(state["rate_limiter"])

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(total_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])

    ckpt_mgr = CheckpointManager(
        runtime, cfg, log_dir, observability=observability, last_checkpoint=last_checkpoint
    )
    train_fn = make_train_fn(
        runtime, actor, critic, (actor_tx, critic_tx, alpha_tx), cfg, target_entropy,
        prioritized=prioritized,
    )
    health = train_fn.health.bind(ckpt_mgr=ckpt_mgr, select=("agent", "opt_states"))
    if health.enabled:
        observability.health_stats = health.stats

    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]
    cumulative_per_rank_gradient_steps = 0

    metric_fetch_gate = MetricFetchGate(cfg.metric.get("fetch_every", 1))
    for iter_num in range(start_iter, total_iters + 1):
        observability.on_iteration(policy_step)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time", SumMetric, sync_on_compute=False):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                actions = np.asarray(player.get_actions(obs, runtime.next_key()))
            next_obs, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
            rewards = rewards.reshape(total_envs, -1)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(infos["final_info"]["_episode"])[0]:
                    if aggregator and not aggregator.disabled:
                        aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                        aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={float(ep['r'][i])}")

        real_next_obs = {k: np.array(v) for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx in np.nonzero(infos["_final_obs"])[0]:
                for k, v in infos["final_obs"][idx].items():
                    real_next_obs[k][idx] = v
        flat_next_obs = np.concatenate([real_next_obs[k] for k in mlp_keys], axis=-1).astype(np.float32)

        step_data["terminated"] = terminated.reshape(1, total_envs, -1).astype(np.uint8)
        step_data["truncated"] = truncated.reshape(1, total_envs, -1).astype(np.uint8)
        step_data["actions"] = actions.reshape(1, total_envs, -1).astype(np.float32)
        step_data["observations"] = np.concatenate([obs[k] for k in mlp_keys], axis=-1).astype(np.float32)[
            np.newaxis
        ]
        if not cfg.buffer.sample_next_obs:
            step_data["next_observations"] = flat_next_obs[np.newaxis]
        step_data["rewards"] = rewards[np.newaxis].astype(np.float32)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        if limiter is not None:
            limiter.insert(total_envs)
        if device_cache is not None:
            device_cache.add(step_data)
        obs = next_obs

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(
                (policy_step - prefill_steps + policy_steps_per_iter) / world_size
            )
            bs = cfg.algo.per_rank_batch_size * world_size
            if limiter is not None and per_rank_gradient_steps > 0:
                # sample-side throttle: clip the granted critic minibatches
                # to the SPI budget (DroQ's high replay ratio is exactly the
                # regime where training outruns collection)
                allowed = limiter.sample_allowance(per_rank_gradient_steps * bs) // bs
                if allowed < per_rank_gradient_steps:
                    limiter.sample_stalls += 1
                per_rank_gradient_steps = allowed
            if per_rank_gradient_steps > 0:
                g = per_rank_gradient_steps
                sample_idx = None
                if device_cache is not None and device_cache.can_sample_transitions(
                    cfg.buffer.sample_next_obs
                ):
                    # on-device gathers + casts; nothing crosses the link
                    if prioritized:
                        sampled, sample_idx = device_cache.sample_transitions_per(
                            g, bs, runtime.next_key(),
                            beta_fn(policy_step),
                            sample_next_obs=cfg.buffer.sample_next_obs,
                            obs_keys=("observations",),
                        )
                        critic_data = {k: v.astype(jnp.float32) for k, v in sampled.items()}
                    else:
                        critic_data = {
                            k: v.astype(jnp.float32)
                            for k, v in device_cache.sample_transitions(
                                g, bs, runtime.next_key(),
                                sample_next_obs=cfg.buffer.sample_next_obs,
                                obs_keys=("observations",),
                            ).items()
                        }
                    actor_data = {
                        k: v[0].astype(jnp.float32)
                        for k, v in device_cache.sample_transitions(
                            1, bs, runtime.next_key(),
                            sample_next_obs=cfg.buffer.sample_next_obs,
                            obs_keys=("observations",),
                        ).items()
                    }
                else:
                    critic_sample = rb.sample(batch_size=g * bs, sample_next_obs=cfg.buffer.sample_next_obs)
                    critic_data = {
                        k: np.asarray(v, np.float32).reshape(g, bs, *v.shape[2:])
                        for k, v in critic_sample.items()
                    }
                    if prioritized:
                        # the cache bailed at runtime: train unweighted on
                        # the uniform host sample, no priorities to update
                        critic_data["is_weights"] = np.ones((g, bs, 1), np.float32)
                    actor_sample = rb.sample(batch_size=bs, sample_next_obs=cfg.buffer.sample_next_obs)
                    actor_data = {
                        k: np.asarray(v, np.float32).reshape(bs, *v.shape[2:])
                        for k, v in actor_sample.items()
                    }
                    # shard the batch axes over the mesh so each device trains
                    # on its own rows (GSPMD inserts the grad psums)
                    critic_data = runtime.shard_batch(critic_data, axis=1)
                    actor_data = runtime.shard_batch(actor_data, axis=0)
                if limiter is not None:
                    limiter.sample(g * bs)
                with timer("Time/train_time", SumMetric, sync_on_compute=cfg.metric.sync_on_compute):
                    if prioritized:
                        params, opt_states, train_metrics, td_abs = train_fn(
                            params, opt_states, critic_data, actor_data, runtime.next_key()
                        )
                    else:
                        params, opt_states, train_metrics = train_fn(
                            params, opt_states, critic_data, actor_data, runtime.next_key()
                        )
                if sample_idx is not None:
                    device_cache.update_priorities(sample_idx, td_abs)
                rolled = health.tick()
                if rolled is not None:
                    params = restore_like(params, rolled["agent"])
                    opt_states = restore_like(opt_states, rolled["opt_states"])
                player.params = params["actor"]
                cumulative_per_rank_gradient_steps += g
                train_step += world_size
                if aggregator and not aggregator.disabled and metric_fetch_gate():
                    with trace_scope("block_until_ready"):
                        fetched_metrics = device_get_metrics(train_metrics)
                    for k, v in fetched_metrics.items():
                        aggregator.update(k, v)

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        ):
            replay_extra = None
            if prioritized or limiter is not None:
                replay_rec: Dict[str, Any] = {}
                if prioritized:
                    replay_rec["prioritized"] = True
                    replay_rec["beta"] = round(beta_fn(policy_step), 4)
                if limiter is not None:
                    replay_rec["limiter"] = limiter.stats()
                replay_extra = {"replay": replay_rec}
            observability.on_log(policy_step, train_step, extra=replay_extra)
            if logger:
                if aggregator and not aggregator.disabled:
                    logger.log_metrics(aggregator.compute(), policy_step)
                    aggregator.reset()
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * world_size / policy_step},
                    policy_step,
                )
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                            policy_step,
                        )
                    if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / timer_metrics["Time/env_interaction_time"]
                            },
                            policy_step,
                        )
                    timer.reset()
            last_log = policy_step
            last_train = train_step

        def _ckpt_state():
            ckpt_state = {
                "agent": params,
                "opt_states": opt_states,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": ckpt_mgr.last_checkpoint,
            }
            if cfg.buffer.checkpoint:
                ckpt_state["rb"] = rb
            if device_cache is not None and device_cache.prioritized:
                ckpt_state["replay_priority"] = device_cache.priority_state()
            if limiter is not None:
                ckpt_state["rate_limiter"] = limiter.state_dict()
            return ckpt_state

        ckpt_mgr.maybe_checkpoint(
            policy_step=policy_step, is_last=iter_num == total_iters, state_fn=_ckpt_state
        )
        if ckpt_mgr.preempted:
            runtime.print(
                f"Preemption signal: emergency checkpoint written, stopping at iter {iter_num}"
            )
            break

    ckpt_mgr.close()
    envs.close()
    observability.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_rew = test(player, runtime, cfg, log_dir)
        if logger:
            logger.log_metrics({"Test/cumulative_reward": test_rew}, policy_step)
    if logger:
        logger.finalize()
