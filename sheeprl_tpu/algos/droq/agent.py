"""DroQ agent (arXiv:2110.02034) — reference sheeprl/algos/droq/agent.py
(DROQCritic:20, DROQAgent:63).

Same functional layout as SAC (vmapped critic ensemble, EMA target pytree);
the critic adds Dropout + LayerNorm, so ensemble application threads a
dropout rng."""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor
from sheeprl_tpu.models.models import MLP


class DROQCritic(nn.Module):
    hidden_size: int = 256
    num_critics: int = 1
    dropout: float = 0.0

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array, deterministic: bool = True) -> jax.Array:
        x = jnp.concatenate([obs, action], -1)
        return MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=self.num_critics,
            activation="relu",
            layer_norm=True,
            dropout=self.dropout,
        )(x, deterministic=deterministic)


def droq_ensemble_init(critic: DROQCritic, n: int, key: jax.Array, obs: jax.Array, act: jax.Array):
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: critic.init({"params": k}, obs, act))(keys)


def droq_ensemble_apply(
    critic: DROQCritic,
    stacked_params: Any,
    obs: jax.Array,
    act: jax.Array,
    dropout_key: Optional[jax.Array] = None,
) -> jax.Array:
    """(B, n) q-values; dropout active iff a dropout_key is given."""
    if dropout_key is None:
        q = jax.vmap(lambda p: critic.apply(p, obs, act, deterministic=True))(stacked_params)
    else:
        n = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        keys = jax.random.split(dropout_key, n)
        q = jax.vmap(
            lambda p, k: critic.apply(p, obs, act, deterministic=False, rngs={"dropout": k})
        )(stacked_params, keys)
    return jnp.moveaxis(q.squeeze(-1), 0, -1)


def build_agent(
    runtime,
    cfg: Dict[str, Any],
    obs_space,
    action_space,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACActor, DROQCritic, Dict[str, Any], float]:
    act_dim = int(prod(action_space.shape))
    obs_dim = int(sum(prod(obs_space[k].shape) for k in cfg.algo.mlp_keys.encoder))
    actor = SACActor(
        hidden_size=int(cfg.algo.actor.hidden_size),
        action_dim=act_dim,
        action_low=np.asarray(action_space.low),
        action_high=np.asarray(action_space.high),
    )
    critic = DROQCritic(
        hidden_size=int(cfg.algo.critic.hidden_size),
        num_critics=1,
        dropout=float(cfg.algo.critic.dropout),
    )
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    else:
        dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
        dummy_act = jnp.zeros((1, act_dim), jnp.float32)
        params = {
            "actor": actor.init(runtime.next_key(), dummy_obs),
            "critic": droq_ensemble_init(
                critic, int(cfg.algo.critic.n), runtime.next_key(), dummy_obs, dummy_act
            ),
        }
        params["target_critic"] = jax.tree_util.tree_map(jnp.copy, params["critic"])
        params["log_alpha"] = jnp.log(jnp.asarray([float(cfg.algo.alpha.alpha)], jnp.float32))
    return actor, critic, params, -float(act_dim)
