"""DroQ helpers (reference sheeprl/algos/droq/utils.py)."""

from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
