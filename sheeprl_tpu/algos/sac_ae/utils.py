"""SAC-AE helpers (reference sheeprl/algos/sac_ae/utils.py):
preprocess_obs:68, AGGREGATOR_KEYS, prepare_obs, test."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.utils.env import make_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}
MODELS_TO_REGISTER = {"agent", "encoder", "decoder"}


def preprocess_obs(obs: jax.Array, key: jax.Array, bits: int = 8) -> jax.Array:
    """Quantize [0, 255] images to ``bits`` bits with uniform dequantization
    noise, centered (reference preprocess_obs:68, arXiv:1807.03039)."""
    bins = 2**bits
    if bits < 8:
        obs = jnp.floor(obs / 2 ** (8 - bits))
    obs = obs / bins
    obs = obs + jax.random.uniform(key, obs.shape) / bins
    return obs - 0.5


def prepare_obs(
    obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), num_envs: int = 1, **kwargs: Any
) -> Dict[str, np.ndarray]:
    """(num_envs, ...) float obs dict; images NHWC normalized to [0, 1]."""
    out = {}
    for k, v in obs.items():
        arr = np.asarray(v, dtype=np.float32)
        if k in cnn_keys:
            arr = arr.reshape(num_envs, *arr.shape[-3:]) / 255.0
        else:
            arr = arr.reshape(num_envs, -1)
        out[k] = arr
    return out


def test(
    player,
    runtime,
    cfg: Dict[str, Any],
    log_dir: str,
    test_name: str = "",
    greedy: bool = True,
    seed: Optional[int] = None,
) -> float:
    from sheeprl_tpu.algos.sac_ae.agent import SACAEPlayer

    player = SACAEPlayer(
        player.modules,
        player.params,
        lambda obs: prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=1),
    )
    seed = cfg.seed if seed is None else seed
    env = make_env(cfg, seed, 0, log_dir, "test" + (f"_{test_name}" if test_name else ""))()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=seed)[0]
    while not done:
        actions = player.get_actions(obs, runtime.next_key(), greedy=greedy)
        obs, reward, terminated, truncated, _ = env.step(
            np.asarray(actions).reshape(env.action_space.shape)
        )
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(reward)
    runtime.print("Test - Reward:", cumulative_rew)
    env.close()
    return cumulative_rew
