from sheeprl_tpu.algos.sac_ae import evaluate, sac_ae  # noqa: F401  (registry side-effect)
