"""SAC-AE evaluation entrypoint (reference
sheeprl/algos/sac_ae/evaluate.py)."""

from __future__ import annotations

from functools import partial

from typing import Any, Dict

import gymnasium as gym

from sheeprl_tpu.algos.sac_ae.agent import SACAEPlayer, build_agent
from sheeprl_tpu.algos.sac_ae.utils import prepare_obs, test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.eval_protocol import run_eval_protocol
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="sac_ae")
def evaluate_sac_ae(runtime, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    runtime.print(f"Log dir: {log_dir}")
    runtime.seed_everything(cfg.seed)

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    env.close()

    modules, params, _ = build_agent(runtime, cfg, observation_space, action_space, state["agent"])
    player = SACAEPlayer(
        modules,
        {"encoder": params["critic"]["encoder"], "actor": params["actor"]},
        lambda obs: prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=1),
    )
    protocol = run_eval_protocol(partial(test, player, runtime, cfg, log_dir), runtime, cfg)
    if logger:
        logger.log_metrics({"Test/cumulative_reward": protocol["greedy"]["median"]}, 0)
        logger.finalize()
