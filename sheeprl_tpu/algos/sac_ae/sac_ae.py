"""SAC-AE — TPU-native main loop (reference sheeprl/algos/sac_ae/sac_ae.py
train:35, main:120).

One jitted ``lax.scan`` over the iteration's G gradient steps; per-step
cadences (actor every N, decoder every M, target EMA every K cumulative
gradient steps) are ``lax.cond`` branches keyed on a carried counter, so the
whole schedule compiles once. Five optimizers as in the reference: critic
(encoder + q-ensemble jointly), actor, alpha, encoder, decoder — the
encoder is stepped by both the critic and the autoencoder losses with
separate optimizer states (reference sac_ae.py:61-117)."""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.sac import _make_optimizer
from sheeprl_tpu.algos.sac_ae.agent import SACAEPlayer, build_agent
from sheeprl_tpu.algos.sac_ae.utils import prepare_obs, preprocess_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_buffer import maybe_create_for_transitions
from sheeprl_tpu.obs import setup_observability, trace_scope
from sheeprl_tpu.resilience import CheckpointManager
from sheeprl_tpu.resilience.sentinel import guard_update, restore_like
from sheeprl_tpu.utils.callback import load_checkpoint, restore_buffer
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import MetricFetchGate, device_get_metrics, Ratio, save_configs
from sheeprl_tpu.optim import restore_opt_states

sg = jax.lax.stop_gradient


def make_train_fn(runtime, modules, txs, cfg: Dict[str, Any], target_entropy: float):
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    encoder_tau = float(cfg.algo.encoder.tau)
    l2_lambda = float(cfg.algo.decoder.l2_lambda)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    actor_freq = int(cfg.algo.actor.per_rank_update_freq)
    decoder_freq = int(cfg.algo.decoder.per_rank_update_freq)
    num_critics = int(cfg.algo.critic.n)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_keys_dec = tuple(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = tuple(cfg.algo.mlp_keys.decoder)
    critic_tx, actor_tx, alpha_tx, encoder_tx, decoder_tx = txs

    def _norm(data, prefix=""):
        obs = {}
        for k in cnn_keys:
            obs[k] = data[prefix + k] / 255.0
        for k in mlp_keys:
            obs[k] = data[prefix + k]
        return obs

    def train(params, opt_states, data, key, counter0):
        """data: (G, B, ...); counter0: cumulative gradient-step counter at
        the start of this call (host int, traced)."""

        def one_step(carry, inp):
            params, opt_states, counter = carry
            batch, k = inp
            k1, k2, k3 = jax.random.split(k, 3)
            alpha = jnp.exp(params["log_alpha"])
            obs = _norm(batch)
            next_obs = _norm(batch, "next_")

            # ------------------------- critic update (encoder + ensemble)
            next_actions, next_logp = modules.actions_and_log_probs(
                params["critic"]["encoder"], params["actor"], next_obs, k1
            )
            target_feat = modules.critic_features(params["target"]["encoder"], next_obs)
            qf_next = modules.q_values(params["target"]["qfs"], target_feat, next_actions)
            min_qf_next = qf_next.min(-1, keepdims=True) - alpha * next_logp
            next_qf_value = sg(
                batch["rewards"] + (1 - batch["terminated"]) * gamma * min_qf_next
            )

            def qf_loss_fn(cp):
                feat = modules.critic_features(cp["encoder"], obs)
                qf_values = modules.q_values(cp["qfs"], feat, batch["actions"])
                return critic_loss(qf_values, next_qf_value, num_critics)

            qf_loss, qf_grads = jax.value_and_grad(qf_loss_fn)(params["critic"])
            updates, new_critic_opt = critic_tx.update(qf_grads, opt_states["critic"], params["critic"])
            new_critic = optax.apply_updates(params["critic"], updates)

            # ------------------------- target EMA (qfs tau, encoder tau)
            def do_ema():
                return {
                    "encoder": optax.incremental_update(
                        new_critic["encoder"], params["target"]["encoder"], encoder_tau
                    ),
                    "qfs": optax.incremental_update(
                        new_critic["qfs"], params["target"]["qfs"], tau
                    ),
                }

            new_target = jax.lax.cond(
                counter % target_freq == 0, do_ema, lambda: params["target"]
            )

            # ------------------------- actor + alpha update (delayed)
            def do_actor():
                def actor_loss_fn(ap):
                    actions, logp = modules.actions_and_log_probs(
                        new_critic["encoder"], ap, obs, k2
                    )
                    feat = modules.critic_features(new_critic["encoder"], obs)
                    q = modules.q_values(new_critic["qfs"], feat, actions)
                    return policy_loss(alpha, logp, q.min(-1, keepdims=True)), logp

                (a_loss, logp), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
                    params["actor"]
                )
                upd, new_actor_opt = actor_tx.update(actor_grads, opt_states["actor"], params["actor"])
                new_actor = optax.apply_updates(params["actor"], upd)

                al_loss, alpha_grad = jax.value_and_grad(
                    lambda la: entropy_loss(la, sg(logp), target_entropy)
                )(params["log_alpha"])
                upd, new_alpha_opt = alpha_tx.update(alpha_grad, opt_states["alpha"], params["log_alpha"])
                new_log_alpha = optax.apply_updates(params["log_alpha"], upd)
                return new_actor, new_actor_opt, new_log_alpha, new_alpha_opt, a_loss, al_loss

            new_actor, new_actor_opt, new_log_alpha, new_alpha_opt, actor_loss_v, alpha_loss_v = (
                jax.lax.cond(
                    counter % actor_freq == 0,
                    do_actor,
                    lambda: (
                        params["actor"],
                        opt_states["actor"],
                        params["log_alpha"],
                        opt_states["alpha"],
                        jnp.zeros(()),
                        jnp.zeros(()),
                    ),
                )
            )

            # ------------------------- autoencoder update (encoder+decoder)
            def do_ae():
                def ae_loss_fn(enc_dec):
                    enc_params, dec_params = enc_dec
                    hidden = modules.critic_features(enc_params, obs)
                    reconstruction = modules.decode(dec_params, hidden)
                    loss = jnp.zeros(())
                    l2 = (0.5 * (hidden**2).sum(-1)).mean()
                    for kk in cnn_keys_dec:
                        target = preprocess_obs(batch[kk], k3, bits=5)
                        loss += jnp.mean((target - reconstruction[kk]) ** 2) + l2_lambda * l2
                    for kk in mlp_keys_dec:
                        loss += jnp.mean((batch[kk] - reconstruction[kk]) ** 2) + l2_lambda * l2
                    return loss

                rec_loss, (enc_grads, dec_grads) = jax.value_and_grad(ae_loss_fn)(
                    (new_critic["encoder"], params["decoder"])
                )
                upd, new_enc_opt = encoder_tx.update(
                    enc_grads, opt_states["encoder"], new_critic["encoder"]
                )
                new_enc = optax.apply_updates(new_critic["encoder"], upd)
                upd, new_dec_opt = decoder_tx.update(
                    dec_grads, opt_states["decoder"], params["decoder"]
                )
                new_dec = optax.apply_updates(params["decoder"], upd)
                return new_enc, new_enc_opt, new_dec, new_dec_opt, rec_loss

            new_encoder, new_enc_opt, new_decoder, new_dec_opt, rec_loss_v = jax.lax.cond(
                counter % decoder_freq == 0,
                do_ae,
                lambda: (
                    new_critic["encoder"],
                    opt_states["encoder"],
                    params["decoder"],
                    opt_states["decoder"],
                    jnp.zeros(()),
                ),
            )

            new_params = {
                "critic": {"encoder": new_encoder, "qfs": new_critic["qfs"]},
                "target": new_target,
                "actor": new_actor,
                "decoder": new_decoder,
                "log_alpha": new_log_alpha,
            }
            new_opt_states = {
                "critic": new_critic_opt,
                "actor": new_actor_opt,
                "alpha": new_alpha_opt,
                "encoder": new_enc_opt,
                "decoder": new_dec_opt,
            }
            losses = jnp.stack([qf_loss, actor_loss_v, alpha_loss_v, rec_loss_v])
            flags = jnp.stack(
                [
                    jnp.ones(()),
                    (counter % actor_freq == 0).astype(jnp.float32),
                    (counter % actor_freq == 0).astype(jnp.float32),
                    (counter % decoder_freq == 0).astype(jnp.float32),
                ]
            )
            return (new_params, new_opt_states, counter + 1), (losses, flags)

        g = data["rewards"].shape[0]
        keys = jax.random.split(key, g)
        (params, opt_states, _), (losses, flags) = jax.lax.scan(
            one_step, (params, opt_states, counter0), (data, keys)
        )
        totals = flags.sum(0)
        mean_losses = losses.sum(0) / jnp.maximum(totals, 1.0)
        metrics = {
            "Loss/value_loss": mean_losses[0],
            "Loss/policy_loss": mean_losses[1],
            "Loss/alpha_loss": mean_losses[2],
            "Loss/reconstruction_loss": mean_losses[3],
        }
        return params, opt_states, metrics

    # training health sentinel hook (resilience/sentinel.py)
    return guard_update(runtime, train, cfg, n_state=2, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    import gymnasium as gym
    from gymnasium.vector import AsyncVectorEnv, AutoresetMode, SyncVectorEnv

    world_size = runtime.world_size
    runtime.seed_everything(cfg.seed)
    state = load_checkpoint(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    runtime.print(f"Log dir: {log_dir}")
    observability = setup_observability(runtime, cfg, log_dir, logger=logger)
    if logger:
        logger.log_hyperparams(cfg)

    total_envs = cfg.env.num_envs * world_size
    thunks = [
        make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
        for i in range(total_envs)
    ]
    envs = (
        SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
        if cfg.env.sync_env
        else AsyncVectorEnv(thunks, context="spawn", autoreset_mode=AutoresetMode.SAME_STEP)
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.decoder) - set(cfg.algo.cnn_keys.encoder)) > 0
        or len(set(cfg.algo.mlp_keys.decoder) - set(cfg.algo.mlp_keys.encoder)) > 0
    ):
        raise RuntimeError("The decoder keys must be contained in the encoder ones")
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)

    modules, params, target_entropy = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    params = runtime.replicate(
        runtime.to_param_dtype(params, exclude=("target", "log_alpha"))
    )

    critic_tx = _make_optimizer(cfg.algo.critic.optimizer, runtime.precision)
    actor_tx = _make_optimizer(cfg.algo.actor.optimizer, runtime.precision)
    alpha_tx = _make_optimizer(cfg.algo.alpha.optimizer, runtime.precision)
    encoder_tx = _make_optimizer(cfg.algo.encoder.optimizer, runtime.precision)
    decoder_tx = _make_optimizer(cfg.algo.decoder.optimizer, runtime.precision)
    if state is not None:
        # the encoder opt state pairs with the encoder SUBTREE nested under
        # the critic params (shared critic/encoder tree, see init below)
        params_for_opt = {**params, "encoder": params["critic"]["encoder"]}
        opt_states = restore_opt_states(
            state["opt_states"], params_for_opt, runtime.precision, key_map={"alpha": "log_alpha"}
        )
    else:
        opt_states = runtime.replicate(
            {
                "critic": critic_tx.init(params["critic"]),
                "actor": actor_tx.init(params["actor"]),
                "alpha": alpha_tx.init(params["log_alpha"]),
                "encoder": encoder_tx.init(params["critic"]["encoder"]),
                "decoder": decoder_tx.init(params["decoder"]),
            }
        )

    player_params = {"encoder": params["critic"]["encoder"], "actor": params["actor"]}
    player = SACAEPlayer(
        modules,
        player_params,
        lambda obs: prepare_obs(obs, cnn_keys=cnn_keys, num_envs=total_envs),
        device=runtime.player_device(player_params),
    )

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(dict(cfg.metric.aggregator))

    buffer_size = cfg.buffer.size // int(total_envs) if not cfg.dry_run else 1
    rb = ReplayBuffer(
        max(buffer_size, 1),
        total_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        obs_keys=tuple(obs_keys),
    )
    if state and cfg.buffer.checkpoint:
        rb = restore_buffer(
            state["rb"],
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        )
    # HBM-resident replay window + on-device sampling (data/device_buffer.py)
    device_cache = maybe_create_for_transitions(
        cfg, runtime, rb, state if state and cfg.buffer.checkpoint else None
    )

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(total_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])

    ckpt_mgr = CheckpointManager(
        runtime, cfg, log_dir, observability=observability, last_checkpoint=last_checkpoint
    )
    train_fn = make_train_fn(
        runtime, modules, (critic_tx, actor_tx, alpha_tx, encoder_tx, decoder_tx), cfg, target_entropy
    )
    health = train_fn.health.bind(ckpt_mgr=ckpt_mgr, select=("agent", "opt_states"))
    if health.enabled:
        observability.health_stats = health.stats

    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]

    cumulative_per_rank_gradient_steps = 0
    metric_fetch_gate = MetricFetchGate(cfg.metric.get("fetch_every", 1))
    for iter_num in range(start_iter, total_iters + 1):
        observability.on_iteration(policy_step)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time", SumMetric, sync_on_compute=False):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                actions = np.asarray(player.get_actions(obs, runtime.next_key()))
            next_obs, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
            rewards = rewards.reshape(total_envs, -1)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(infos["final_info"]["_episode"])[0]:
                    if aggregator and not aggregator.disabled:
                        aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                        aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={float(ep['r'][i])}")

        real_next_obs = {k: np.array(v) for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx in np.nonzero(infos["_final_obs"])[0]:
                for k, v in infos["final_obs"][idx].items():
                    real_next_obs[k][idx] = v

        for k in obs_keys:
            step_data[k] = obs[k][np.newaxis]
            if not cfg.buffer.sample_next_obs:
                step_data[f"next_{k}"] = real_next_obs[k][np.newaxis]
        step_data["terminated"] = terminated.reshape(1, total_envs, -1).astype(np.uint8)
        step_data["truncated"] = truncated.reshape(1, total_envs, -1).astype(np.uint8)
        step_data["actions"] = actions.reshape(1, total_envs, -1).astype(np.float32)
        step_data["rewards"] = rewards[np.newaxis].astype(np.float32)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        if device_cache is not None:
            device_cache.add(step_data)
        obs = next_obs

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(
                (policy_step - prefill_steps + policy_steps_per_iter) / world_size
            )
            if per_rank_gradient_steps > 0:
                g = per_rank_gradient_steps
                batch_total = g * cfg.algo.per_rank_batch_size * world_size
                if device_cache is not None and device_cache.can_sample_transitions(
                    cfg.buffer.sample_next_obs
                ):
                    # on-device gather + cast (pixels stay uint8 in HBM and
                    # widen to f32 on device); nothing crosses the link
                    data = {
                        k: v.astype(jnp.float32)
                        for k, v in device_cache.sample_transitions(
                            g,
                            cfg.algo.per_rank_batch_size * world_size,
                            runtime.next_key(),
                            sample_next_obs=cfg.buffer.sample_next_obs,
                            obs_keys=tuple(obs_keys),
                        ).items()
                    }
                else:
                    sample = rb.sample(
                        batch_size=batch_total,
                        sample_next_obs=cfg.buffer.sample_next_obs,
                    )
                    data = {
                        k: np.asarray(v, dtype=np.float32).reshape(
                            g, cfg.algo.per_rank_batch_size * world_size, *v.shape[2:]
                        )
                        for k, v in sample.items()
                    }
                    # shard the batch axis over the mesh so each device
                    # trains on its own rows (GSPMD inserts the grad psums)
                    data = runtime.shard_batch(data, axis=1)
                with timer("Time/train_time", SumMetric, sync_on_compute=cfg.metric.sync_on_compute):
                    params, opt_states, train_metrics = train_fn(
                        params,
                        opt_states,
                        data,
                        runtime.next_key(),
                        jnp.asarray(cumulative_per_rank_gradient_steps),
                    )
                rolled = health.tick()
                if rolled is not None:
                    params = restore_like(params, rolled["agent"])
                    opt_states = restore_like(opt_states, rolled["opt_states"])
                player.params = {"encoder": params["critic"]["encoder"], "actor": params["actor"]}
                cumulative_per_rank_gradient_steps += g
                train_step += world_size
                if aggregator and not aggregator.disabled and metric_fetch_gate():
                    with trace_scope("block_until_ready"):
                        fetched_metrics = device_get_metrics(train_metrics)
                    for k, v in fetched_metrics.items():
                        aggregator.update(k, v)

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        ):
            observability.on_log(policy_step, train_step)
            if logger:
                if aggregator and not aggregator.disabled:
                    logger.log_metrics(aggregator.compute(), policy_step)
                    aggregator.reset()
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * world_size / policy_step},
                    policy_step,
                )
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                            policy_step,
                        )
                    if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / timer_metrics["Time/env_interaction_time"]
                            },
                            policy_step,
                        )
                    timer.reset()
            last_log = policy_step
            last_train = train_step

        def _ckpt_state():
            ckpt_state = {
                "agent": params,
                "opt_states": opt_states,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": ckpt_mgr.last_checkpoint,
            }
            if cfg.buffer.checkpoint:
                ckpt_state["rb"] = rb
            return ckpt_state

        ckpt_mgr.maybe_checkpoint(
            policy_step=policy_step, is_last=iter_num == total_iters, state_fn=_ckpt_state
        )
        if ckpt_mgr.preempted:
            runtime.print(
                f"Preemption signal: emergency checkpoint written, stopping at iter {iter_num}"
            )
            break

    ckpt_mgr.close()
    envs.close()
    observability.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_rew = test(player, runtime, cfg, log_dir)
        if logger:
            logger.log_metrics({"Test/cumulative_reward": test_rew}, policy_step)
    if logger:
        logger.finalize()
