"""SAC-AE agent (flax) — counterpart of reference
sheeprl/algos/sac_ae/agent.py (CNNEncoder:26, MLPEncoder:89, MLPDecoder:122,
CNNDecoder:153, SACAEQFunction:204, SACAECritic:226,
SACAEContinuousActor:240, SACAEAgent:321, SACAEPlayer:453, build_agent:505).

SAC with a pixel autoencoder (arXiv:1910.01741):
- conv stack [32]*4 * mult, kernel 3, strides [2, 1, 1, 1], VALID, NHWC,
  then Dense(features_dim) -> LayerNorm -> tanh;
- the ACTOR shares the critic encoder's conv weights but owns a private
  Dense head, and its gradients never touch the conv stack (the reference
  ties ``.model`` only and detaches conv features, agent.py:442-447, 77-83);
- delta-orthogonal conv init / orthogonal dense init (reference
  sac_ae/utils.py:79);
- decoder inverts the encoder, with the final transposed conv reproducing
  torch's ``output_padding=1`` via explicit ((2, 3), (2, 3)) pads.

Functional param layout:
``params = {critic: {encoder, qfs}, target: {encoder, qfs}, actor, decoder,
log_alpha}``; the weight tying of the reference is positional — the actor
and player read the conv weights out of ``params["critic"]["encoder"]``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.utils.utils import transfer_tree

LOG_STD_MIN = -10.0
LOG_STD_MAX = 2.0

sg = jax.lax.stop_gradient

ortho_init = nn.initializers.orthogonal()


def delta_ortho_init(key, shape, dtype=jnp.float32):
    """Delta-orthogonal conv init (arXiv:1806.05393; reference
    sac_ae/utils.py:79): zero kernel with an orthogonal center tap, relu
    gain. Unlike jax's built-in it accepts fan_in > fan_out (orthogonal on
    the transposed matrix), matching torch's ``nn.init.orthogonal_``."""
    w = jnp.zeros(shape, dtype)
    center = nn.initializers.orthogonal(scale=float(np.sqrt(2.0)))(key, shape[-2:], dtype)
    return w.at[shape[0] // 2, shape[1] // 2].set(center)


class AEConvStack(nn.Module):
    """[32, 32, 32, 32] * mult, kernel 3, strides [2, 1, 1, 1], VALID,
    ReLU; flattens."""

    channels_multiplier: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for stride in (2, 1, 1, 1):
            x = nn.Conv(
                32 * self.channels_multiplier,
                (3, 3),
                strides=(stride, stride),
                padding="VALID",
                kernel_init=delta_ortho_init,
            )(x)
            x = nn.relu(x)
        return x.reshape(*x.shape[:-3], -1)


class AEFeatureHead(nn.Module):
    """Dense(features_dim) -> LayerNorm -> tanh (reference CNNEncoder.fc)."""

    features_dim: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(self.features_dim, kernel_init=ortho_init)(x)
        x = nn.LayerNorm()(x)
        return jnp.tanh(x)


class AECNNEncoder(nn.Module):
    keys: Sequence[str]
    features_dim: int
    channels_multiplier: int = 1

    def setup(self) -> None:
        self.convnet = AEConvStack(self.channels_multiplier)
        self.head = AEFeatureHead(self.features_dim)

    def conv(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return self.convnet(x)

    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.head(self.conv(obs))


class AEMLPEncoder(nn.Module):
    keys: Sequence[str]
    dense_units: int = 64
    mlp_layers: int = 2
    layer_norm: bool = False

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], -1)
        for _ in range(self.mlp_layers):
            x = nn.Dense(self.dense_units, kernel_init=ortho_init)(x)
            if self.layer_norm:
                x = nn.LayerNorm()(x)
            x = nn.relu(x)
        return x


class AECNNDecoder(nn.Module):
    """fc -> (s4, s4, 32*mult) -> 3 VALID deconvs k3 s1 -> final deconv k3
    s2 with torch-style output_padding=1 (reference CNNDecoder:153)."""

    keys: Sequence[str]
    channels: Sequence[int]
    conv_output_shape: Tuple[int, int, int]  # (s4, s4, 32*mult)
    channels_multiplier: int = 1

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        lead = latent.shape[:-1]
        x = nn.Dense(int(np.prod(self.conv_output_shape)), kernel_init=ortho_init)(latent)
        x = x.reshape(-1, *self.conv_output_shape)
        for _ in range(3):
            x = nn.ConvTranspose(
                32 * self.channels_multiplier,
                (3, 3),
                strides=(1, 1),
                padding="VALID",
                kernel_init=delta_ortho_init,
            )(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(
            int(sum(self.channels)),
            (3, 3),
            strides=(2, 2),
            padding=((2, 3), (2, 3)),
            kernel_init=delta_ortho_init,
        )(x)
        x = x.reshape(*lead, *x.shape[1:])
        out: Dict[str, jax.Array] = {}
        start = 0
        for k, c in zip(self.keys, self.channels):
            out[k] = x[..., start : start + c]
            start += c
        return out


class AEMLPDecoder(nn.Module):
    keys: Sequence[str]
    output_dims: Sequence[int]
    dense_units: int = 64
    mlp_layers: int = 2
    layer_norm: bool = False

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        x = latent
        for _ in range(self.mlp_layers):
            x = nn.Dense(self.dense_units, kernel_init=ortho_init)(x)
            if self.layer_norm:
                x = nn.LayerNorm()(x)
            x = nn.relu(x)
        return {
            k: nn.Dense(d, kernel_init=ortho_init)(x) for k, d in zip(self.keys, self.output_dims)
        }


class SACAEQFunction(nn.Module):
    hidden_size: int = 256

    @nn.compact
    def __call__(self, features: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([features, action], -1)
        x = nn.relu(nn.Dense(self.hidden_size, kernel_init=ortho_init)(x))
        x = nn.relu(nn.Dense(self.hidden_size, kernel_init=ortho_init)(x))
        return nn.Dense(1, kernel_init=ortho_init)(x)


class SACAEActorTrunk(nn.Module):
    """MLP (hidden, hidden) + mean/logstd heads; logstd squashed into
    [LOG_STD_MIN, LOG_STD_MAX] by tanh rescale (reference
    SACAEContinuousActor:240)."""

    action_dim: int
    hidden_size: int = 1024

    @nn.compact
    def __call__(self, features: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = nn.relu(nn.Dense(self.hidden_size, kernel_init=ortho_init)(features))
        x = nn.relu(nn.Dense(self.hidden_size, kernel_init=ortho_init)(x))
        mean = nn.Dense(self.action_dim, kernel_init=ortho_init)(x)
        log_std = nn.Dense(self.action_dim, kernel_init=ortho_init)(x)
        log_std = jnp.tanh(log_std)
        log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (log_std + 1)
        return mean, log_std


class SACAEModules:
    """Static container of the flax modules + action-space scaling."""

    def __init__(
        self,
        cnn_encoder: Optional[AECNNEncoder],
        mlp_encoder: Optional[AEMLPEncoder],
        actor_cnn_head: Optional[AEFeatureHead],
        actor_trunk: SACAEActorTrunk,
        qf: SACAEQFunction,
        cnn_decoder: Optional[AECNNDecoder],
        mlp_decoder: Optional[AEMLPDecoder],
        num_critics: int,
        action_low,
        action_high,
    ):
        self.cnn_encoder = cnn_encoder
        self.mlp_encoder = mlp_encoder
        self.actor_cnn_head = actor_cnn_head
        self.actor_trunk = actor_trunk
        self.qf = qf
        self.cnn_decoder = cnn_decoder
        self.mlp_decoder = mlp_decoder
        self.num_critics = num_critics
        self.action_scale = jnp.asarray((action_high - action_low) / 2.0, jnp.float32)
        self.action_bias = jnp.asarray((action_high + action_low) / 2.0, jnp.float32)

    # ------------------------------------------------------------- features
    def critic_features(self, enc_params, obs) -> jax.Array:
        feats = []
        if self.cnn_encoder is not None:
            feats.append(self.cnn_encoder.apply(enc_params["cnn"], obs))
        if self.mlp_encoder is not None:
            feats.append(self.mlp_encoder.apply(enc_params["mlp"], obs))
        return jnp.concatenate(feats, -1) if len(feats) > 1 else feats[0]

    def actor_features(self, enc_params, actor_params, obs) -> jax.Array:
        """Conv weights come (detached) from the critic encoder; the Dense
        head is the actor's own (reference agent.py:442-447 ties .model
        only; detach_encoder_features=True in the actor/critic calls of the
        actor update)."""
        feats = []
        if self.cnn_encoder is not None:
            conv = self.cnn_encoder.apply(enc_params["cnn"], obs, method=AECNNEncoder.conv)
            feats.append(self.actor_cnn_head.apply(actor_params["cnn_head"], sg(conv)))
        if self.mlp_encoder is not None:
            feats.append(sg(self.mlp_encoder.apply(enc_params["mlp"], obs)))
        return jnp.concatenate(feats, -1) if len(feats) > 1 else feats[0]

    # ------------------------------------------------------------- heads
    def q_values(self, qfs_params, features, actions) -> jax.Array:
        """(B, num_critics) — ensemble vmapped over stacked params."""
        q = jax.vmap(lambda p: self.qf.apply(p, features, actions))(qfs_params)  # (N, B, 1)
        return jnp.moveaxis(q[..., 0], 0, -1)

    def actions_and_log_probs(self, enc_params, actor_params, obs, key):
        mean, log_std = self.actor_trunk.apply(
            actor_params["trunk"], self.actor_features(enc_params, actor_params, obs)
        )
        std = jnp.exp(log_std)
        x = mean + std * jax.random.normal(key, mean.shape)
        y = jnp.tanh(x)
        action = y * self.action_scale + self.action_bias
        logp = -((x - mean) ** 2) / (2 * std**2) - log_std - 0.5 * jnp.log(2 * jnp.pi)
        logp = logp - jnp.log(self.action_scale * (1 - y**2) + 1e-6)
        return action, logp.sum(-1, keepdims=True)

    def greedy_actions(self, enc_params, actor_params, obs) -> jax.Array:
        mean, _ = self.actor_trunk.apply(
            actor_params["trunk"], self.actor_features(enc_params, actor_params, obs)
        )
        return jnp.tanh(mean) * self.action_scale + self.action_bias

    def decode(self, dec_params, latent) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder.apply(dec_params["cnn"], latent))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder.apply(dec_params["mlp"], latent))
        return out


class SACAEPlayer:
    """Env-interaction policy over the tied conv + private actor head
    (reference SACAEPlayer:453)."""

    def __init__(self, modules: SACAEModules, params, prepare_obs_fn, device=None):
        self.modules = modules
        self.prepare_obs_fn = prepare_obs_fn
        self.device = device
        self.params = params  # {"encoder": ..., "actor": ...}

        def _act(params, obs, key):
            a, _ = modules.actions_and_log_probs(params["encoder"], params["actor"], obs, key)
            return a

        def _greedy(params, obs):
            return modules.greedy_actions(params["encoder"], params["actor"], obs)

        self._act = jax.jit(_act)
        self._greedy = jax.jit(_greedy)

    @property
    def params(self):
        return self._params

    @params.setter
    def params(self, value):
        self._params = transfer_tree(value, self.device)

    def get_actions(self, obs, key=None, greedy: bool = False):
        prepared = self.prepare_obs_fn(obs)
        if self.device is not None:
            prepared = jax.device_put(prepared, self.device)
            key = jax.device_put(key, self.device) if key is not None else None
        if greedy:
            return self._greedy(self._params, prepared)
        return self._act(self._params, prepared, key)


def build_agent(
    runtime,
    cfg: Dict[str, Any],
    obs_space,
    action_space,
    agent_state: Optional[Dict[str, Any]] = None,
):
    """-> (modules(SACAEModules), params, target_entropy)."""
    act_dim = int(np.prod(action_space.shape))
    target_entropy = -act_dim

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_channels = [int(obs_space[k].shape[-1]) for k in cnn_keys]
    mlp_dims = [int(obs_space[k].shape[0]) for k in mlp_keys]
    screen_size = int(obs_space[cnn_keys[0]].shape[0]) if cnn_keys else 0
    mult = int(cfg.algo.encoder.cnn_channels_multiplier)

    cnn_encoder = (
        AECNNEncoder(
            keys=cnn_keys, features_dim=cfg.algo.encoder.features_dim, channels_multiplier=mult
        )
        if cnn_keys
        else None
    )
    mlp_encoder = (
        AEMLPEncoder(
            keys=mlp_keys,
            dense_units=cfg.algo.encoder.dense_units,
            mlp_layers=cfg.algo.encoder.mlp_layers,
            layer_norm=bool(cfg.algo.encoder.layer_norm),
        )
        if mlp_keys
        else None
    )

    # conv output spatial size: strides [2, 1, 1, 1], kernel 3, VALID
    if cnn_keys:
        s = (screen_size - 3) // 2 + 1
        for _ in range(3):
            s -= 2
        if s <= 0:
            raise ValueError(f"screen_size {screen_size} too small for the SAC-AE conv stack")
        if screen_size % 2 != 0:
            raise ValueError("SAC-AE decoder requires an even env.screen_size")
        conv_output_shape = (s, s, 32 * mult)
        cnn_features_dim = int(cfg.algo.encoder.features_dim)
    else:
        conv_output_shape = None
        cnn_features_dim = 0
    mlp_features_dim = cfg.algo.encoder.dense_units if mlp_encoder is not None else 0
    features_dim = cnn_features_dim + mlp_features_dim

    actor_cnn_head = AEFeatureHead(cfg.algo.encoder.features_dim) if cnn_keys else None
    actor_trunk = SACAEActorTrunk(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
    qf = SACAEQFunction(hidden_size=cfg.algo.critic.hidden_size)
    num_critics = int(cfg.algo.critic.n)

    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    cnn_decoder = (
        AECNNDecoder(
            keys=cnn_dec_keys,
            channels=[int(obs_space[k].shape[-1]) for k in cnn_dec_keys],
            conv_output_shape=conv_output_shape,
            channels_multiplier=int(cfg.algo.decoder.cnn_channels_multiplier),
        )
        if len(cnn_dec_keys) > 0
        else None
    )
    mlp_decoder = (
        AEMLPDecoder(
            keys=mlp_dec_keys,
            output_dims=[int(obs_space[k].shape[0]) for k in mlp_dec_keys],
            dense_units=cfg.algo.decoder.dense_units,
            mlp_layers=cfg.algo.decoder.mlp_layers,
            layer_norm=bool(cfg.algo.decoder.layer_norm),
        )
        if len(mlp_dec_keys) > 0
        else None
    )

    modules = SACAEModules(
        cnn_encoder,
        mlp_encoder,
        actor_cnn_head,
        actor_trunk,
        qf,
        cnn_decoder,
        mlp_decoder,
        num_critics,
        action_space.low,
        action_space.high,
    )

    B = 1
    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((B, *obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((B, *obs_space[k].shape), jnp.float32)
    dummy_feat = jnp.zeros((B, features_dim), jnp.float32)
    dummy_act = jnp.zeros((B, act_dim), jnp.float32)
    k = runtime.next_key

    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
        return modules, params, target_entropy

    enc_params = {}
    if cnn_encoder is not None:
        enc_params["cnn"] = cnn_encoder.init(k(), dummy_obs)
    if mlp_encoder is not None:
        enc_params["mlp"] = mlp_encoder.init(k(), dummy_obs)

    qfs_params = jax.vmap(lambda kk: qf.init(kk, dummy_feat, dummy_act))(
        jax.random.split(k(), num_critics)
    )
    actor_params = {"trunk": actor_trunk.init(k(), dummy_feat)}
    if actor_cnn_head is not None:
        conv_flat_dim = int(np.prod(conv_output_shape))
        actor_params["cnn_head"] = actor_cnn_head.init(k(), jnp.zeros((B, conv_flat_dim)))

    dec_params = {}
    if cnn_decoder is not None:
        dec_params["cnn"] = cnn_decoder.init(k(), dummy_feat)
        rec = cnn_decoder.apply(dec_params["cnn"], dummy_feat)
        for key_, c in zip(cnn_decoder.keys, cnn_decoder.channels):
            expect = (B, screen_size, screen_size, c)
            if rec[key_].shape != expect:
                raise RuntimeError(
                    f"SAC-AE decoder shape mismatch for '{key_}': {rec[key_].shape} != {expect}"
                )
    if mlp_decoder is not None:
        dec_params["mlp"] = mlp_decoder.init(k(), dummy_feat)

    params = {
        "critic": {"encoder": enc_params, "qfs": qfs_params},
        "target": {
            "encoder": jax.tree_util.tree_map(jnp.copy, enc_params),
            "qfs": jax.tree_util.tree_map(jnp.copy, qfs_params),
        },
        "actor": actor_params,
        "decoder": dec_params,
        "log_alpha": jnp.log(jnp.asarray([float(cfg.algo.alpha.alpha)], jnp.float32)),
    }
    return modules, params, target_entropy
