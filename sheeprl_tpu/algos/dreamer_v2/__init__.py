from sheeprl_tpu.algos.dreamer_v2 import dreamer_v2, evaluate  # noqa: F401  (registry side-effect)
