"""DreamerV2 world-model loss (reference sheeprl/algos/dreamer_v2/loss.py:9):
ELBO with KL balancing (alpha * KL(sg(post) || prior) +
(1 - alpha) * KL(post || sg(prior))), free-nats clamping (averaged or
element-wise per ``kl_free_avg``), Normal(.., 1) obs/reward heads and an
optional Bernoulli continue head."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.distribution import (
    Distribution,
    Independent,
    OneHotCategoricalStraightThrough,
    kl_divergence,
)

sg = jax.lax.stop_gradient


def reconstruction_loss(
    po: Dict[str, Distribution],
    observations: Dict[str, jax.Array],
    pr: Distribution,
    rewards: jax.Array,
    priors_logits: jax.Array,
    posteriors_logits: jax.Array,
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 0.0,
    kl_free_avg: bool = True,
    kl_regularizer: float = 1.0,
    pc: Optional[Distribution] = None,
    continue_targets: Optional[jax.Array] = None,
    discount_scale_factor: float = 1.0,
) -> Tuple[jax.Array, ...]:
    """-> (reconstruction_loss, kl, kl_loss, reward_loss, observation_loss,
    continue_loss)."""
    observation_loss = -sum(po[k].log_prob(observations[k]).mean() for k in po.keys())
    reward_loss = -pr.log_prob(rewards).mean()
    lhs = kl = kl_divergence(
        Independent(OneHotCategoricalStraightThrough(logits=sg(posteriors_logits)), 1),
        Independent(OneHotCategoricalStraightThrough(logits=priors_logits), 1),
    )
    rhs = kl_divergence(
        Independent(OneHotCategoricalStraightThrough(logits=posteriors_logits), 1),
        Independent(OneHotCategoricalStraightThrough(logits=sg(priors_logits)), 1),
    )
    if kl_free_avg:
        loss_lhs = jnp.maximum(lhs.mean(), kl_free_nats)
        loss_rhs = jnp.maximum(rhs.mean(), kl_free_nats)
    else:
        loss_lhs = jnp.maximum(lhs, kl_free_nats).mean()
        loss_rhs = jnp.maximum(rhs, kl_free_nats).mean()
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs
    if pc is not None and continue_targets is not None:
        continue_loss = discount_scale_factor * -pc.log_prob(continue_targets).mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    rec_loss = kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss
    return rec_loss, kl, kl_loss, reward_loss, observation_loss, continue_loss
