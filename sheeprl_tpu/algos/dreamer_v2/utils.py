"""DreamerV2 helpers (reference sheeprl/algos/dreamer_v2/utils.py):
compute_lambda_values:86, prepare_obs:109, test, AGGREGATOR_KEYS:24."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.utils.env import make_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/post_entropy",
    "State/prior_entropy",
    "State/kl",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    bootstrap: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(lambda) targets, Hafner-V2 form (reference compute_lambda_values:86):
    inputs = r + c * V_next * (1 - lambda), backward recursion
    agg = inputs_t + c_t * lambda * agg. All shapes (H, N, 1); ``bootstrap``
    is (1, N, 1)."""
    next_values = jnp.concatenate([values[1:], bootstrap], 0)
    inputs = rewards + continues * next_values * (1 - lmbda)

    def step(agg, inp):
        inp_t, cont_t = inp
        agg = inp_t + cont_t * lmbda * agg
        return agg, agg

    _, lv = jax.lax.scan(step, bootstrap[0], (inputs, continues), reverse=True)
    return lv


def prepare_obs(
    obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), num_envs: int = 1, **kwargs: Any
) -> Dict[str, np.ndarray]:
    """(1, num_envs, ...) float obs dict; images NHWC normalized to
    [-0.5, 0.5]."""
    out = {}
    for k, v in obs.items():
        arr = np.asarray(v, dtype=np.float32)
        if k in cnn_keys:
            arr = arr.reshape(1, num_envs, *arr.shape[-3:]) / 255.0 - 0.5
        else:
            arr = arr.reshape(1, num_envs, -1)
        out[k] = arr
    return out


def test(
    player,
    runtime,
    cfg: Dict[str, Any],
    log_dir: str,
    test_name: str = "",
    greedy: bool = True,
    seed: Optional[int] = None,
) -> float:
    seed = cfg.seed if seed is None else seed
    env = make_env(cfg, seed, 0, log_dir, "test" + (f"_{test_name}" if test_name else ""))()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=seed)[0]
    old_num_envs = player.num_envs
    player.num_envs = 1
    player.init_states()
    while not done:
        prepared = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=1)
        mask = {k: v for k, v in prepared.items() if k.startswith("mask")} or None
        real_actions = player.get_actions(prepared, runtime.next_key(), greedy, mask)
        if player.actor_module.is_continuous:
            acts = np.stack([np.asarray(a) for a in real_actions], -1)
        else:
            acts = np.stack([np.asarray(a).argmax(-1) for a in real_actions], -1)
        obs, reward, terminated, truncated, _ = env.step(acts.reshape(env.action_space.shape))
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(reward)
    runtime.print("Test - Reward:", cumulative_rew)
    env.close()
    player.num_envs = old_num_envs
    player.init_states()
    return cumulative_rew
