"""DreamerV2 agent (flax) — counterpart of reference
sheeprl/algos/dreamer_v2/agent.py (CNNEncoder:31, MLPEncoder:85,
CNNDecoder:129, MLPDecoder:199, RecurrentModel:246, RSSM:301, Actor:416,
WorldModel:707, PlayerDV2:735, build_agent:836).

Differences from the DV3 agent that define the V2 behavior:
- ELU activations, LayerNorm mostly off (GRU keeps its LN);
- encoder convs are VALID-padded k=4 s=2 (64 -> 31 -> 14 -> 6 -> 2), the
  decoder inverts with VALID deconvs of kernels [5, 5, 6, 6] from a 1x1
  feature map;
- no unimix on latent/actor logits, no learnable initial recurrent state
  (zeros resets), no symlog/two-hot heads;
- continuous actor defaults to a TruncatedNormal on tanh(mean);
- Xavier-normal init with zero biases (reference dreamer_v2/utils.py:64).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import (
    LayerNormGRUCell,
    batch_major_flatten,
    batch_major_unflatten,
    resolve_activation,
)
from sheeprl_tpu.utils.distribution import (
    Independent,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    TanhNormal,
    TruncatedNormal,
)
from sheeprl_tpu.utils.utils import transfer_tree

xavier_init = nn.initializers.xavier_normal()


class DenseActLn(nn.Module):
    """Dense -> (optional LayerNorm) -> activation, Xavier-normal init."""

    units: int
    act: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32  # compute dtype; params f32, LN statistics f32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(self.units, kernel_init=xavier_init, dtype=self.dtype)(x)
        if self.layer_norm:
            x = nn.LayerNorm()(x)
        return resolve_activation(self.act)(x.astype(self.dtype))


class V2MLP(nn.Module):
    """Stack of DenseActLn blocks + optional linear output head."""

    units: int
    layers: int
    output_dim: Optional[int] = None
    act: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for _ in range(self.layers):
            x = DenseActLn(self.units, self.act, self.layer_norm, dtype=self.dtype)(x)
        if self.output_dim is not None:
            # heads emit f32 for the downstream distributions
            x = nn.Dense(self.output_dim, kernel_init=xavier_init)(x.astype(jnp.float32))
        return x


class CNNEncoder(nn.Module):
    """4-stage VALID conv encoder, kernel 4 stride 2, channels
    [1, 2, 4, 8] * mult, NHWC (reference CNNEncoder:31 assumes 64x64)."""

    keys: Sequence[str]
    channels_multiplier: int
    layer_norm: bool = False
    act: Any = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        # sharding-critical: see batch_major_flatten
        x, lead = batch_major_flatten(x, 3)
        for i in range(4):
            x = nn.Conv(
                (2**i) * self.channels_multiplier,
                (4, 4),
                strides=(2, 2),
                padding="VALID",
                kernel_init=xavier_init,
                dtype=self.dtype,
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm()(x)
            x = resolve_activation(self.act)(x.astype(self.dtype))
        return batch_major_unflatten(x.reshape(x.shape[0], -1), lead)


class MLPEncoder(nn.Module):
    keys: Sequence[str]
    mlp_layers: int = 4
    dense_units: int = 400
    layer_norm: bool = False
    act: Any = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], -1)
        return V2MLP(self.dense_units, self.mlp_layers, None, self.act, self.layer_norm, dtype=self.dtype)(x)


class MultiEncoderV2(nn.Module):
    cnn_encoder: Optional[nn.Module] = None
    mlp_encoder: Optional[nn.Module] = None

    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self.cnn_encoder is not None:
            feats.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            feats.append(self.mlp_encoder(obs))
        return jnp.concatenate(feats, -1) if len(feats) > 1 else feats[0]


class CNNDecoder(nn.Module):
    """Linear latent -> (1, 1, cnn_encoder_output_dim) -> 4 VALID deconvs of
    kernels [5, 5, 6, 6] stride 2 back to 64x64 (reference CNNDecoder:129)."""

    keys: Sequence[str]
    output_channels: Sequence[int]
    channels_multiplier: int
    cnn_encoder_output_dim: int
    layer_norm: bool = False
    act: Any = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        x = nn.Dense(self.cnn_encoder_output_dim, kernel_init=xavier_init, dtype=self.dtype)(latent)
        # sharding-critical: see batch_major_flatten
        x, lead = batch_major_flatten(x, 1)
        x = x.reshape(-1, 1, 1, self.cnn_encoder_output_dim)
        chans = [4 * self.channels_multiplier, 2 * self.channels_multiplier, self.channels_multiplier]
        kernels = [5, 5, 6, 6]
        for i, ch in enumerate(chans):
            x = nn.ConvTranspose(
                ch, (kernels[i], kernels[i]), strides=(2, 2), padding="VALID", kernel_init=xavier_init,
                dtype=self.dtype,
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm()(x)
            x = resolve_activation(self.act)(x.astype(self.dtype))
        x = x.astype(jnp.float32)  # final deconv emits f32 for the dists
        x = nn.ConvTranspose(
            int(sum(self.output_channels)),
            (kernels[-1], kernels[-1]),
            strides=(2, 2),
            padding="VALID",
            kernel_init=xavier_init,
        )(x)
        x = batch_major_unflatten(x, lead)
        out: Dict[str, jax.Array] = {}
        start = 0
        for k, c in zip(self.keys, self.output_channels):
            out[k] = x[..., start : start + c]
            start += c
        return out


class MLPDecoder(nn.Module):
    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 400
    layer_norm: bool = False
    act: Any = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        x = V2MLP(self.dense_units, self.mlp_layers, None, self.act, self.layer_norm, dtype=self.dtype)(latent)
        x = x.astype(jnp.float32)
        return {
            k: nn.Dense(d, kernel_init=xavier_init)(x) for k, d in zip(self.keys, self.output_dims)
        }


class MultiDecoderV2(nn.Module):
    cnn_decoder: Optional[nn.Module] = None
    mlp_decoder: Optional[nn.Module] = None

    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latent))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent))
        return out


class RecurrentModel(nn.Module):
    """Dense+act projection -> LayerNormGRUCell with bias and LN (reference
    RecurrentModel:246: the GRU always keeps its LayerNorm in V2)."""

    recurrent_state_size: int
    dense_units: int
    layer_norm: bool = False  # LN of the pre-GRU MLP only
    act: Any = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, inp: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = DenseActLn(self.dense_units, self.act, self.layer_norm, dtype=self.dtype)(inp)
        new_h, _ = LayerNormGRUCell(
            hidden_size=self.recurrent_state_size, use_bias=True, layer_norm=True,
            dtype=self.dtype,
        )(recurrent_state, feat)
        return new_h.astype(jnp.float32)


def compute_stochastic_state(
    logits: jax.Array,
    discrete: int,
    key: Optional[jax.Array],
    sample: bool = True,
    noise: Optional[jax.Array] = None,
) -> jax.Array:
    """(..., stoch*discrete) logits -> (..., stoch, discrete) one-hot ST
    sample (reference dreamer_v2/utils.py:44); no unimix in V2.

    ``noise`` is pre-drawn Gumbel noise of the reshaped logits' shape —
    the categorical sample becomes ``argmax(logits + noise)`` with the
    same straight-through estimator, letting train scans hoist all RNG
    out of their latency-bound bodies (see dreamer_v3.agent)."""
    logits = logits.reshape(*logits.shape[:-1], -1, discrete)
    if noise is not None and sample:
        hard = jax.nn.one_hot(
            jnp.argmax(logits + noise, -1), discrete, dtype=logits.dtype
        )
        p = jax.nn.softmax(logits, -1)
        return jax.lax.stop_gradient(hard) + p - jax.lax.stop_gradient(p)
    dist = OneHotCategoricalStraightThrough(logits=logits)
    return dist.rsample(key) if sample else dist.mode


class RSSM(nn.Module):
    """Discrete-latent RSSM with zeros initial state and is_first-gated
    zero resets (reference RSSM:301)."""

    actions_dim: Sequence[int]
    embedded_obs_dim: int
    recurrent_state_size: int
    dense_units: int
    stochastic_size: int = 32
    discrete_size: int = 32
    representation_hidden_size: int = 600
    transition_hidden_size: int = 600
    layer_norm: bool = False
    recurrent_layer_norm: bool = False
    act: Any = "elu"
    dtype: Any = jnp.float32

    def setup(self) -> None:
        stoch = self.stochastic_size * self.discrete_size
        self.recurrent_model = RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            layer_norm=self.recurrent_layer_norm,
            act=self.act,
            dtype=self.dtype,
        )
        self.representation_model = V2MLP(
            self.representation_hidden_size, 1, stoch, self.act, self.layer_norm, dtype=self.dtype
        )
        self.transition_model = V2MLP(
            self.transition_hidden_size, 1, stoch, self.act, self.layer_norm, dtype=self.dtype
        )

    def recurrent_step(self, inp: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        return self.recurrent_model(inp, recurrent_state)

    def _representation(
        self,
        recurrent_state: jax.Array,
        embedded_obs: jax.Array,
        key: Optional[jax.Array],
        noise: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, jax.Array]:
        logits = self.representation_model(jnp.concatenate([recurrent_state, embedded_obs], -1))
        return logits, compute_stochastic_state(logits, self.discrete_size, key, noise=noise)

    def _transition(
        self,
        recurrent_out: jax.Array,
        key: Optional[jax.Array],
        sample_state: bool = True,
        noise: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, jax.Array]:
        logits = self.transition_model(recurrent_out)
        return logits, compute_stochastic_state(
            logits, self.discrete_size, key, sample=sample_state, noise=noise
        )

    def representation_embed_proj(self, embedded_obs: jax.Array) -> jax.Array:
        """Embed-side half of the representation model's first Dense.

        The first DenseActLn of the representation model sees
        ``[h_t, embed_t]``; splitting its kernel lets the (big) embed-side
        product — plus the Dense bias — run as ONE batched matmul over the
        whole sequence outside the train scan, and moves its
        (embed_dim, units) kernel-gradient accumulation out of the
        backward while-loop's carry (same argument as the DV3 hoist,
        dreamer_v3.agent.RSSM.representation_embed_proj)."""
        p = self.representation_model.variables["params"]["DenseActLn_0"]["Dense_0"]
        k_e = p["kernel"][self.recurrent_state_size:].astype(self.dtype)
        return embedded_obs.astype(self.dtype) @ k_e + p["bias"].astype(self.dtype)

    def _representation_from_proj(
        self,
        emb_proj: jax.Array,
        recurrent_state: jax.Array,
        key: Optional[jax.Array] = None,
        noise: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, jax.Array]:
        """Posterior from a precomputed embed projection: the scan-body
        slice of :meth:`_representation` (manually unrolled V2MLP(layers=1)
        so the h-side product adds onto ``emb_proj``)."""
        from sheeprl_tpu.models.models import ln_act_apply, resolve_activation

        params = self.representation_model.variables["params"]
        p = params["DenseActLn_0"]["Dense_0"]
        k_h = p["kernel"][: self.recurrent_state_size].astype(self.dtype)
        x = recurrent_state.astype(self.dtype) @ k_h + emb_proj
        if self.layer_norm:
            # DenseActLn uses flax LayerNorm defaults (eps 1e-6, f32 stats)
            x = ln_act_apply(
                params["DenseActLn_0"]["LayerNorm_0"], x,
                eps=1e-6, act=self.act, dtype=self.dtype,
            )
        else:
            x = resolve_activation(self.act)(x.astype(self.dtype))
        head = params["Dense_0"]
        logits = x.astype(jnp.float32) @ head["kernel"] + head["bias"]
        return logits, compute_stochastic_state(
            logits, self.discrete_size, key, noise=noise
        )

    def dynamic_posterior_from_proj(
        self,
        posterior: jax.Array,
        recurrent_state: jax.Array,
        action: jax.Array,
        emb_proj: jax.Array,
        is_first: jax.Array,
        key: Optional[jax.Array] = None,
        noise: Optional[jax.Array] = None,
    ):
        """:meth:`dynamic_posterior` with the representation model's
        embed-side product precomputed (see
        :meth:`representation_embed_proj`)."""
        action = (1 - is_first) * action
        posterior = (1 - is_first) * posterior.reshape(*posterior.shape[:-2], -1)
        recurrent_state = (1 - is_first) * recurrent_state
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        posterior_logits, posterior = self._representation_from_proj(
            emb_proj, recurrent_state, key, noise=noise
        )
        return recurrent_state, posterior, posterior_logits

    def dynamic(
        self,
        posterior: jax.Array,
        recurrent_state: jax.Array,
        action: jax.Array,
        embedded_obs: jax.Array,
        is_first: jax.Array,
        key: jax.Array,
    ):
        """One dynamic step; zero resets where is_first (reference
        dynamic:336-369)."""
        k1, k2 = jax.random.split(key)
        action = (1 - is_first) * action
        posterior = (1 - is_first) * posterior.reshape(*posterior.shape[:-2], -1)
        recurrent_state = (1 - is_first) * recurrent_state
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        prior_logits, prior = self._transition(recurrent_state, k1)
        posterior_logits, posterior = self._representation(recurrent_state, embedded_obs, k2)
        return recurrent_state, posterior, prior, posterior_logits, prior_logits

    def dynamic_posterior(
        self,
        posterior: jax.Array,
        recurrent_state: jax.Array,
        action: jax.Array,
        embedded_obs: jax.Array,
        is_first: jax.Array,
        key: Optional[jax.Array] = None,
        noise: Optional[jax.Array] = None,
    ):
        """Sequential-only slice of :meth:`dynamic` for the train scan: the
        transition model (prior) is a pure function of ``h_t``, its SAMPLE
        is unused by the world-model loss, and it batches over the stacked
        recurrent states outside the scan (see dreamer_v3.agent)."""
        action = (1 - is_first) * action
        posterior = (1 - is_first) * posterior.reshape(*posterior.shape[:-2], -1)
        recurrent_state = (1 - is_first) * recurrent_state
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        posterior_logits, posterior = self._representation(
            recurrent_state, embedded_obs, key, noise=noise
        )
        return recurrent_state, posterior, posterior_logits

    def imagination(
        self,
        prior: jax.Array,
        recurrent_state: jax.Array,
        actions: jax.Array,
        key: Optional[jax.Array],
        noise: Optional[jax.Array] = None,
    ):
        recurrent_state = self.recurrent_model(
            jnp.concatenate([prior, actions], -1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key, noise=noise)
        return imagined_prior, recurrent_state


class Actor(nn.Module):
    """DV2 actor: ELU trunk + per-subaction one-hot ST heads (discrete) or a
    TruncatedNormal/TanhNormal/Normal head (continuous) (reference Actor:416)."""

    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    init_std: float = 0.0
    min_std: float = 0.1
    dense_units: int = 400
    mlp_layers: int = 4
    layer_norm: bool = False
    act: Any = "elu"
    dtype: Any = jnp.float32

    def _dist_name(self) -> str:
        d = self.distribution.lower()
        if d == "auto":
            return "trunc_normal" if self.is_continuous else "discrete"
        return d

    @nn.compact
    def __call__(
        self,
        state: jax.Array,
        greedy: bool = False,
        key: Optional[jax.Array] = None,
        mask: Optional[Dict[str, jax.Array]] = None,
    ):
        x = state
        for _ in range(self.mlp_layers):
            x = DenseActLn(self.dense_units, self.act, self.layer_norm, dtype=self.dtype)(x)
        x = x.astype(jnp.float32)  # dist heads in f32
        if self.is_continuous:
            pre = nn.Dense(int(np.sum(self.actions_dim)) * 2, kernel_init=xavier_init)(x)
            mean, std = jnp.split(pre, 2, -1)
            name = self._dist_name()
            if name == "tanh_normal":
                mean = 5 * jnp.tanh(mean / 5)
                std = jax.nn.softplus(std + self.init_std) + self.min_std
                dist = Independent(TanhNormal(mean, std), 1)
            elif name == "normal":
                dist = Independent(Normal(mean, std), 1)
            elif name == "trunc_normal":
                std = 2 * jax.nn.sigmoid((std + self.init_std) / 2) + self.min_std
                dist = Independent(TruncatedNormal(jnp.tanh(mean), std, -1.0, 1.0), 1)
            else:
                raise ValueError(f"Bad continuous distribution: {name}")
            # reference (greedy) samples 100 and keeps the argmax-log-prob
            # one; for these unimodal dists the mode is that argmax
            actions = dist.mode if greedy else dist.rsample(key)
            return (actions,), (dist,)
        heads = [nn.Dense(d, kernel_init=xavier_init)(x) for d in self.actions_dim]
        actions: List[jax.Array] = []
        dists = []
        keys = jax.random.split(key, len(heads)) if key is not None else [None] * len(heads)
        # MineDojo-style conditional masks (reference MinedojoActor:577),
        # vectorized: craft head constrained when the functional action is
        # craft (15), inventory head for equip/place (16/17) / destroy (18)
        functional_action = None
        for i, logits in enumerate(heads):
            if mask is not None:
                if i == 0 and "mask_action_type" in mask:
                    logits = jnp.where(mask["mask_action_type"], logits, -jnp.inf)
                elif i == 1 and "mask_craft_smelt" in mask:
                    is_craft = (functional_action == 15)[..., None]
                    valid = jnp.where(is_craft, mask["mask_craft_smelt"], True)
                    logits = jnp.where(valid, logits, -jnp.inf)
                elif i == 2 and "mask_equip_place" in mask and "mask_destroy" in mask:
                    fa = functional_action[..., None]
                    valid = jnp.where(
                        (fa == 16) | (fa == 17),
                        mask["mask_equip_place"],
                        jnp.where(fa == 18, mask["mask_destroy"], True),
                    )
                    logits = jnp.where(valid, logits, -jnp.inf)
            d = OneHotCategoricalStraightThrough(logits=logits)
            dists.append(d)
            actions.append(d.mode if greedy else d.rsample(keys[i]))
            if functional_action is None:
                functional_action = actions[0].argmax(-1)
        return tuple(actions), tuple(dists)


# cfg.algo.actor.cls target for MineDojo runs (reference MinedojoActor:577)
MinedojoActor = Actor


def add_exploration_noise(
    actions: Sequence[jax.Array],
    key: jax.Array,
    expl_amount: float,
    actions_dim: Sequence[int],
    is_continuous: bool,
) -> Sequence[jax.Array]:
    """Epsilon-style exploration noise (reference Actor.add_exploration_noise:
    clipped Normal jitter for continuous, uniform one-hot resample with
    probability ``expl_amount`` for discrete). ``expl_amount`` may be a
    traced scalar (decay schedules); amount 0 is then a no-op rather than a
    short-circuit."""
    if isinstance(expl_amount, (int, float)) and expl_amount <= 0.0:
        return tuple(actions)
    if is_continuous:
        flat = jnp.concatenate(list(actions), -1)
        noisy = jnp.clip(flat + expl_amount * jax.random.normal(key, flat.shape), -1.0, 1.0)
        # the clip belongs to the noise: with amount 0 (traced) return the
        # raw action so unbounded heads are not silently truncated
        return (jnp.where(jnp.asarray(expl_amount) > 0, noisy, flat),)
    out = []
    keys = jax.random.split(key, 2 * len(actions))
    for i, act in enumerate(actions):
        sample = OneHotCategorical(logits=jnp.zeros_like(act)).sample(keys[2 * i])
        coin = jax.random.uniform(keys[2 * i + 1], act.shape[:-1] + (1,))
        out.append(jnp.where(coin < expl_amount, sample, act))
    return tuple(out)


class WorldModel:
    """Container of the world-model modules sharing one params tree
    (reference WorldModel:707). ``continue_model`` may be None
    (use_continues=False default in V2)."""

    def __init__(self, encoder, rssm, observation_model, reward_model, continue_model=None):
        self.encoder = encoder
        self.rssm = rssm
        self.observation_model = observation_model
        self.reward_model = reward_model
        self.continue_model = continue_model


class PlayerDV2:
    """Stateful env-interaction wrapper with zeros init states
    (reference PlayerDV2:735)."""

    def __init__(
        self,
        world_model: WorldModel,
        actor: Actor,
        params: Dict[str, Any],
        actions_dim: Sequence[int],
        num_envs: int,
        stochastic_size: int,
        recurrent_state_size: int,
        discrete_size: int = 32,
        actor_type: Optional[str] = None,
        expl_amount: float = 0.0,
        device=None,
    ):
        self.wm = world_model
        self.actor_module = actor
        self.actions_dim = tuple(actions_dim)
        self.num_envs = num_envs
        self.stochastic_size = stochastic_size
        self.discrete_size = discrete_size
        self.recurrent_state_size = recurrent_state_size
        self.actor_type = actor_type
        self.expl_amount = expl_amount
        self.device = device
        self.params = params

        def _step(params, obs, prev_actions, recurrent_state, stochastic_state, key, mask, greedy, expl_amount):
            embedded_obs = self.wm.encoder.apply(params["world_model"]["encoder"], obs)
            recurrent_state = self.wm.rssm.apply(
                params["world_model"]["rssm"],
                jnp.concatenate([stochastic_state, prev_actions], -1),
                recurrent_state,
                method=RSSM.recurrent_step,
            )
            k1, k2, k3 = jax.random.split(key, 3)
            _, stoch = self.wm.rssm.apply(
                params["world_model"]["rssm"], recurrent_state, embedded_obs, k1,
                method=RSSM._representation,
            )
            stoch_flat = stoch.reshape(*stoch.shape[:-2], self.stochastic_size * self.discrete_size)
            actions, _ = self.actor_module.apply(
                params["actor"],
                jnp.concatenate([stoch_flat, recurrent_state], -1),
                greedy,
                k2,
                mask,
            )
            # greedy/expl_amount are static_argnums=(7, 8): static trace
            # specialization, not tracer concretization
            if expl_amount > 0.0 and not greedy:  # jaxlint: disable=retrace-branch
                actions = add_exploration_noise(
                    actions, k3, expl_amount, self.actions_dim, self.actor_module.is_continuous
                )
            return actions, jnp.concatenate(actions, -1), recurrent_state, stoch_flat

        self._step = jax.jit(_step, static_argnums=(7, 8))
        self.init_states()

    @property
    def params(self):
        return self._params

    @params.setter
    def params(self, value):
        self._params = transfer_tree(value, self.device)

    def init_states(self, reset_envs: Optional[Sequence[int]] = None) -> None:
        if reset_envs is None or len(reset_envs) == 0:
            self.actions = jnp.zeros((1, self.num_envs, int(np.sum(self.actions_dim))))
            self.recurrent_state = jnp.zeros((1, self.num_envs, self.recurrent_state_size))
            self.stochastic_state = jnp.zeros(
                (1, self.num_envs, self.stochastic_size * self.discrete_size)
            )
        else:
            idx = np.asarray(reset_envs)
            self.actions = self.actions.at[:, idx].set(0.0)
            self.recurrent_state = self.recurrent_state.at[:, idx].set(0.0)
            self.stochastic_state = self.stochastic_state.at[:, idx].set(0.0)

    def get_actions(
        self, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False, mask=None
    ) -> Sequence[jax.Array]:
        if self.device is not None:
            obs = jax.device_put(obs, self.device)
            key = jax.device_put(key, self.device)
        actions, flat, self.recurrent_state, self.stochastic_state = self._step(
            self._params,
            obs,
            self.actions,
            self.recurrent_state,
            self.stochastic_state,
            key,
            mask,
            greedy,
            float(self.expl_amount),
        )
        self.actions = flat
        return actions


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space,
    world_model_state: Optional[Any] = None,
    actor_state: Optional[Any] = None,
    critic_state: Optional[Any] = None,
    target_critic_state: Optional[Any] = None,
):
    """-> (world_model, actor, critic(V2MLP), params) with
    params = {world_model, actor, critic, target_critic} (reference
    build_agent:836)."""
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic

    recurrent_state_size = world_model_cfg.recurrent_model.recurrent_state_size
    stochastic_size = world_model_cfg.stochastic_size * world_model_cfg.discrete_size
    latent_state_size = stochastic_size + recurrent_state_size

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    use_continues = bool(world_model_cfg.use_continues)

    cnn_act = world_model_cfg.encoder.get("cnn_act", "elu")
    dense_act = world_model_cfg.encoder.get("dense_act", "elu")
    enc_ln = bool(world_model_cfg.encoder.layer_norm)
    # fabric.precision policy: trunks compute in bf16 under *-mixed/true,
    # heads/LN statistics/scan carries stay f32 (same split as DV3)
    compute_dtype = runtime.compute_dtype

    cnn_encoder = (
        CNNEncoder(
            keys=cnn_keys,
            channels_multiplier=world_model_cfg.encoder.cnn_channels_multiplier,
            layer_norm=enc_ln,
            act=cnn_act,
            dtype=compute_dtype,
        )
        if len(cnn_keys) > 0
        else None
    )
    mlp_encoder = (
        MLPEncoder(
            keys=mlp_keys,
            mlp_layers=world_model_cfg.encoder.mlp_layers,
            dense_units=world_model_cfg.encoder.dense_units,
            layer_norm=enc_ln,
            act=dense_act,
            dtype=compute_dtype,
        )
        if len(mlp_keys) > 0
        else None
    )
    encoder = MultiEncoderV2(cnn_encoder, mlp_encoder)

    if cnn_encoder is not None:
        size = int(obs_space[cnn_keys[0]].shape[0])
        if size != 64:
            # the fixed 4-stage VALID encoder/decoder pair round-trips 64x64
            # only (reference CNNEncoder:31 'assumes that the image is a 64x64')
            raise ValueError(
                f"DreamerV2's conv encoder/decoder require env.screen_size=64, got: {size}"
            )
        for _ in range(4):
            size = (size - 4) // 2 + 1
        cnn_encoder_output_dim = size * size * 8 * world_model_cfg.encoder.cnn_channels_multiplier
    else:
        cnn_encoder_output_dim = 0
    mlp_encoder_output_dim = world_model_cfg.encoder.dense_units if mlp_encoder is not None else 0
    embedded_obs_dim = cnn_encoder_output_dim + mlp_encoder_output_dim

    rssm = RSSM(
        actions_dim=tuple(actions_dim),
        embedded_obs_dim=embedded_obs_dim,
        recurrent_state_size=recurrent_state_size,
        dense_units=world_model_cfg.recurrent_model.dense_units,
        stochastic_size=world_model_cfg.stochastic_size,
        discrete_size=world_model_cfg.discrete_size,
        representation_hidden_size=world_model_cfg.representation_model.hidden_size,
        transition_hidden_size=world_model_cfg.transition_model.hidden_size,
        layer_norm=bool(world_model_cfg.representation_model.layer_norm),
        recurrent_layer_norm=bool(world_model_cfg.recurrent_model.layer_norm),
        act=dense_act,
        dtype=compute_dtype,
    )

    cnn_decoder = (
        CNNDecoder(
            keys=tuple(cfg.algo.cnn_keys.decoder),
            output_channels=[int(obs_space[k].shape[-1]) for k in cfg.algo.cnn_keys.decoder],
            channels_multiplier=world_model_cfg.observation_model.cnn_channels_multiplier,
            cnn_encoder_output_dim=cnn_encoder_output_dim,
            layer_norm=bool(world_model_cfg.observation_model.layer_norm),
            act=cnn_act,
            dtype=compute_dtype,
        )
        if len(cfg.algo.cnn_keys.decoder) > 0
        else None
    )
    mlp_decoder = (
        MLPDecoder(
            keys=tuple(cfg.algo.mlp_keys.decoder),
            output_dims=[int(obs_space[k].shape[0]) for k in cfg.algo.mlp_keys.decoder],
            mlp_layers=world_model_cfg.observation_model.mlp_layers,
            dense_units=world_model_cfg.observation_model.dense_units,
            layer_norm=bool(world_model_cfg.observation_model.layer_norm),
            act=dense_act,
            dtype=compute_dtype,
        )
        if len(cfg.algo.mlp_keys.decoder) > 0
        else None
    )
    observation_model = MultiDecoderV2(cnn_decoder, mlp_decoder)

    reward_model = V2MLP(
        units=world_model_cfg.reward_model.dense_units,
        layers=world_model_cfg.reward_model.mlp_layers,
        output_dim=1,
        act=dense_act,
        layer_norm=bool(world_model_cfg.reward_model.layer_norm),
        dtype=compute_dtype,
    )
    continue_model = (
        V2MLP(
            units=world_model_cfg.discount_model.dense_units,
            layers=world_model_cfg.discount_model.mlp_layers,
            output_dim=1,
            act=dense_act,
            layer_norm=bool(world_model_cfg.discount_model.layer_norm),
            dtype=compute_dtype,
        )
        if use_continues
        else None
    )
    world_model = WorldModel(encoder, rssm, observation_model, reward_model, continue_model)

    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=actor_cfg.init_std,
        min_std=actor_cfg.min_std,
        dense_units=actor_cfg.dense_units,
        mlp_layers=actor_cfg.mlp_layers,
        layer_norm=bool(actor_cfg.layer_norm),
        act=actor_cfg.get("dense_act", "elu"),
        dtype=compute_dtype,
    )
    critic = V2MLP(
        units=critic_cfg.dense_units,
        layers=critic_cfg.mlp_layers,
        output_dim=1,
        act=critic_cfg.get("dense_act", "elu"),
        layer_norm=bool(critic_cfg.layer_norm),
        dtype=compute_dtype,
    )

    # ------------------------------------------------------------- init
    B = 1
    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((B, *obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((B, *obs_space[k].shape), jnp.float32)
    dummy_embed = jnp.zeros((B, embedded_obs_dim), jnp.float32)
    dummy_latent = jnp.zeros((B, latent_state_size), jnp.float32)
    k = runtime.next_key

    if world_model_state is not None:
        wm_params = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    else:
        rssm_params = rssm.init(
            {"params": k()},
            jnp.zeros((B, world_model_cfg.stochastic_size, world_model_cfg.discrete_size)),
            jnp.zeros((B, recurrent_state_size)),
            jnp.zeros((B, int(np.sum(actions_dim)))),
            dummy_embed,
            jnp.zeros((B, 1)),
            k(),
            method=RSSM.dynamic,
        )
        wm_params = {
            "encoder": encoder.init(k(), dummy_obs),
            "rssm": rssm_params,
            "observation_model": observation_model.init(k(), dummy_latent),
            "reward_model": reward_model.init(k(), dummy_latent),
        }
        if continue_model is not None:
            wm_params["continue_model"] = continue_model.init(k(), dummy_latent)
    actor_params = (
        jax.tree_util.tree_map(jnp.asarray, actor_state)
        if actor_state is not None
        else actor.init({"params": k()}, dummy_latent, False, k())
    )
    critic_params = (
        jax.tree_util.tree_map(jnp.asarray, critic_state)
        if critic_state is not None
        else critic.init(k(), dummy_latent)
    )
    target_critic_params = (
        jax.tree_util.tree_map(jnp.asarray, target_critic_state)
        if target_critic_state is not None
        else jax.tree_util.tree_map(jnp.copy, critic_params)
    )
    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": target_critic_params,
    }
    return world_model, actor, critic, params
